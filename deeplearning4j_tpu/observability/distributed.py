"""Cross-process observability plane: identity, snapshot federation and
the fleet health scoreboard.

The observability core (trace.py / metrics.py / goodput.py) is strictly
single-process: one tracer ring, one registry, one ledger. Every open
ROADMAP direction that remains — the multi-replica serving fleet,
elastic multi-process resilience — needs to see *across* processes. The
TensorFlow systems papers treat cluster-wide tracing and health as a
precondition for running fleets at all; this module is that plane:

- **Identity** — every process carries a stable
  :class:`ProcessIdentity`: a ``run_id`` shared by all members of one
  logical run (env ``DL4J_TPU_RUN_ID``, generated otherwise), an
  ``instance`` name unique per process (env ``DL4J_TPU_INSTANCE``,
  default ``<host>-<pid>``) and an ``incarnation`` counter bumped on
  every supervisor relaunch (env ``DL4J_TPU_INCARNATION`` seeds it;
  ``chaos_train.py`` relaunches in-process, so the counter — not the
  pid — is what tells launch 3's artifacts from launch 1's). The
  identity is stamped onto Chrome-trace exports, RunReports, the
  ``dl4j_instance_info`` metric family and flight-recorder artifacts.
- **Trace propagation** — :func:`new_trace_id` mints the ids that ride
  the ``X-DL4J-Trace-Id`` header through ``/predict`` into the
  batcher's ``queue_wait`` / ``batch_assembly`` / ``device_compute``
  span attrs, so one client request correlates across process
  boundaries in a merged timeline.
- **Federation** — :func:`export_snapshot` renders a registry into a
  full-fidelity JSON wire form (family name/kind/help + samples with
  the *canonical exposition-escaped key*, so the JSON side and the
  Prometheus side can never encode a label value differently);
  :class:`MetricsFederation` ingests pushed (or scraped) snapshots
  from N child processes and re-exports ONE merged Prometheus view:
  every child sample labeled with its ``instance``, plus a fleet
  rollup sample per series (``instance="fleet"``: counters and
  histogram buckets sum, gauges are last-write-wins by push time).
- **Health scoreboard** — per-instance liveness/readiness derived from
  heartbeat age (``dl4j_heartbeat_timestamp_seconds``), the pushed
  ``healthy`` flag (the serving batcher's device-thread liveness),
  queue depth and fit-step progress between pushes. This is the seam a
  replica router reads to weight or evict workers.

The UIServer hosts the aggregator (``POST /api/metrics_push``,
``GET /api/fleet``, merged ``GET /metrics``); ``scripts/fleet_demo.py``
proves the three-worker merged exposition end to end. See
OBSERVABILITY.md "Fleet & post-mortems".
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability.metrics import (MetricFamily,
                                                      get_registry,
                                                      sample_key)

__all__ = [
    "ProcessIdentity", "get_identity", "set_identity", "reset_identity",
    "bump_incarnation", "new_trace_id", "stamp_run_marker", "TRACE_HEADER",
    "export_snapshot", "MetricsFederation", "SNAPSHOT_SCHEMA_VERSION",
    "rank_suffix", "push_snapshot", "HeartbeatPusher",
]

#: the header /predict accepts and echoes; serve_bench generates them
TRACE_HEADER = "X-DL4J-Trace-Id"

SNAPSHOT_SCHEMA_VERSION = 1


# ---------------------------------------------------------------------------
# identity
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ProcessIdentity:
    """Who this process is, fleet-wide. ``run_id`` groups the members of
    one logical run; ``instance`` is unique per process; ``incarnation``
    counts supervisor relaunches (same instance, new lifetime)."""

    run_id: str
    instance: str
    pid: int
    incarnation: int
    start_time: float

    @property
    def tag(self) -> str:
        """The fleet-unique name artifacts are keyed by: the instance,
        suffixed with the incarnation once the process has relaunched
        (``worker-0`` -> ``worker-0-i2``)."""
        if self.incarnation:
            return f"{self.instance}-i{self.incarnation}"
        return self.instance

    def labels(self) -> Dict[str, str]:
        """The label set stamped onto ``dl4j_instance_info``."""
        return {"run_id": self.run_id, "instance": self.instance,
                "incarnation": str(self.incarnation), "pid": str(self.pid)}

    def to_dict(self) -> dict:
        return {"run_id": self.run_id, "instance": self.instance,
                "pid": self.pid, "incarnation": self.incarnation,
                "start_time": self.start_time, "tag": self.tag}


_id_lock = threading.Lock()
_IDENTITY: Optional[ProcessIdentity] = None


def _build_identity() -> ProcessIdentity:
    run_id = os.environ.get("DL4J_TPU_RUN_ID") or uuid.uuid4().hex[:12]
    instance = os.environ.get("DL4J_TPU_INSTANCE") or (
        f"{socket.gethostname()}-{os.getpid()}")
    try:
        incarnation = int(os.environ.get("DL4J_TPU_INCARNATION", "0"))
    except ValueError:
        incarnation = 0
    return ProcessIdentity(run_id=run_id, instance=instance,
                           pid=os.getpid(), incarnation=incarnation,
                           start_time=time.time())


def get_identity() -> ProcessIdentity:
    """The process identity, built lazily from the ``DL4J_TPU_RUN_ID`` /
    ``DL4J_TPU_INSTANCE`` / ``DL4J_TPU_INCARNATION`` environment on
    first use (so a launcher exports them once and every subsystem —
    tracer export, RunReports, metrics, flight recorder — agrees)."""
    global _IDENTITY
    with _id_lock:
        if _IDENTITY is None:
            _IDENTITY = _build_identity()
        return _IDENTITY


def set_identity(**fields) -> ProcessIdentity:
    """Replace identity fields in place (``set_identity(instance="w0")``).
    Returns the new identity."""
    global _IDENTITY
    with _id_lock:
        base = _IDENTITY if _IDENTITY is not None else _build_identity()
        d = base.to_dict()
        d.pop("tag")
        d.update(fields)
        _IDENTITY = ProcessIdentity(**d)
        return _IDENTITY


def reset_identity() -> None:
    """Forget the cached identity (tests: re-read the environment)."""
    global _IDENTITY
    with _id_lock:
        _IDENTITY = None


def bump_incarnation() -> ProcessIdentity:
    """Advance the incarnation counter — called per supervisor relaunch
    so artifacts (flight recordings, federation tags) from different
    lifetimes of the same instance never collide, even when the
    relaunch happens in-process with an unchanged pid."""
    ident = get_identity()
    return set_identity(incarnation=ident.incarnation + 1,
                        start_time=time.time())


def rank_suffix() -> str:
    """Per-rank artifact disambiguator for multi-process runs writing
    into one shared directory: ``""`` on rank 0 (and outside any
    multi-process runtime — legacy names stay stable), ``".r<k>"`` on
    rank k>0. Inserted before the extension of ``run_report.json`` and
    ``flight_<tag>.json`` so a 2-process run stops silently clobbering
    its own post-mortems."""
    try:
        import jax
        if jax.process_count() > 1:
            idx = int(jax.process_index())
            if idx:
                return f".r{idx}"
    except Exception:
        pass
    return ""


def new_trace_id() -> str:
    """Mint a trace id for the ``X-DL4J-Trace-Id`` header (16 hex chars
    — W3C-traceparent-sized, stdlib-only)."""
    return uuid.uuid4().hex[:16]


def stamp_run_marker(kind: str) -> None:
    """Record a zero-duration ``run_start`` span carrying the process
    identity — the fit loops and servers call this at run start so any
    exported timeline says which fleet member and incarnation it came
    from even when sliced out of the full export."""
    try:
        from deeplearning4j_tpu.observability.trace import get_tracer
        ident = get_identity()
        t = time.perf_counter()
        get_tracer().record("run_start", t, t, {
            "kind": str(kind), "run_id": ident.run_id,
            "instance": ident.instance,
            "incarnation": ident.incarnation})
    except Exception:
        pass


# ---------------------------------------------------------------------------
# snapshot wire format
# ---------------------------------------------------------------------------

def export_snapshot(registry=None, health: Optional[dict] = None) -> dict:
    """Render a registry into the federation wire form: full fidelity
    (family kind/help, every sample's labels + suffix) plus the
    canonical exposition-escaped ``key`` per sample, so the aggregator
    merges and re-renders without re-deriving escaping. ``health`` is
    the pusher's self-reported readiness payload (e.g. the serving
    batcher's ``healthy`` flag)."""
    reg = registry if registry is not None else get_registry()
    fams = []
    for fam in reg.collect():
        fams.append({
            "name": fam.name,
            "kind": fam.kind,
            "help": fam.help,
            "samples": [
                {"key": sample_key(fam.name, s.labels, s.suffix),
                 "labels": dict(s.labels), "suffix": s.suffix,
                 "value": s.value}
                for s in fam.samples],
        })
    return {
        "schema": SNAPSHOT_SCHEMA_VERSION,
        "identity": get_identity().to_dict(),
        "time": time.time(),
        "families": fams,
        "health": dict(health or {}),
    }


def push_snapshot(url: str, registry=None, health: Optional[dict] = None,
                  timeout: float = 5.0, *, attempts: int = 1,
                  backoff_initial_s: float = 0.2,
                  backoff_factor: float = 2.0, backoff_max_s: float = 5.0,
                  jitter: float = 0.5, sleep_fn=time.sleep) -> dict:
    """POST :func:`export_snapshot` to an aggregator's
    ``/api/metrics_push`` endpoint; returns the aggregator's reply.

    ``attempts > 1`` opts into retry with exponential backoff + jitter:
    a restarting aggregator (connection refused, reset, 5xx) costs a
    worker one delayed heartbeat instead of dropping it permanently.
    The snapshot is re-exported per attempt so the delivered heartbeat
    timestamp is fresh, not the first attempt's stale one. Jitter
    de-synchronizes a fleet whose workers all lost the same aggregator
    at the same moment (the thundering-herd reconnect)."""
    import random
    import urllib.request
    attempts = max(1, int(attempts))
    delay = backoff_initial_s
    for attempt in range(attempts):
        try:
            body = json.dumps(export_snapshot(registry, health)).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except OSError:
            # URLError (incl. HTTPError) subclasses OSError: covers
            # refused/reset connections, DNS blips, and 5xx restarts
            if attempt + 1 >= attempts:
                raise
            sleep_fn(min(backoff_max_s,
                         delay * (1.0 + jitter * random.random())))
            delay = min(delay * backoff_factor, backoff_max_s)


class HeartbeatPusher:
    """Background push loop: POST a fresh :func:`export_snapshot` to an
    aggregator every ``interval_s`` until stopped.

    This is the worker-fleet side of the cross-host serving federation
    (serving/router.py): each host's ``ModelServer`` runs one of these
    against the router's ``/api/metrics_push``, so the router's routing
    and liveness decisions ride live queue-depth/heartbeat gauges. The
    push retry is ON here (``attempts=3`` by default, jittered
    exponential backoff — the :func:`push_snapshot` opt-in): a router
    restart or transient refusal costs a host one delayed heartbeat,
    not its scoreboard row. The backoff schedule is pinned by
    ``tests/test_crosshost_serving.py``.

    ``health_fn`` (no-arg -> dict) is re-evaluated per push so the
    delivered readiness payload is current, not construction-time.
    """

    def __init__(self, url: str, interval_s: float = 2.0, *,
                 health_fn=None, registry=None, timeout: float = 5.0,
                 attempts: int = 3, backoff_initial_s: float = 0.2,
                 backoff_factor: float = 2.0, backoff_max_s: float = 2.0,
                 jitter: float = 0.5):
        self.url = url
        self.interval_s = float(interval_s)
        self.health_fn = health_fn
        self.registry = registry
        self.timeout = float(timeout)
        self.attempts = int(attempts)
        self.backoff_initial_s = float(backoff_initial_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.pushes_ok = 0
        self.pushes_failed = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def push_once(self) -> bool:
        """One push (with the retry policy applied); returns success.
        Exhausted retries are counted, never raised — a heartbeat loop
        must outlive its aggregator's bad day."""
        try:
            health = self.health_fn() if self.health_fn else None
            push_snapshot(self.url, self.registry, health,
                          timeout=self.timeout, attempts=self.attempts,
                          backoff_initial_s=self.backoff_initial_s,
                          backoff_factor=self.backoff_factor,
                          backoff_max_s=self.backoff_max_s,
                          jitter=self.jitter)
        except Exception as e:
            self.pushes_failed += 1
            self.last_error = f"{type(e).__name__}: {e}"
            return False
        self.pushes_ok += 1
        return True

    def start(self) -> "HeartbeatPusher":
        if self._thread is not None:
            return self
        # one synchronous push before the loop: the aggregator knows
        # this instance the moment start() returns, not one interval in
        self.push_once()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.push_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dl4j-heartbeat-push")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout + 1.0)


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------

class MetricsFederation:
    """Aggregates the latest snapshot per instance and re-exports one
    merged Prometheus view.

    Ingest is last-write-wins per instance tag (a push wholly replaces
    that instance's previous snapshot, under one lock — concurrent
    pushes from N worker threads/processes are safe and the merge
    always reflects a consistent set of "latest" snapshots). Merge
    semantics per family across instances:

    - every sample re-emitted with an added ``instance=<tag>`` label
    - one fleet rollup sample per distinct (labels, suffix) series with
      ``instance="fleet"``: counters and histogram ``_bucket``/``_sum``
      /``_count`` samples SUM; gauges take the value from the most
      recently pushed snapshot that carries the series (last-write)
    - kind conflicts keep the first-seen kind and skip the conflicting
      family from later snapshots (a broken pusher must not corrupt the
      merged exposition)
    """

    FLEET = "fleet"

    def __init__(self, stale_after_s: float = 15.0,
                 evict_after_factor: Optional[float] = 4.0):
        self.stale_after_s = float(stale_after_s)
        #: auto-eviction threshold as a MULTIPLE of ``stale_after_s``:
        #: an instance whose heartbeat age exceeds
        #: ``evict_after_factor * stale_after_s`` is dropped from the
        #: scoreboard entirely (a shrunken fleet must not list its dead
        #: processes forever — stale marks the wobble, eviction the
        #: departure). None disables; ``drop()`` stays for explicit
        #: eviction either way.
        self.evict_after_factor = (None if evict_after_factor is None
                                   else float(evict_after_factor))
        if self.evict_after_factor is not None \
                and self.evict_after_factor < 1.0:
            raise ValueError("evict_after_factor must be >= 1 (eviction "
                             "below the stale threshold would hide "
                             "instances that are merely slow)")
        #: dead instances reaped by the heartbeat-age auto-eviction
        self.auto_evicted_total = 0
        self._lock = threading.Lock()
        #: tag -> {"snapshot", "received_at", "seq", "pushes",
        #:         "steps", "steps_changed_at"}
        self._instances: Dict[str, dict] = {}
        self._seq = 0

    # ---------------------------------------------------------------- ingest
    def ingest(self, snapshot: dict) -> str:
        """Accept one pushed/scraped snapshot; returns the instance tag
        it was filed under. Raises ValueError on a malformed payload."""
        if not isinstance(snapshot, dict) or "families" not in snapshot:
            raise ValueError("not a metrics snapshot (no 'families')")
        ident = snapshot.get("identity") or {}
        tag = ident.get("tag") or ident.get("instance")
        if not tag:
            raise ValueError("snapshot carries no identity.tag/instance")
        steps = _family_value(snapshot, "dl4j_fit_steps_total")
        now = time.time()
        with self._lock:
            self._seq += 1
            prev = self._instances.get(tag)
            ent = {
                "snapshot": snapshot,
                "received_at": now,
                "seq": self._seq,
                "pushes": (prev["pushes"] + 1) if prev else 1,
                "steps": steps,
                "steps_changed_at": now,
            }
            if prev is not None and steps is not None \
                    and steps == prev.get("steps"):
                ent["steps_changed_at"] = prev["steps_changed_at"]
            self._instances[tag] = ent
        return str(tag)

    def scrape(self, url: str, timeout: float = 5.0) -> str:
        """Pull one child's ``/metrics?format=snapshot`` and ingest it
        (the pull-mode twin of the push endpoint)."""
        import urllib.request
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return self.ingest(json.loads(resp.read().decode()))

    def drop(self, tag: str) -> None:
        with self._lock:
            self._instances.pop(tag, None)

    def instance_tags(self) -> List[str]:
        with self._lock:
            return sorted(self._instances)

    def instance_count(self) -> int:
        with self._lock:
            return len(self._instances)

    # ----------------------------------------------------------------- merge
    def merged_families(self, local: Optional[Tuple[str, list]] = None
                        ) -> List[MetricFamily]:
        """The merged view. ``local`` = ``(tag, families)`` folds the
        aggregator's own registry in as one more instance (the UIServer
        passes its own ``registry.collect()`` so the merged exposition
        covers the whole fleet including the host process)."""
        with self._lock:
            instances = [(tag, ent["seq"], ent["snapshot"])
                         for tag, ent in sorted(self._instances.items())]
        contributions: List[Tuple[str, int, dict]] = []
        if local is not None:
            tag, fams = local
            snap = {"families": [
                {"name": f.name, "kind": f.kind, "help": f.help,
                 "samples": [{"labels": dict(s.labels), "suffix": s.suffix,
                              "value": s.value} for s in f.samples]}
                for f in fams]}
            # the local process is always the freshest writer
            contributions.append((tag, 1 + max(
                [seq for _, seq, _ in instances], default=0), snap))
        contributions.extend(instances)

        merged: Dict[str, MetricFamily] = {}
        kinds: Dict[str, str] = {}
        # series -> rollup accumulator:
        # (family, suffix, labelkey) -> [labels, value, best_seq]
        rollup: Dict[Tuple[str, str, str], list] = {}
        order: List[str] = []
        for tag, seq, snap in contributions:
            for fdict in snap.get("families", ()):
                name, kind = fdict.get("name"), fdict.get("kind")
                if not name or kind not in ("counter", "gauge", "histogram"):
                    continue
                if name not in kinds:
                    kinds[name] = kind
                    merged[name] = MetricFamily(
                        name, kind, fdict.get("help") or "")
                    order.append(name)
                elif kinds[name] != kind:
                    continue  # conflicting kind: first writer wins
                fam = merged[name]
                for s in fdict.get("samples", ()):
                    labels = {str(k): str(v)
                              for k, v in (s.get("labels") or {}).items()}
                    labels.pop("instance", None)
                    suffix = s.get("suffix") or ""
                    try:
                        value = float(s.get("value"))
                    except (TypeError, ValueError):
                        continue
                    fam.add(value, {**labels, "instance": tag}, suffix)
                    rkey = (name, suffix,
                            sample_key(name, labels, suffix))
                    ent = rollup.get(rkey)
                    summed = (kinds[name] == "counter"
                              or kinds[name] == "histogram")
                    if ent is None:
                        rollup[rkey] = [labels, value, seq]
                    elif summed:
                        ent[1] += value
                    elif seq >= ent[2]:      # gauge: last write wins
                        ent[1], ent[2] = value, seq
        for (name, suffix, _), (labels, value, _) in rollup.items():
            merged[name].add(value, {**labels, "instance": self.FLEET},
                             suffix)
        return [merged[name] for name in order]

    def render_prometheus(self, local: Optional[Tuple[str, list]] = None
                          ) -> str:
        fams = self.merged_families(local)
        if not fams:
            return "\n"
        return "\n".join(f.render() for f in fams) + "\n"

    # ---------------------------------------------------------------- health
    def health(self) -> List[dict]:
        """The scoreboard: one dict per instance with liveness (heartbeat
        + push age vs ``stale_after_s``), readiness (the pushed
        ``healthy`` flags, e.g. the serving batcher's device-thread
        liveness), queue depth, step count and progress age.

        Instances whose heartbeat age exceeds
        ``evict_after_factor * stale_after_s`` are auto-evicted here —
        removed from the federation, not just flagged stale — so a
        fleet that shrank stops advertising its dead processes."""
        now = time.time()
        with self._lock:
            items = sorted(self._instances.items())
        evict = []
        out = []
        for tag, ent in items:
            snap = ent["snapshot"]
            push_age = max(0.0, now - ent["received_at"])
            hb = _family_value(snap, "dl4j_heartbeat_timestamp_seconds")
            snap_time = snap.get("time")
            # heartbeat age = staleness at push time (child clock) plus
            # how long ago the push landed (aggregator clock) — robust
            # to small cross-host clock skew
            hb_age = push_age
            if hb is not None and snap_time is not None:
                hb_age += max(0.0, float(snap_time) - float(hb))
            if self.evict_after_factor is not None and \
                    hb_age > self.evict_after_factor * self.stale_after_s:
                evict.append((tag, ent["seq"]))
                continue
            health_payload = snap.get("health") or {}
            flags = [bool(v) for k, v in health_payload.items()
                     if k.endswith("healthy") or k == "ready"]
            live = hb_age <= self.stale_after_s
            steps = ent.get("steps")
            row = {
                "instance": tag,
                "identity": snap.get("identity") or {},
                "live": live,
                "ready": live and all(flags) if flags else live,
                "heartbeat_age_s": round(hb_age, 3),
                "push_age_s": round(push_age, 3),
                "pushes": ent["pushes"],
                "queue_depth": _family_value(
                    snap, "dl4j_serving_queue_depth", agg=sum),
                # the cross-host routing gauges (serving/router.py):
                # backlog-derived Retry-After and observed drain rate,
                # straight off the host's pushed serving families
                "retry_after_s": _family_value(
                    snap, "dl4j_serving_retry_after_seconds", agg=min),
                "drain_rate_rows_per_s": _family_value(
                    snap, "dl4j_serving_drain_rate_rows_per_s", agg=sum),
                "steps_total": steps,
                "last_progress_age_s": (
                    round(max(0.0, now - ent["steps_changed_at"]), 3)
                    if steps is not None else None),
                "health": health_payload,
                # per-replica serving rows (status + queue depth), pushed
                # by a fleet-mode ModelServer — the scoreboard shows the
                # replica hole behind a "degraded" instance
                "replicas": health_payload.get("replicas"),
            }
            out.append(row)
        if evict:
            with self._lock:
                for tag, seq in evict:
                    ent = self._instances.get(tag)
                    # seq guard: a push that landed while we were
                    # scoring means the instance is alive after all
                    if ent is not None and ent["seq"] == seq:
                        self._instances.pop(tag)
                        self.auto_evicted_total += 1
        return out

    def fleet_payload(self) -> dict:
        """The ``/api/fleet`` JSON: scoreboard + aggregate counts."""
        rows = self.health()
        return {
            "time": time.time(),
            "instances": rows,
            "live": sum(1 for r in rows if r["live"]),
            "ready": sum(1 for r in rows if r["ready"]),
            "stale_after_s": self.stale_after_s,
            "evict_after_factor": self.evict_after_factor,
            "auto_evicted_total": self.auto_evicted_total,
        }


def _family_value(snapshot: dict, name: str, agg=None) -> Optional[float]:
    """Pull one family's scalar out of a wire snapshot (sum of its plain
    samples by default — per-label children of a counter/gauge)."""
    for fdict in snapshot.get("families", ()):
        if fdict.get("name") != name:
            continue
        vals = []
        for s in fdict.get("samples", ()):
            if s.get("suffix"):
                continue
            try:
                vals.append(float(s.get("value")))
            except (TypeError, ValueError):
                continue
        if not vals:
            return None
        return float((agg or sum)(vals)) if len(vals) > 1 else vals[0]
    return None
