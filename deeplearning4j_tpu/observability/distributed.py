"""Cross-process observability plane: identity, snapshot federation and
the fleet health scoreboard.

The observability core (trace.py / metrics.py / goodput.py) is strictly
single-process: one tracer ring, one registry, one ledger. Every open
ROADMAP direction that remains — the multi-replica serving fleet,
elastic multi-process resilience — needs to see *across* processes. The
TensorFlow systems papers treat cluster-wide tracing and health as a
precondition for running fleets at all; this module is that plane:

- **Identity** — every process carries a stable
  :class:`ProcessIdentity`: a ``run_id`` shared by all members of one
  logical run (env ``DL4J_TPU_RUN_ID``, generated otherwise), an
  ``instance`` name unique per process (env ``DL4J_TPU_INSTANCE``,
  default ``<host>-<pid>``) and an ``incarnation`` counter bumped on
  every supervisor relaunch (env ``DL4J_TPU_INCARNATION`` seeds it;
  ``chaos_train.py`` relaunches in-process, so the counter — not the
  pid — is what tells launch 3's artifacts from launch 1's). The
  identity is stamped onto Chrome-trace exports, RunReports, the
  ``dl4j_instance_info`` metric family and flight-recorder artifacts.
- **Trace propagation** — :func:`new_trace_id` mints the ids that ride
  the ``X-DL4J-Trace-Id`` header through ``/predict`` into the
  batcher's ``queue_wait`` / ``batch_assembly`` / ``device_compute``
  span attrs, so one client request correlates across process
  boundaries in a merged timeline.
- **Federation** — :func:`export_snapshot` renders a registry into a
  full-fidelity JSON wire form (family name/kind/help + samples with
  the *canonical exposition-escaped key*, so the JSON side and the
  Prometheus side can never encode a label value differently);
  :class:`MetricsFederation` ingests pushed (or scraped) snapshots
  from N child processes and re-exports ONE merged Prometheus view:
  every child sample labeled with its ``instance``, plus a fleet
  rollup sample per series (``instance="fleet"``: counters and
  histogram buckets sum, gauges are last-write-wins by push time).
- **Health scoreboard** — per-instance liveness/readiness derived from
  heartbeat age (``dl4j_heartbeat_timestamp_seconds``), the pushed
  ``healthy`` flag (the serving batcher's device-thread liveness),
  queue depth and fit-step progress between pushes. This is the seam a
  replica router reads to weight or evict workers.

The UIServer hosts the aggregator (``POST /api/metrics_push``,
``GET /api/fleet``, merged ``GET /metrics``); ``scripts/fleet_demo.py``
proves the three-worker merged exposition end to end. See
OBSERVABILITY.md "Fleet & post-mortems".
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability.metrics import (MetricFamily,
                                                      get_registry,
                                                      sample_key)

__all__ = [
    "ProcessIdentity", "get_identity", "set_identity", "reset_identity",
    "bump_incarnation", "new_trace_id", "stamp_run_marker", "TRACE_HEADER",
    "export_snapshot", "MetricsFederation", "SNAPSHOT_SCHEMA_VERSION",
    "rank_suffix", "push_snapshot", "HeartbeatPusher",
    "SpanPushBuffer", "TraceStore", "TRACE_PUSH_SCHEMA_VERSION",
]

#: the header /predict and /decode accept and echo; serve_bench
#: generates them
TRACE_HEADER = "X-DL4J-Trace-Id"

SNAPSHOT_SCHEMA_VERSION = 1

#: wire schema of the span-batch payload riding the metrics snapshot
#: under its "spans" key (see SpanPushBuffer.payload / TraceStore)
TRACE_PUSH_SCHEMA_VERSION = 1


# ---------------------------------------------------------------------------
# identity
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ProcessIdentity:
    """Who this process is, fleet-wide. ``run_id`` groups the members of
    one logical run; ``instance`` is unique per process; ``incarnation``
    counts supervisor relaunches (same instance, new lifetime)."""

    run_id: str
    instance: str
    pid: int
    incarnation: int
    start_time: float

    @property
    def tag(self) -> str:
        """The fleet-unique name artifacts are keyed by: the instance,
        suffixed with the incarnation once the process has relaunched
        (``worker-0`` -> ``worker-0-i2``)."""
        if self.incarnation:
            return f"{self.instance}-i{self.incarnation}"
        return self.instance

    def labels(self) -> Dict[str, str]:
        """The label set stamped onto ``dl4j_instance_info``."""
        return {"run_id": self.run_id, "instance": self.instance,
                "incarnation": str(self.incarnation), "pid": str(self.pid)}

    def to_dict(self) -> dict:
        return {"run_id": self.run_id, "instance": self.instance,
                "pid": self.pid, "incarnation": self.incarnation,
                "start_time": self.start_time, "tag": self.tag}


_id_lock = threading.Lock()
_IDENTITY: Optional[ProcessIdentity] = None


def _build_identity() -> ProcessIdentity:
    run_id = os.environ.get("DL4J_TPU_RUN_ID") or uuid.uuid4().hex[:12]
    instance = os.environ.get("DL4J_TPU_INSTANCE") or (
        f"{socket.gethostname()}-{os.getpid()}")
    try:
        incarnation = int(os.environ.get("DL4J_TPU_INCARNATION", "0"))
    except ValueError:
        incarnation = 0
    return ProcessIdentity(run_id=run_id, instance=instance,
                           pid=os.getpid(), incarnation=incarnation,
                           start_time=time.time())


def get_identity() -> ProcessIdentity:
    """The process identity, built lazily from the ``DL4J_TPU_RUN_ID`` /
    ``DL4J_TPU_INSTANCE`` / ``DL4J_TPU_INCARNATION`` environment on
    first use (so a launcher exports them once and every subsystem —
    tracer export, RunReports, metrics, flight recorder — agrees)."""
    global _IDENTITY
    with _id_lock:
        if _IDENTITY is None:
            _IDENTITY = _build_identity()
        return _IDENTITY


def set_identity(**fields) -> ProcessIdentity:
    """Replace identity fields in place (``set_identity(instance="w0")``).
    Returns the new identity."""
    global _IDENTITY
    with _id_lock:
        base = _IDENTITY if _IDENTITY is not None else _build_identity()
        d = base.to_dict()
        d.pop("tag")
        d.update(fields)
        _IDENTITY = ProcessIdentity(**d)
        return _IDENTITY


def reset_identity() -> None:
    """Forget the cached identity (tests: re-read the environment)."""
    global _IDENTITY
    with _id_lock:
        _IDENTITY = None


def bump_incarnation() -> ProcessIdentity:
    """Advance the incarnation counter — called per supervisor relaunch
    so artifacts (flight recordings, federation tags) from different
    lifetimes of the same instance never collide, even when the
    relaunch happens in-process with an unchanged pid."""
    ident = get_identity()
    return set_identity(incarnation=ident.incarnation + 1,
                        start_time=time.time())


def rank_suffix() -> str:
    """Per-rank artifact disambiguator for multi-process runs writing
    into one shared directory: ``""`` on rank 0 (and outside any
    multi-process runtime — legacy names stay stable), ``".r<k>"`` on
    rank k>0. Inserted before the extension of ``run_report.json`` and
    ``flight_<tag>.json`` so a 2-process run stops silently clobbering
    its own post-mortems."""
    try:
        import jax
        if jax.process_count() > 1:
            idx = int(jax.process_index())
            if idx:
                return f".r{idx}"
    except Exception:
        pass
    return ""


def new_trace_id() -> str:
    """Mint a trace id for the ``X-DL4J-Trace-Id`` header (16 hex chars
    — W3C-traceparent-sized, stdlib-only)."""
    return uuid.uuid4().hex[:16]


def stamp_run_marker(kind: str) -> None:
    """Record a zero-duration ``run_start`` span carrying the process
    identity — the fit loops and servers call this at run start so any
    exported timeline says which fleet member and incarnation it came
    from even when sliced out of the full export."""
    try:
        from deeplearning4j_tpu.observability.trace import get_tracer
        ident = get_identity()
        t = time.perf_counter()
        get_tracer().record("run_start", t, t, {
            "kind": str(kind), "run_id": ident.run_id,
            "instance": ident.instance,
            "incarnation": ident.incarnation})
    except Exception:
        pass


# ---------------------------------------------------------------------------
# snapshot wire format
# ---------------------------------------------------------------------------

def export_snapshot(registry=None, health: Optional[dict] = None,
                    spans: Optional[dict] = None) -> dict:
    """Render a registry into the federation wire form: full fidelity
    (family kind/help, every sample's labels + suffix) plus the
    canonical exposition-escaped ``key`` per sample, so the aggregator
    merges and re-renders without re-deriving escaping. ``health`` is
    the pusher's self-reported readiness payload (e.g. the serving
    batcher's ``healthy`` flag). ``spans`` is a span-batch payload
    (:meth:`SpanPushBuffer.payload`) riding the same push — aggregators
    that predate it ignore the extra key (``MetricsFederation.ingest``
    validates only ``families``)."""
    reg = registry if registry is not None else get_registry()
    fams = []
    for fam in reg.collect():
        fams.append({
            "name": fam.name,
            "kind": fam.kind,
            "help": fam.help,
            "samples": [
                {"key": sample_key(fam.name, s.labels, s.suffix),
                 "labels": dict(s.labels), "suffix": s.suffix,
                 "value": s.value}
                for s in fam.samples],
        })
    out = {
        "schema": SNAPSHOT_SCHEMA_VERSION,
        "identity": get_identity().to_dict(),
        "time": time.time(),
        "families": fams,
        "health": dict(health or {}),
    }
    if spans:
        out["spans"] = spans
    return out


def push_snapshot(url: str, registry=None, health: Optional[dict] = None,
                  timeout: float = 5.0, *, attempts: int = 1,
                  backoff_initial_s: float = 0.2,
                  backoff_factor: float = 2.0, backoff_max_s: float = 5.0,
                  jitter: float = 0.5, sleep_fn=time.sleep,
                  spans_fn=None) -> dict:
    """POST :func:`export_snapshot` to an aggregator's
    ``/api/metrics_push`` endpoint; returns the aggregator's reply.

    ``attempts > 1`` opts into retry with exponential backoff + jitter:
    a restarting aggregator (connection refused, reset, 5xx) costs a
    worker one delayed heartbeat instead of dropping it permanently.
    The snapshot is re-exported per attempt so the delivered heartbeat
    timestamp is fresh, not the first attempt's stale one. Jitter
    de-synchronizes a fleet whose workers all lost the same aggregator
    at the same moment (the thundering-herd reconnect).

    ``spans_fn`` (no-arg -> span payload dict or None) is evaluated
    ONCE, before the first attempt — a drain-style source
    (:meth:`SpanPushBuffer.payload`) must not lose its batch to a retry,
    so the same batch rides every attempt."""
    import random
    import urllib.request
    attempts = max(1, int(attempts))
    delay = backoff_initial_s
    spans = spans_fn() if spans_fn is not None else None
    for attempt in range(attempts):
        try:
            body = json.dumps(
                export_snapshot(registry, health, spans)).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except OSError:
            # URLError (incl. HTTPError) subclasses OSError: covers
            # refused/reset connections, DNS blips, and 5xx restarts
            if attempt + 1 >= attempts:
                raise
            sleep_fn(min(backoff_max_s,
                         delay * (1.0 + jitter * random.random())))
            delay = min(delay * backoff_factor, backoff_max_s)


class HeartbeatPusher:
    """Background push loop: POST a fresh :func:`export_snapshot` to an
    aggregator every ``interval_s`` until stopped.

    This is the worker-fleet side of the cross-host serving federation
    (serving/router.py): each host's ``ModelServer`` runs one of these
    against the router's ``/api/metrics_push``, so the router's routing
    and liveness decisions ride live queue-depth/heartbeat gauges. The
    push retry is ON here (``attempts=3`` by default, jittered
    exponential backoff — the :func:`push_snapshot` opt-in): a router
    restart or transient refusal costs a host one delayed heartbeat,
    not its scoreboard row. The backoff schedule is pinned by
    ``tests/test_crosshost_serving.py``.

    ``health_fn`` (no-arg -> dict) is re-evaluated per push so the
    delivered readiness payload is current, not construction-time.
    ``spans_fn`` (no-arg -> span payload dict or None, e.g.
    :meth:`SpanPushBuffer.payload`) rides each push under the
    snapshot's ``spans`` key — the trace-stitching wire.
    """

    def __init__(self, url: str, interval_s: float = 2.0, *,
                 health_fn=None, registry=None, timeout: float = 5.0,
                 attempts: int = 3, backoff_initial_s: float = 0.2,
                 backoff_factor: float = 2.0, backoff_max_s: float = 2.0,
                 jitter: float = 0.5, spans_fn=None):
        self.url = url
        self.interval_s = float(interval_s)
        self.health_fn = health_fn
        self.spans_fn = spans_fn
        self.registry = registry
        self.timeout = float(timeout)
        self.attempts = int(attempts)
        self.backoff_initial_s = float(backoff_initial_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.pushes_ok = 0
        self.pushes_failed = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def push_once(self) -> bool:
        """One push (with the retry policy applied); returns success.
        Exhausted retries are counted, never raised — a heartbeat loop
        must outlive its aggregator's bad day."""
        try:
            health = self.health_fn() if self.health_fn else None
            push_snapshot(self.url, self.registry, health,
                          timeout=self.timeout, attempts=self.attempts,
                          backoff_initial_s=self.backoff_initial_s,
                          backoff_factor=self.backoff_factor,
                          backoff_max_s=self.backoff_max_s,
                          jitter=self.jitter, spans_fn=self.spans_fn)
        except Exception as e:
            self.pushes_failed += 1
            self.last_error = f"{type(e).__name__}: {e}"
            return False
        self.pushes_ok += 1
        return True

    def start(self) -> "HeartbeatPusher":
        if self._thread is not None:
            return self
        # one synchronous push before the loop: the aggregator knows
        # this instance the moment start() returns, not one interval in
        self.push_once()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.push_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dl4j-heartbeat-push")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout + 1.0)


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------

class MetricsFederation:
    """Aggregates the latest snapshot per instance and re-exports one
    merged Prometheus view.

    Ingest is last-write-wins per instance tag (a push wholly replaces
    that instance's previous snapshot, under one lock — concurrent
    pushes from N worker threads/processes are safe and the merge
    always reflects a consistent set of "latest" snapshots). Merge
    semantics per family across instances:

    - every sample re-emitted with an added ``instance=<tag>`` label
    - one fleet rollup sample per distinct (labels, suffix) series with
      ``instance="fleet"``: counters and histogram ``_bucket``/``_sum``
      /``_count`` samples SUM; gauges take the value from the most
      recently pushed snapshot that carries the series (last-write)
    - kind conflicts keep the first-seen kind and skip the conflicting
      family from later snapshots (a broken pusher must not corrupt the
      merged exposition)
    """

    FLEET = "fleet"

    def __init__(self, stale_after_s: float = 15.0,
                 evict_after_factor: Optional[float] = 4.0):
        self.stale_after_s = float(stale_after_s)
        #: auto-eviction threshold as a MULTIPLE of ``stale_after_s``:
        #: an instance whose heartbeat age exceeds
        #: ``evict_after_factor * stale_after_s`` is dropped from the
        #: scoreboard entirely (a shrunken fleet must not list its dead
        #: processes forever — stale marks the wobble, eviction the
        #: departure). None disables; ``drop()`` stays for explicit
        #: eviction either way.
        self.evict_after_factor = (None if evict_after_factor is None
                                   else float(evict_after_factor))
        if self.evict_after_factor is not None \
                and self.evict_after_factor < 1.0:
            raise ValueError("evict_after_factor must be >= 1 (eviction "
                             "below the stale threshold would hide "
                             "instances that are merely slow)")
        #: dead instances reaped by the heartbeat-age auto-eviction
        self.auto_evicted_total = 0
        self._lock = threading.Lock()
        #: tag -> {"snapshot", "received_at", "seq", "pushes",
        #:         "steps", "steps_changed_at"}
        self._instances: Dict[str, dict] = {}
        self._seq = 0

    # ---------------------------------------------------------------- ingest
    def ingest(self, snapshot: dict) -> str:
        """Accept one pushed/scraped snapshot; returns the instance tag
        it was filed under. Raises ValueError on a malformed payload."""
        if not isinstance(snapshot, dict) or "families" not in snapshot:
            raise ValueError("not a metrics snapshot (no 'families')")
        ident = snapshot.get("identity") or {}
        tag = ident.get("tag") or ident.get("instance")
        if not tag:
            raise ValueError("snapshot carries no identity.tag/instance")
        steps = _family_value(snapshot, "dl4j_fit_steps_total")
        now = time.time()
        with self._lock:
            self._seq += 1
            prev = self._instances.get(tag)
            ent = {
                "snapshot": snapshot,
                "received_at": now,
                "seq": self._seq,
                "pushes": (prev["pushes"] + 1) if prev else 1,
                "steps": steps,
                "steps_changed_at": now,
            }
            if prev is not None and steps is not None \
                    and steps == prev.get("steps"):
                ent["steps_changed_at"] = prev["steps_changed_at"]
            self._instances[tag] = ent
        return str(tag)

    def scrape(self, url: str, timeout: float = 5.0) -> str:
        """Pull one child's ``/metrics?format=snapshot`` and ingest it
        (the pull-mode twin of the push endpoint)."""
        import urllib.request
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return self.ingest(json.loads(resp.read().decode()))

    def drop(self, tag: str) -> None:
        with self._lock:
            self._instances.pop(tag, None)

    def instance_tags(self) -> List[str]:
        with self._lock:
            return sorted(self._instances)

    def instance_count(self) -> int:
        with self._lock:
            return len(self._instances)

    # ----------------------------------------------------------------- merge
    def merged_families(self, local: Optional[Tuple[str, list]] = None
                        ) -> List[MetricFamily]:
        """The merged view. ``local`` = ``(tag, families)`` folds the
        aggregator's own registry in as one more instance (the UIServer
        passes its own ``registry.collect()`` so the merged exposition
        covers the whole fleet including the host process)."""
        with self._lock:
            instances = [(tag, ent["seq"], ent["snapshot"])
                         for tag, ent in sorted(self._instances.items())]
        contributions: List[Tuple[str, int, dict]] = []
        if local is not None:
            tag, fams = local
            snap = {"families": [
                {"name": f.name, "kind": f.kind, "help": f.help,
                 "samples": [{"labels": dict(s.labels), "suffix": s.suffix,
                              "value": s.value} for s in f.samples]}
                for f in fams]}
            # the local process is always the freshest writer
            contributions.append((tag, 1 + max(
                [seq for _, seq, _ in instances], default=0), snap))
        contributions.extend(instances)

        merged: Dict[str, MetricFamily] = {}
        kinds: Dict[str, str] = {}
        # series -> rollup accumulator:
        # (family, suffix, labelkey) -> [labels, value, best_seq]
        rollup: Dict[Tuple[str, str, str], list] = {}
        order: List[str] = []
        for tag, seq, snap in contributions:
            for fdict in snap.get("families", ()):
                name, kind = fdict.get("name"), fdict.get("kind")
                if not name or kind not in ("counter", "gauge", "histogram"):
                    continue
                if name not in kinds:
                    kinds[name] = kind
                    merged[name] = MetricFamily(
                        name, kind, fdict.get("help") or "")
                    order.append(name)
                elif kinds[name] != kind:
                    continue  # conflicting kind: first writer wins
                fam = merged[name]
                for s in fdict.get("samples", ()):
                    labels = {str(k): str(v)
                              for k, v in (s.get("labels") or {}).items()}
                    labels.pop("instance", None)
                    suffix = s.get("suffix") or ""
                    try:
                        value = float(s.get("value"))
                    except (TypeError, ValueError):
                        continue
                    fam.add(value, {**labels, "instance": tag}, suffix)
                    rkey = (name, suffix,
                            sample_key(name, labels, suffix))
                    ent = rollup.get(rkey)
                    summed = (kinds[name] == "counter"
                              or kinds[name] == "histogram")
                    if ent is None:
                        rollup[rkey] = [labels, value, seq]
                    elif summed:
                        ent[1] += value
                    elif seq >= ent[2]:      # gauge: last write wins
                        ent[1], ent[2] = value, seq
        for (name, suffix, _), (labels, value, _) in rollup.items():
            merged[name].add(value, {**labels, "instance": self.FLEET},
                             suffix)
        return [merged[name] for name in order]

    def render_prometheus(self, local: Optional[Tuple[str, list]] = None
                          ) -> str:
        fams = self.merged_families(local)
        if not fams:
            return "\n"
        return "\n".join(f.render() for f in fams) + "\n"

    # ---------------------------------------------------------------- health
    def health(self) -> List[dict]:
        """The scoreboard: one dict per instance with liveness (heartbeat
        + push age vs ``stale_after_s``), readiness (the pushed
        ``healthy`` flags, e.g. the serving batcher's device-thread
        liveness), queue depth, step count and progress age.

        Instances whose heartbeat age exceeds
        ``evict_after_factor * stale_after_s`` are auto-evicted here —
        removed from the federation, not just flagged stale — so a
        fleet that shrank stops advertising its dead processes."""
        now = time.time()
        with self._lock:
            items = sorted(self._instances.items())
        evict = []
        out = []
        for tag, ent in items:
            snap = ent["snapshot"]
            push_age = max(0.0, now - ent["received_at"])
            hb = _family_value(snap, "dl4j_heartbeat_timestamp_seconds")
            snap_time = snap.get("time")
            # heartbeat age = staleness at push time (child clock) plus
            # how long ago the push landed (aggregator clock) — robust
            # to small cross-host clock skew
            hb_age = push_age
            if hb is not None and snap_time is not None:
                hb_age += max(0.0, float(snap_time) - float(hb))
            if self.evict_after_factor is not None and \
                    hb_age > self.evict_after_factor * self.stale_after_s:
                evict.append((tag, ent["seq"]))
                continue
            health_payload = snap.get("health") or {}
            flags = [bool(v) for k, v in health_payload.items()
                     if k.endswith("healthy") or k == "ready"]
            live = hb_age <= self.stale_after_s
            steps = ent.get("steps")
            row = {
                "instance": tag,
                "identity": snap.get("identity") or {},
                "live": live,
                "ready": live and all(flags) if flags else live,
                "heartbeat_age_s": round(hb_age, 3),
                "push_age_s": round(push_age, 3),
                "pushes": ent["pushes"],
                "queue_depth": _family_value(
                    snap, "dl4j_serving_queue_depth", agg=sum),
                # the cross-host routing gauges (serving/router.py):
                # backlog-derived Retry-After and observed drain rate,
                # straight off the host's pushed serving families
                "retry_after_s": _family_value(
                    snap, "dl4j_serving_retry_after_seconds", agg=min),
                "drain_rate_rows_per_s": _family_value(
                    snap, "dl4j_serving_drain_rate_rows_per_s", agg=sum),
                "steps_total": steps,
                "last_progress_age_s": (
                    round(max(0.0, now - ent["steps_changed_at"]), 3)
                    if steps is not None else None),
                "health": health_payload,
                # per-replica serving rows (status + queue depth), pushed
                # by a fleet-mode ModelServer — the scoreboard shows the
                # replica hole behind a "degraded" instance
                "replicas": health_payload.get("replicas"),
            }
            out.append(row)
        if evict:
            with self._lock:
                for tag, seq in evict:
                    ent = self._instances.get(tag)
                    # seq guard: a push that landed while we were
                    # scoring means the instance is alive after all
                    if ent is not None and ent["seq"] == seq:
                        self._instances.pop(tag)
                        self.auto_evicted_total += 1
        return out

    def fleet_payload(self) -> dict:
        """The ``/api/fleet`` JSON: scoreboard + aggregate counts."""
        rows = self.health()
        return {
            "time": time.time(),
            "instances": rows,
            "live": sum(1 for r in rows if r["live"]),
            "ready": sum(1 for r in rows if r["ready"]),
            "stale_after_s": self.stale_after_s,
            "evict_after_factor": self.evict_after_factor,
            "auto_evicted_total": self.auto_evicted_total,
        }


# ---------------------------------------------------------------------------
# request-scoped trace stitching: span push + the aggregator-side store
# ---------------------------------------------------------------------------

class SpanPushBuffer:
    """Bounded tracer sink collecting request-scoped spans (any span
    whose attrs carry a ``trace_id`` or ``trace_ids``) for the
    federation push channel.

    Registered via :meth:`install` as a ``Tracer`` sink, so it sees
    exactly the spans that survived the tracer's own sampling —
    ``DL4J_TPU_TRACE_SAMPLE`` throttles the push wire for free, and
    ``DL4J_TPU_TRACE=0`` silences it entirely (a disabled tracer records
    nothing, so nothing reaches any sink). The buffer is a drain-on-push
    ring: :meth:`payload` empties it into one schema-versioned batch
    (the ``spans`` key of :func:`export_snapshot`); overflow between
    pushes drops the OLDEST spans and counts them, so a stalled pusher
    degrades to losing history, never memory."""

    def __init__(self, tracer=None, capacity: int = 2048):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._spans: list = []
        self.dropped = 0
        self._tracer = None
        if tracer is not None:
            self.install(tracer)

    # ----------------------------------------------------------------- sink
    def _sink(self, span) -> None:
        attrs = span.attrs
        if not attrs or ("trace_id" not in attrs
                         and "trace_ids" not in attrs):
            return
        with self._lock:
            if len(self._spans) >= self.capacity:
                del self._spans[0]
                self.dropped += 1
            self._spans.append(span)

    def install(self, tracer=None) -> "SpanPushBuffer":
        from deeplearning4j_tpu.observability.trace import get_tracer
        t = tracer if tracer is not None else get_tracer()
        if self._tracer is not None and self._tracer is not t:
            self._tracer.remove_sink(self._sink)
        self._tracer = t
        t.add_sink(self._sink)
        return self

    def remove(self) -> None:
        t, self._tracer = self._tracer, None
        if t is not None:
            t.remove_sink(self._sink)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # ---------------------------------------------------------------- export
    def drain(self) -> list:
        with self._lock:
            out, self._spans = self._spans, []
        return out

    def payload(self) -> Optional[dict]:
        """Drain into one push batch, or None when there is nothing to
        say (the snapshot then carries no ``spans`` key at all).
        ``epoch_unix`` anchors the batch's monotonic ``ts_us`` stamps to
        wall-clock so the TraceStore can lay spans from N processes on
        one timeline."""
        spans = self.drain()
        if not spans:
            return None
        tracer = self._tracer
        if tracer is not None:
            epoch = tracer.epoch_unix()
        else:
            epoch = time.time() - time.perf_counter()
        return {
            "schema": TRACE_PUSH_SCHEMA_VERSION,
            "epoch_unix": epoch,
            "count": len(spans),
            "dropped_total": self.dropped,
            "spans": [s.to_dict() for s in spans],
        }


class TraceStore:
    """Router/UIServer-side index of pushed spans by trace id, plus the
    stitcher that renders ``GET /api/trace/<id>`` waterfalls.

    Ingest side: :meth:`ingest_snapshot` pulls the ``spans`` batch out
    of a pushed metrics snapshot (the ``/api/metrics_push`` hook) and
    files every span under each trace id its attrs carry, stamped with
    the pushing instance and rebased to approximate unix time via the
    batch's ``epoch_unix`` anchor. The aggregator's OWN network spans
    (the router's per-hop send/recv timestamps) enter directly through
    :meth:`observe_network` — they are already on the local clock.

    Bounds: at most ``max_traces`` trace ids (LRU by last update) and
    ``max_spans_per_trace`` spans each (oldest dropped, counted) — a
    busy fleet ages out history, never grows without bound.

    Stitching (:meth:`waterfall`): per-process clocks only agree to
    within NTP skew, so spans from each instance are rebased against
    the router's send/recv anchors — for every proxied hop matched to
    its server-side handler span (same ``host``/``server_url``, paired
    in time order) the instance's clock offset is chosen so the handler
    span sits centered inside the hop's [send, recv] window (the
    classic RPC skew correction; the residual uncertainty is the
    asymmetry of the two network legs). What the hop window does not
    explain becomes explicit ``network`` segments — the queue_wait /
    batch_assembly / device_compute / network waterfall the dashboard
    renders."""

    #: server-side spans that cover one whole proxied request — the
    #: skew-correction partners of the router's network hops
    HANDLER_SPANS = frozenset({"predict_handler", "decode_op"})
    NETWORK_SPAN = "router_proxy"

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 512):
        self.max_traces = max(1, int(max_traces))
        self.max_spans_per_trace = max(1, int(max_spans_per_trace))
        self._lock = threading.Lock()
        self._traces: Dict[str, dict] = {}   # insertion order = LRU
        self.ingested_spans = 0
        self.dropped_spans = 0
        self.evicted_traces = 0

    # ---------------------------------------------------------------- ingest
    def ingest_snapshot(self, snapshot: dict) -> int:
        """File the ``spans`` batch of one pushed snapshot (if any);
        returns the number of span records filed."""
        if not isinstance(snapshot, dict):
            return 0
        payload = snapshot.get("spans")
        if not isinstance(payload, dict):
            return 0
        ident = snapshot.get("identity") or {}
        tag = ident.get("tag") or ident.get("instance") or "unknown"
        return self.ingest_payload(str(tag), payload)

    def ingest_payload(self, instance: str, payload: dict) -> int:
        if payload.get("schema") != TRACE_PUSH_SCHEMA_VERSION:
            return 0   # unknown schema: drop whole batch, never guess
        try:
            epoch = float(payload.get("epoch_unix"))
        except (TypeError, ValueError):
            return 0
        n = 0
        for sd in payload.get("spans", ()):
            attrs = sd.get("attrs") or {}
            ids = []
            tid = attrs.get("trace_id")
            if tid:
                ids.append(str(tid))
            for t in attrs.get("trace_ids") or ():
                ids.append(str(t))
            if not ids:
                continue
            try:
                start = epoch + float(sd.get("ts_us", 0.0)) / 1e6
                dur_ms = float(sd.get("dur_us", 0.0)) / 1e3
            except (TypeError, ValueError):
                continue
            ent = {"name": sd.get("name") or "", "instance": instance,
                   "start_unix": start, "dur_ms": dur_ms,
                   "thread": sd.get("thread") or "", "attrs": attrs}
            for t in dict.fromkeys(ids):
                self._add(t, ent)
                n += 1
        return n

    def observe_network(self, trace_id: str, *, host: str, path: str,
                        send_unix: float, recv_unix: float,
                        status: Optional[int] = None,
                        instance: str = "router") -> None:
        """Record one proxied hop's send/recv anchor (the aggregator's
        own clock) — the timestamps every other instance's spans are
        rebased against."""
        self._add(str(trace_id), {
            "name": self.NETWORK_SPAN, "instance": instance,
            "start_unix": float(send_unix),
            "dur_ms": max(0.0, (float(recv_unix) - float(send_unix))
                          * 1e3),
            "thread": "",
            "attrs": {"trace_id": str(trace_id), "host": host,
                      "path": path, "send_unix": float(send_unix),
                      "recv_unix": float(recv_unix),
                      **({"status": int(status)}
                         if status is not None else {})},
        })

    def _add(self, trace_id: str, ent: dict) -> None:
        with self._lock:
            rec = self._traces.pop(trace_id, None)
            if rec is None:
                rec = {"spans": [], "dropped": 0}
            self._traces[trace_id] = rec      # re-insert: LRU freshest
            if len(rec["spans"]) >= self.max_spans_per_trace:
                del rec["spans"][0]
                rec["dropped"] += 1
                self.dropped_spans += 1
            rec["spans"].append(ent)
            self.ingested_spans += 1
            while len(self._traces) > self.max_traces:
                oldest = next(iter(self._traces))
                self._traces.pop(oldest)
                self.evicted_traces += 1

    # ----------------------------------------------------------------- views
    def trace_ids(self) -> List[str]:
        """Known trace ids, least recently updated first."""
        with self._lock:
            return list(self._traces)

    def get(self, trace_id: str) -> List[dict]:
        with self._lock:
            rec = self._traces.get(str(trace_id))
            spans = list(rec["spans"]) if rec else []
        return sorted(spans, key=lambda e: e["start_unix"])

    def describe(self) -> dict:
        with self._lock:
            return {"traces": len(self._traces),
                    "ingested_spans": self.ingested_spans,
                    "dropped_spans": self.dropped_spans,
                    "evicted_traces": self.evicted_traces,
                    "max_traces": self.max_traces,
                    "max_spans_per_trace": self.max_spans_per_trace}

    # ------------------------------------------------------------- stitching
    def waterfall(self, trace_id: str) -> dict:
        """The stitched per-request view: every span of the trace,
        clock-skew-rebased onto the aggregator's timeline, plus derived
        ``network`` gap segments per proxied hop and a per-phase summary
        (the ``/api/trace/<id>`` payload)."""
        spans = self.get(trace_id)
        if not spans:
            return {"trace_id": str(trace_id), "found": False,
                    "segments": []}
        hops = [s for s in spans if s["name"] == self.NETWORK_SPAN]
        offsets = self._clock_offsets(spans, hops)

        segments = []
        for s in spans:
            off = offsets.get(s["instance"], 0.0)
            segments.append({
                "name": s["name"], "instance": s["instance"],
                "start_unix": s["start_unix"] + off,
                "dur_ms": s["dur_ms"], "thread": s["thread"],
                "attrs": s["attrs"],
            })
        # derived network gaps: hop window minus its handler span
        for hop, handler in self._match_hops(spans, hops):
            send = hop["start_unix"]
            recv = send + hop["dur_ms"] / 1e3
            if handler is None:
                continue
            off = offsets.get(handler["instance"], 0.0)
            h0 = handler["start_unix"] + off
            h1 = h0 + handler["dur_ms"] / 1e3
            out_ms = max(0.0, (h0 - send) * 1e3)
            back_ms = max(0.0, (recv - h1) * 1e3)
            host = hop["attrs"].get("host", "")
            if out_ms > 0.0:
                segments.append({"name": "network", "instance": "wire",
                                 "start_unix": send, "dur_ms": out_ms,
                                 "thread": "",
                                 "attrs": {"direction": "request",
                                           "host": host}})
            if back_ms > 0.0:
                segments.append({"name": "network", "instance": "wire",
                                 "start_unix": h1, "dur_ms": back_ms,
                                 "thread": "",
                                 "attrs": {"direction": "response",
                                           "host": host}})
        segments.sort(key=lambda e: e["start_unix"])
        t0 = segments[0]["start_unix"]
        t1 = max(e["start_unix"] + e["dur_ms"] / 1e3 for e in segments)
        for e in segments:
            e["start_ms"] = round((e.pop("start_unix") - t0) * 1e3, 3)
            e["dur_ms"] = round(e["dur_ms"], 3)
        summary: Dict[str, float] = {}
        for e in segments:
            summary[e["name"]] = summary.get(e["name"], 0.0) + e["dur_ms"]
        return {
            "trace_id": str(trace_id),
            "found": True,
            "t0_unix": t0,
            "total_ms": round((t1 - t0) * 1e3, 3),
            "instances": sorted({e["instance"] for e in segments}),
            "clock_offsets_ms": {k: round(v * 1e3, 3)
                                 for k, v in offsets.items() if v},
            "summary_ms": {k: round(v, 3)
                           for k, v in sorted(summary.items())},
            "segments": segments,
        }

    def _match_hops(self, spans, hops):
        """Pair each network hop with the server-side handler span it
        carried: same target (hop ``host`` == handler ``server_url``),
        paired in time order — the k-th hop to a host matches the k-th
        handler span that host reported for this trace."""
        handlers: Dict[str, list] = {}
        for s in spans:
            if s["name"] in self.HANDLER_SPANS:
                url = str(s["attrs"].get("server_url", ""))
                handlers.setdefault(url.rstrip("/"), []).append(s)
        for url in handlers:
            handlers[url].sort(key=lambda e: e["start_unix"])
        cursor: Dict[str, int] = {}
        pairs = []
        for hop in sorted(hops, key=lambda e: e["start_unix"]):
            url = str(hop["attrs"].get("host", "")).rstrip("/")
            cand = handlers.get(url, [])
            i = cursor.get(url, 0)
            pairs.append((hop, cand[i] if i < len(cand) else None))
            cursor[url] = i + 1
        return pairs

    def _clock_offsets(self, spans, hops) -> Dict[str, float]:
        """Per-instance clock correction (seconds to ADD to that
        instance's timestamps): center each matched handler span inside
        its hop's [send, recv] window and take the median correction
        per instance. Instances with no matched hop keep offset 0 (they
        already share the aggregator's clock, or there is nothing to
        rebase against)."""
        by_instance: Dict[str, list] = {}
        for hop, handler in self._match_hops(spans, hops):
            if handler is None:
                continue
            hop_center = hop["start_unix"] + hop["dur_ms"] / 2e3
            h_center = handler["start_unix"] + handler["dur_ms"] / 2e3
            by_instance.setdefault(handler["instance"], []).append(
                hop_center - h_center)
        out = {}
        for inst, offs in by_instance.items():
            offs.sort()
            out[inst] = offs[len(offs) // 2]
        return out


def _family_value(snapshot: dict, name: str, agg=None) -> Optional[float]:
    """Pull one family's scalar out of a wire snapshot (sum of its plain
    samples by default — per-label children of a counter/gauge)."""
    for fdict in snapshot.get("families", ()):
        if fdict.get("name") != name:
            continue
        vals = []
        for s in fdict.get("samples", ()):
            if s.get("suffix"):
                continue
            try:
                vals.append(float(s.get("value")))
            except (TypeError, ValueError):
                continue
        if not vals:
            return None
        return float((agg or sum)(vals)) if len(vals) > 1 else vals[0]
    return None
