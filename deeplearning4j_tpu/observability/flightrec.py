"""Crash flight recorder: a black-box ring flushed on the way down.

Post-mortems for the resilience tier currently depend on whatever the
process managed to log before dying. The flight recorder keeps a small
always-on ring of the most recent spans (fed straight off the tracer's
sink seam, so it sees exactly what the tracer saw, including sampled-in
spans only) plus recent supervisor events (recovery, NaN rollback,
preemption, checkpoint activity) and, at flush time, a full metrics
snapshot. On SIGTERM, unhandled exception, NaN rollback or preemption
the ring is flushed atomically (tmp + ``os.replace``) to
``flight_<tag>.json`` — ``tag`` being the instance name suffixed with
the supervisor incarnation, so every relaunch of ``chaos_train.py``
leaves its own readable artifact instead of overwriting the last one.

The recorder is deliberately cheap on the hot path: recording a span is
one deque append under the tracer's existing sink call; recording an
event is one deque append under its own lock; everything expensive
(metrics snapshot, JSON encode, file IO) happens only at flush. The
``identity_overhead`` bench in ``bench.py`` holds the installed-vs-not
fit-time delta under 1%.

Schema (``"schema": 1``) is documented with an example in
OBSERVABILITY.md "Fleet & post-mortems".
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback as _tb
from collections import deque
from typing import Optional

from deeplearning4j_tpu.observability import trace as _trace
from deeplearning4j_tpu.observability.distributed import get_identity

__all__ = [
    "FlightRecorder", "get_flight_recorder", "install_flight_recorder",
    "uninstall_flight_recorder",
]

FLIGHT_SCHEMA_VERSION = 1


def _ring_trace_ids(spans) -> list:
    """Ordered unique trace ids riding the ring's span attrs (oldest
    first) — the ``trace_ids`` field of the flight artifact, and the
    join key that lets a post-mortem pull the same requests' stitched
    waterfalls out of the router's TraceStore."""
    seen: dict = {}
    for s in spans:
        attrs = s.attrs or {}
        tid = attrs.get("trace_id")
        if tid:
            seen[str(tid)] = None
        for t in attrs.get("trace_ids") or ():
            seen[str(t)] = None
    return list(seen)


def _sanitize(tag: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "_" for c in tag)


class FlightRecorder:
    """Bounded black-box ring of spans + events, flushed atomically to
    ``flight_<tag>.json`` when something goes wrong."""

    def __init__(self, dir: Optional[str] = None, capacity: int = 256,
                 event_capacity: int = 128):
        self.dir = (dir or os.environ.get("DL4J_TPU_FLIGHT_DIR")
                    or os.getcwd())
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._spans = deque(maxlen=int(capacity))
        self._events = deque(maxlen=int(event_capacity))
        self._installed = False
        self._prev_excepthook = None
        self._flushes = 0
        #: path of the most recent artifact (None until first flush)
        self.last_path: Optional[str] = None

    # ------------------------------------------------------------- recording
    def _sink(self, span) -> None:
        # called by the tracer outside its lock, per recorded span
        with self._lock:
            self._spans.append(span)

    def record_event(self, kind: str, step: Optional[int] = None,
                     detail: str = "") -> None:
        """Append one supervisor/runtime event (recovery, nan_rollback,
        preemption, checkpoint, ...) to the event ring."""
        with self._lock:
            self._events.append({"time": time.time(), "kind": str(kind),
                                 "step": step, "detail": str(detail)})

    # ----------------------------------------------------------- lifecycle
    def install(self) -> "FlightRecorder":
        """Attach to the current tracer's sink seam and chain into
        ``sys.excepthook`` so a crash flushes the box. Idempotent."""
        if self._installed:
            return self
        _trace.get_tracer().add_sink(self._sink)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        try:
            _trace.get_tracer().remove_sink(self._sink)
        except Exception:
            pass
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        self._prev_excepthook = None
        self._installed = False

    def _excepthook(self, exc_type, exc, tb):
        try:
            self.flush("unhandled_exception", exc=exc)
        except Exception:
            pass
        (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    # --------------------------------------------------------------- flush
    def flush(self, reason: str, exc: Optional[BaseException] = None
              ) -> Optional[str]:
        """Write the black box to ``flight_<tag>.json`` atomically;
        returns the path (None if the write failed — a flight recorder
        must never turn a crash into a different crash)."""
        ident = get_identity()
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            self._flushes += 1
        doc = {
            "schema": FLIGHT_SCHEMA_VERSION,
            "reason": str(reason),
            "time": time.time(),
            "identity": ident.to_dict(),
            "exception": None,
            "events": events,
            # the last-N request trace ids this process saw — join
            # these against the aggregator's /api/trace/<id> store
            "trace_ids": _ring_trace_ids(spans),
            "spans": [
                {"name": s.name, "ts_us": s.ts_us, "dur_us": s.dur_us,
                 "thread": s.thread, "attrs": dict(s.attrs or {})}
                for s in spans],
            "metrics": None,
        }
        if exc is not None:
            doc["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(_tb.format_exception(
                    type(exc), exc, exc.__traceback__))[-8000:],
            }
        try:
            from deeplearning4j_tpu.observability.metrics import get_registry
            doc["metrics"] = get_registry().snapshot()
        except Exception:
            pass
        # rank-suffixed in multi-process runs (rank 0 keeps the legacy
        # name): N workers sharing one checkpoint dir under a default
        # identity would otherwise clobber each other's post-mortems
        from deeplearning4j_tpu.observability.distributed import rank_suffix
        path = os.path.join(
            self.dir, f"flight_{_sanitize(ident.tag)}{rank_suffix()}.json")
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        self.last_path = path
        return path


_rec_lock = threading.Lock()
_RECORDER: Optional[FlightRecorder] = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The installed process-wide recorder, or None."""
    return _RECORDER


def install_flight_recorder(dir: Optional[str] = None,
                            capacity: int = 256) -> FlightRecorder:
    """Create-or-reuse the process-wide recorder and install it. A
    second call just repoints the flush directory (the supervisor calls
    this per launch with its checkpoint dir)."""
    global _RECORDER
    with _rec_lock:
        if _RECORDER is None:
            _RECORDER = FlightRecorder(dir=dir, capacity=capacity)
        elif dir is not None:
            _RECORDER.dir = dir
        return _RECORDER.install()


def uninstall_flight_recorder() -> None:
    """Detach and forget the process-wide recorder (tests, benches)."""
    global _RECORDER
    with _rec_lock:
        if _RECORDER is not None:
            _RECORDER.uninstall()
            _RECORDER = None
