"""Structured span tracing: the "where did step N spend its time" core.

The reference dedicates a module tier (deeplearning4j-ui-parent, ~25k
LoC) to stats collection and timeline export; TensorFlow (arXiv:
1605.08695 §9) treats tracing as a first-class runtime subsystem. After
PRs 1-3 this framework runs real concurrency — a pipelined fit loop, a
MicroBatcher device thread, an async checkpoint writer — and a span
tracer is the only honest way to see them against each other.

Design constraints, in order:

1. **Hot-path overhead**: recording one span is two ``perf_counter``
   calls plus one append into a bounded ring, under one uncontended
   lock — no allocation of dicts/strings beyond the tuple, no I/O, no
   device sync. The ``trace_overhead`` bench entry holds the fit-loop
   regression under 3% at default sampling; ``Tracer.disabled`` spans
   cost one attribute read.
2. **Thread lanes**: every span records its thread id + name, so the
   Chrome-trace export renders the fit loop, the ``microbatcher-device``
   thread and the ``dl4j-ckpt-writer`` thread as separate lanes in
   Perfetto / ``chrome://tracing``.
3. **XLA correlation**: with ``annotate=True`` each span is also wrapped
   in ``jax.profiler.TraceAnnotation``, so the same names appear inside
   device profiles captured by ``ProfilerListener`` — one taxonomy
   across host timeline and XLA trace.

Span taxonomy (OBSERVABILITY.md has the full table):

- fit loop (both nets): ``data_wait``, ``host_dispatch``,
  ``device_step``, ``score_sync``
- serving (MicroBatcher): ``queue_wait``, ``batch_assembly``,
  ``device_compute``
- resilience supervisor: ``checkpoint_snapshot``, ``checkpoint_write``,
  ``checkpoint_barrier``, ``rollback``, ``restore``
- distributed phases (parallel/stats.py): ``fit``, ``average``,
  ``checkpoint_barrier`` (the TrainingStatsCollector feeds the same
  tracer, so Spark-tier phases land in the same timeline)
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, NamedTuple, Optional, Sequence

__all__ = [
    "Span", "Tracer", "get_tracer", "set_tracer", "span", "trace_span",
    "trace_timeline_component",
]


class Span(NamedTuple):
    """One completed span. Times are microseconds since the tracer's
    epoch (``perf_counter`` based — monotonic, comparable across threads
    of one process)."""
    name: str
    ts_us: float
    dur_us: float
    tid: int
    thread: str
    attrs: Optional[dict]

    def to_dict(self) -> dict:
        d = {"name": self.name, "ts_us": round(self.ts_us, 3),
             "dur_us": round(self.dur_us, 3), "tid": self.tid,
             "thread": self.thread}
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _SpanCtx:
    """Hand-rolled context manager: ~2x cheaper than
    ``@contextmanager`` on the per-step hot path."""

    __slots__ = ("_tracer", "_name", "_attrs", "_t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, attrs):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._ann = None

    def __enter__(self):
        if self._tracer.annotate:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self._name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tracer._record(self._name, self._t0, t1, self._attrs)
        return False


class _NullCtx:
    """Returned by a disabled tracer — a shared no-op (no allocation)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class Tracer:
    """Thread-safe bounded-ring span recorder.

    - ``capacity``: ring size (oldest spans evicted — a dashboard wants
      the recent window, not since-boot history; export what you need
      before it scrolls off).
    - ``sample_every``: keep 1 of every N occurrences *per span name*
      (N=1, the default, records everything — the fit-loop overhead
      budget already clears 3% unsampled; raise it for pathological
      span rates).
    - ``annotate``: additionally wrap each span in
      ``jax.profiler.TraceAnnotation`` so names appear in XLA/Perfetto
      device profiles (off by default: TraceMe has its own cost and is
      only useful while a profiler trace is recording).
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True,
                 annotate: bool = False, sample_every: int = 1):
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self.annotate = bool(annotate)
        self.sample_every = max(1, int(sample_every))
        self._ring: deque[Span] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        self._seen: dict = {}       # name -> occurrence count (sampling)
        self.dropped = 0            # spans evicted or sampled away
        self._dropped_by_name: dict = {}  # name -> drop count
        self._sinks: list = []      # fns called with each recorded Span

    # ------------------------------------------------------------ recording
    def span(self, name: str, **attrs):
        """Context manager timing one span: ``with tracer.span("x"): ...``"""
        if not self.enabled:
            return _NULL
        return _SpanCtx(self, name, attrs or None)

    def record(self, name: str, t0: float, t1: float, attrs: dict = None,
               tid: int = None, thread: str = None):
        """Record an explicitly-timed span (``perf_counter`` endpoints) —
        for spans whose start lives on another thread (e.g. a serving
        ticket's ``queue_wait`` measured from its submit timestamp)."""
        if self.enabled:
            self._record(name, t0, t1, attrs, tid, thread)

    def _record(self, name, t0, t1, attrs, tid=None, thread=None):
        if tid is None:
            t = threading.current_thread()
            tid, thread = t.ident or 0, t.name
        with self._lock:
            if self.sample_every > 1:
                seen = self._seen.get(name, 0)
                self._seen[name] = seen + 1
                if seen % self.sample_every:
                    self.dropped += 1
                    self._dropped_by_name[name] = \
                        self._dropped_by_name.get(name, 0) + 1
                    return
            if len(self._ring) == self.capacity:
                # ring eviction loses the OLDEST span — count its name,
                # not the incoming one, so the drop table says which
                # phase's history actually scrolled off
                self.dropped += 1
                evicted = self._ring[0].name
                self._dropped_by_name[evicted] = \
                    self._dropped_by_name.get(evicted, 0) + 1
            span = Span(
                name, (t0 - self._epoch) * 1e6, (t1 - t0) * 1e6,
                tid, thread or "", attrs)
            self._ring.append(span)
            sinks = self._sinks
        for sink in sinks:
            try:
                sink(span)
            except Exception:
                pass  # a broken sink must never break the hot path

    # ---------------------------------------------------------------- sinks
    def add_sink(self, fn) -> None:
        """Register a callable invoked with every recorded Span (outside
        the ring lock; exceptions swallowed). Sinks see spans even when
        the ring later evicts them — the goodput ledger's feed."""
        with self._lock:
            if fn not in self._sinks:
                # copy-on-write: _record iterates a snapshot lock-free
                self._sinks = self._sinks + [fn]

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks = [s for s in self._sinks if s is not fn]

    # -------------------------------------------------------------- control
    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._seen.clear()
            self.dropped = 0
            self._dropped_by_name = {}

    # ---------------------------------------------------------------- clocks
    def epoch_unix(self) -> float:
        """Approximate unix time of the tracer's perf_counter epoch —
        the anchor that converts ``Span.ts_us`` (µs since epoch,
        monotonic, per-process) into wall-clock time so spans pushed
        from different processes can be laid on one timeline. Computed
        fresh per call from the current clock pair; the residual error
        is the clock-read skew (µs), far below the network gaps the
        cross-process waterfall resolves."""
        return time.time() - (time.perf_counter() - self._epoch)

    # ------------------------------------------------------------ drop stats
    def dropped_spans(self) -> dict:
        """Per-name dropped-span counts (ring eviction counts the
        evicted span's name; sampling counts the sampled-away name)."""
        with self._lock:
            return dict(self._dropped_by_name)

    # --------------------------------------------------------------- export
    def spans(self) -> List[Span]:
        """Snapshot of the ring (oldest first). Taken under the lock —
        recorder threads may keep appending while the caller iterates
        the returned list safely."""
        with self._lock:
            return list(self._ring)

    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` form
        Perfetto and ``chrome://tracing`` load): one ``ph: "X"`` complete
        event per span, one ``ph: "M"`` thread_name metadata event per
        thread so lanes are labeled. Events are sorted by ``ts``."""
        spans = self.spans()
        pid = os.getpid()
        events = []
        threads = {}
        for s in spans:
            threads.setdefault(s.tid, s.thread)
            ev = {"ph": "X", "name": s.name, "cat": "dl4j_tpu",
                  "pid": pid, "tid": s.tid,
                  "ts": round(s.ts_us, 3), "dur": round(s.dur_us, 3)}
            if s.attrs:
                ev["args"] = s.attrs
            events.append(ev)
        events.sort(key=lambda e: e["ts"])
        meta = [{"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                 "args": {"name": name or f"thread-{tid}"}}
                for tid, name in sorted(threads.items())]
        out = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        dropped = self.dropped_spans()
        if self.dropped or dropped:
            # stamp data loss into the artifact: a timeline missing its
            # oldest spans should say so rather than look complete
            out["otherData"] = {
                "dropped_spans_total": self.dropped,
                "dropped_spans_by_name": dropped,
            }
        try:
            # identity rides in otherData (NOT a metadata event — lanes
            # stay thread_name-only) so exports from different fleet
            # members can be attributed and merged after the fact
            from deeplearning4j_tpu.observability.distributed import \
                get_identity
            out.setdefault("otherData", {})["identity"] = \
                get_identity().to_dict()
        except Exception:
            pass
        return out

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def export_jsonl(self, path: str) -> str:
        """One span per line — the grep/pandas-friendly raw form."""
        with open(path, "w") as f:
            for s in self.spans():
                f.write(json.dumps(s.to_dict()) + "\n")
        return path

    # ------------------------------------------------------------- analysis
    def totals_ms(self) -> dict:
        """Total recorded wall-clock per span name, in ms (the quick
        "what dominates" table)."""
        out: dict = {}
        for s in self.spans():
            out[s.name] = out.get(s.name, 0.0) + s.dur_us / 1000.0
        return out


# --------------------------------------------------------------------------
# process-global tracer (the one every runtime feeds by default)
# --------------------------------------------------------------------------

def _env_default() -> Tracer:
    """DL4J_TPU_TRACE=0 disables span recording process-wide;
    DL4J_TPU_TRACE_SAMPLE=N sets the default sampling."""
    enabled = os.environ.get("DL4J_TPU_TRACE", "1") != "0"
    sample = int(os.environ.get("DL4J_TPU_TRACE_SAMPLE", "1"))
    return Tracer(enabled=enabled, sample_every=sample)


_GLOBAL = _env_default()


def get_tracer() -> Tracer:
    return _GLOBAL


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer (tests, custom capacities).
    Returns the previous one so callers can restore it."""
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, tracer
    return prev


def span(name: str, **attrs):
    """``with span("data_wait"): ...`` against the global tracer."""
    return _GLOBAL.span(name, **attrs)


def trace_span(name: str):
    """Decorator form: ``@trace_span("checkpoint_write")``."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with _GLOBAL.span(name):
                return fn(*a, **kw)
        return wrapped
    return deco


# --------------------------------------------------------------------------
# timeline rendering (the ChartTimeline tier the Spark stats export uses)
# --------------------------------------------------------------------------

_PALETTE = ("#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
            "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf")


def span_color(name: str) -> str:
    """Stable span-name -> color (shared by the dashboard JS panel and
    the exported HTML timeline)."""
    return _PALETTE[hash(name) % len(_PALETTE)]


def trace_timeline_component(spans: Sequence[Span],
                             title: str = "Runtime trace"):
    """Per-thread lanes of colored span bars through the same
    ``ChartTimeline`` component the Spark phase timeline renders with
    (parallel/stats.py timeline_component is the phase-tier sibling)."""
    from deeplearning4j_tpu.ui.components import ChartTimeline, Style

    by_thread: dict = {}
    for s in spans:
        by_thread.setdefault(s.thread or f"thread-{s.tid}", []).append(s)
    chart = ChartTimeline(title, Style(
        width=760, height=max(120, 46 + 34 * len(by_thread))),
        xlabel="seconds")
    for name in sorted(by_thread):
        entries = [(s.ts_us / 1e6, (s.ts_us + s.dur_us) / 1e6, s.name,
                    span_color(s.name))
                   for s in sorted(by_thread[name], key=lambda s: s.ts_us)]
        chart.add_lane(name, entries)
    return chart


def export_trace_html(spans: Sequence[Span], path: str,
                      title: str = "Runtime trace") -> None:
    """Standalone HTML timeline (StatsUtils.exportStatsAsHtml parity for
    the span tier)."""
    from deeplearning4j_tpu.ui.components import render_components_to_file

    render_components_to_file([trace_timeline_component(spans)], path, title)
