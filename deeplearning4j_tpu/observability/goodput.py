"""Goodput & efficiency attribution: where did every wall-second go,
and how much of the machine did it buy?

The north-star for this stack is an MFU bar (ROADMAP: 40%; ResNet-50
sits at ~29.8% in BENCH_r05), yet until this module MFU and FLOPs
accounting lived only in offline bench scripts and a manually-wired
``PerformanceListener(flops_per_step=...)``. Here the runtime itself
keeps the books:

- **EfficiencyLedger** — a per-run wall-time ledger fed by the span
  tracer (``Tracer.add_sink``): every recorded span accumulates into a
  per-phase total, independent of the tracer's bounded ring, so the
  attribution never loses data to ring eviction. Phases recorded on the
  run's own thread and named in the run-kind's *exclusive* set
  (``data_wait`` / ``host_dispatch`` / ``device_step`` / ``score_sync``
  / ``flops_derive`` for fit; plus the ``checkpoint_*`` / ``rollback`` / ``restore``
  family under the supervisor; ``batch_assembly`` / ``device_compute``
  for serving) are mutually non-overlapping, so their sum is the
  *attributed* share of total wall time — the ledger invariant tested
  in CI is ``attributed_s ≈ wall_s`` within 5% for a fit run.
- **Goodput** — productive device seconds (``device_step`` +
  ``device_compute``) over total wall seconds. The industry "goodput"
  framing: time making forward progress vs time spent on data stalls,
  host dispatch, checkpoints, rollbacks, recompiles.
- **Live MFU with zero wiring** — both nets derive per-step FLOPs from
  the XLA cost model on the *lowered* train step at step-build time
  (``utils.perf.xla_step_cost_lowered`` — tracing only, no second
  backend compile) and report them here, so ``dl4j_mfu`` /
  ``dl4j_flops_per_second`` / ``dl4j_goodput_fraction`` are live
  Prometheus gauges during any ``fit`` without user code. Peak FLOP/s
  comes from the device table (``utils.perf.PEAK_FLOPS``) or the
  ``DL4J_TPU_PEAK_FLOPS`` override (CPU has no table entry — set the
  override to get MFU there).
- **Padding waste** — the serving bucket ladder and
  ``datapipe.bucket_batch`` report real vs padded rows/cells per
  source; the waste fraction is padded / (real + padded).
- **RunReport** — a structured JSON artifact emitted at the end of
  ``fit`` / ``resilient_fit`` / server drain: goodput %, MFU, the phase
  ledger, compile count/seconds over the run, device-memory watermark,
  padding waste. ``scripts/check_budgets.py`` gates CI on it against
  the committed ``BUDGETS.json``.

Kill switch: ``DL4J_TPU_GOODPUT=0`` (or ``set_enabled(False)``) makes
``start_run`` return a no-op ledger — the bench ``goodput`` entry uses
this to measure the ledger's own overhead (< 3% budget, PERF.md §11).
Set ``DL4J_TPU_RUN_REPORT_DIR`` to also write every report to a file.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "EfficiencyLedger", "RunReport", "start_run", "end_run",
    "current_ledger", "last_report", "observe_steps", "observe_flops",
    "record_padding", "goodput_collector", "live_snapshot",
    "set_enabled", "enabled", "auto_flops_enabled", "resolve_peak_flops",
    "SCHEMA_VERSION",
]

SCHEMA_VERSION = 1

#: phases that are mutually exclusive on the thread driving a training
#: run — their sum is the attributed share of the run's wall time
FIT_EXCLUSIVE = frozenset({
    "data_wait", "host_dispatch", "device_step", "score_sync",
    "flops_derive",
})
SUPERVISOR_EXCLUSIVE = FIT_EXCLUSIVE | frozenset({
    "checkpoint_snapshot", "checkpoint_write", "checkpoint_barrier",
    "rollback", "restore",
})
#: serving attribution happens on the single micro-batcher device
#: thread, not the thread that called start()/stop() — no tid filter
SERVING_EXCLUSIVE = frozenset({"batch_assembly", "device_compute"})

#: productive device time — the goodput numerator
DEVICE_PHASES = frozenset({"device_step", "device_compute"})

_EXCLUSIVE_BY_KIND = {
    "fit": (FIT_EXCLUSIVE, True),
    "resilient_fit": (SUPERVISOR_EXCLUSIVE, True),
    "serving": (SERVING_EXCLUSIVE, False),
}

_lock = threading.Lock()
_ACTIVE: List["EfficiencyLedger"] = []
_LAST_REPORT: Optional["RunReport"] = None
_ENABLED: Optional[bool] = None  # None = read env on first use


def enabled() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("DL4J_TPU_GOODPUT", "1") != "0"
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Process-wide switch (bench uses it to measure ledger overhead)."""
    global _ENABLED
    _ENABLED = bool(flag)


def auto_flops_enabled() -> bool:
    """Whether the fit loops should auto-derive per-step FLOPs from the
    lowered cost model (``DL4J_TPU_AUTO_FLOPS=0`` disables just the
    derivation while keeping the ledger)."""
    return enabled() and os.environ.get("DL4J_TPU_AUTO_FLOPS", "1") != "0"


def resolve_peak_flops() -> Optional[float]:
    """Device peak FLOP/s for the MFU denominator: the PEAK_FLOPS table
    keyed by device kind, or the ``DL4J_TPU_PEAK_FLOPS`` env override
    (the only way to get MFU on CPU, which has no honest table entry)."""
    try:
        import jax

        from deeplearning4j_tpu.utils.perf import peak_flops
        return peak_flops(jax.devices()[0])
    except Exception:
        return None


# ---------------------------------------------------------------------------
# the report artifact
# ---------------------------------------------------------------------------

@dataclass
class RunReport:
    """Structured end-of-run efficiency report (JSON round-trippable).

    ``phases`` maps span name -> {"seconds", "count"} over the whole
    run; ``attributed_s`` sums the run-kind's exclusive phases (on the
    run thread where that applies) and ``untracked_s`` is the wall time
    no exclusive phase claimed. ``padding`` maps source ->
    {"real", "padded", "waste_fraction"}."""

    kind: str
    status: str = "completed"
    wall_s: float = 0.0
    steps: int = 0
    phases: Dict[str, dict] = field(default_factory=dict)
    attributed_s: float = 0.0
    untracked_s: float = 0.0
    device_s: float = 0.0
    goodput_fraction: Optional[float] = None
    flops_per_step: Optional[float] = None
    flops_per_second: Optional[float] = None
    mfu: Optional[float] = None
    peak_flops: Optional[float] = None
    compile_count: int = 0
    compile_seconds: float = 0.0
    # persistent-compilation-cache traffic over the run (compilecache/):
    # warm boots show hits ~= ladder size and misses ~= 0; both 0 when
    # no cache dir is configured
    xla_cache_hits: int = 0
    xla_cache_misses: int = 0
    # cold-start attribution, annotated by the serving runtime:
    # process start -> first successful reply, and the warm-up ladder's
    # wall time (None outside serving / before the first reply)
    cold_start_s: Optional[float] = None
    warmup_s: Optional[float] = None
    device_memory_peak_bytes: Optional[float] = None
    padding: Dict[str, dict] = field(default_factory=dict)
    trace_dropped_spans: int = 0
    # elastic resharding (resilience/supervisor): old/new mesh + datapipe
    # shard cursors when this run resumed a checkpoint saved under a
    # different fleet size; None for a same-topology run
    reshard: Optional[dict] = None
    # SLO attainment summary (observability.slo): SLOEngine.report()
    # stamped by ModelServer.stop() onto the serving drain report, so
    # the receipt that says how fast the run was also says whether it
    # honored its objectives; None outside the serving tier
    slo: Optional[dict] = None
    # fleet identity (observability.distributed): which process/relaunch
    # produced this report — stamped by the ledger at finish time
    run_id: Optional[str] = None
    instance: Optional[str] = None
    incarnation: Optional[int] = None
    schema_version: int = SCHEMA_VERSION

    def to_dict(self) -> dict:
        return {
            "schema_version": self.schema_version,
            "kind": self.kind,
            "status": self.status,
            "wall_s": self.wall_s,
            "steps": self.steps,
            "phases": self.phases,
            "attributed_s": self.attributed_s,
            "untracked_s": self.untracked_s,
            "device_s": self.device_s,
            "goodput_fraction": self.goodput_fraction,
            "flops_per_step": self.flops_per_step,
            "flops_per_second": self.flops_per_second,
            "mfu": self.mfu,
            "peak_flops": self.peak_flops,
            "compile_count": self.compile_count,
            "compile_seconds": self.compile_seconds,
            "xla_cache_hits": self.xla_cache_hits,
            "xla_cache_misses": self.xla_cache_misses,
            "cold_start_s": self.cold_start_s,
            "warmup_s": self.warmup_s,
            "device_memory_peak_bytes": self.device_memory_peak_bytes,
            "padding": self.padding,
            "trace_dropped_spans": self.trace_dropped_spans,
            "reshard": self.reshard,
            "slo": self.slo,
            "run_id": self.run_id,
            "instance": self.instance,
            "incarnation": self.incarnation,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RunReport":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in d.items() if k in known})

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    @classmethod
    def from_json(cls, s: str) -> "RunReport":
        return cls.from_dict(json.loads(s))

    def save(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json(indent=2, sort_keys=True))
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "RunReport":
        with open(path) as f:
            return cls.from_json(f.read())


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class EfficiencyLedger:
    """Accumulates one run's wall-time attribution. Registered as a
    tracer sink for its lifetime, so every span recorded anywhere in
    the process lands in ``phases`` — the exclusive/attributed subset
    is filtered by name (and, for training runs, by the run thread, so
    e.g. an async ``checkpoint_write`` on the writer thread shows up in
    the breakdown without double-counting the main thread's overlapping
    ``device_step`` time)."""

    def __init__(self, kind: str):
        self.kind = kind
        exclusive, tid_filtered = _EXCLUSIVE_BY_KIND.get(
            kind, (FIT_EXCLUSIVE, True))
        self._exclusive = exclusive
        self._tid_filtered = tid_filtered
        self._tid = threading.get_ident()
        self._lock = threading.Lock()
        self._phases: Dict[str, list] = {}   # name -> [seconds, count]
        self._attributed_s = 0.0
        self._device_s = 0.0
        self._steps = 0
        self._flops_per_step: Optional[float] = None
        self._padding: Dict[str, list] = {}  # source -> [real, padded]
        self._t0 = time.perf_counter()
        self._tracer = None
        # per-run compile/cache baseline over the process-global
        # counters (metrics.compile_snapshot — the documented delta
        # seam); start_run overwrites this with a live snapshot
        self._compile0 = {"count": 0, "seconds": 0.0,
                          "cache_hits": 0, "cache_misses": 0}
        self._annotations: Dict[str, object] = {}
        self._dropped0 = 0
        self._closed = False

    # -------------------------------------------------------------- feeding
    def _on_span(self, span) -> None:
        dur_s = span.dur_us / 1e6
        with self._lock:
            ent = self._phases.get(span.name)
            if ent is None:
                self._phases[span.name] = [dur_s, 1]
            else:
                ent[0] += dur_s
                ent[1] += 1
            if span.name in self._exclusive and (
                    not self._tid_filtered or span.tid == self._tid):
                self._attributed_s += dur_s
            if span.name in DEVICE_PHASES:
                self._device_s += dur_s

    def observe_steps(self, n: int) -> None:
        with self._lock:
            self._steps += int(n)

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        if flops:
            with self._lock:
                self._flops_per_step = float(flops)

    def record_padding(self, source: str, real: int, padded: int) -> None:
        with self._lock:
            ent = self._padding.get(source)
            if ent is None:
                self._padding[source] = [int(real), int(padded)]
            else:
                ent[0] += int(real)
                ent[1] += int(padded)

    def annotate(self, **fields) -> None:
        """Stamp RunReport fields the runtime measures out-of-band of
        the span stream (e.g. the server's ``warmup_s`` / ``cold_start_s``).
        Only keys that are RunReport dataclass fields land on the
        report; unknown keys are dropped at finish, so annotating stays
        forward-compatible across schema versions."""
        with self._lock:
            self._annotations.update(fields)

    def rebase_compile(self, snapshot: dict) -> None:
        """Move the compile/cache baseline back to *snapshot* (an
        earlier ``metrics.compile_snapshot()``), so compiles that ran
        before ``start_run`` — e.g. the server's warm-up ladder — are
        charged to this run's report."""
        with self._lock:
            self._compile0 = dict(snapshot)

    # ---------------------------------------------------------------- views
    @property
    def closed(self) -> bool:
        return self._closed

    def live(self) -> dict:
        """Current-state snapshot (the live-gauge source): same shape
        as RunReport.to_dict() minus the end-of-run-only fields."""
        wall = time.perf_counter() - self._t0
        with self._lock:
            steps = self._steps
            device_s = self._device_s
            flops_step = self._flops_per_step
            padding = {k: list(v) for k, v in self._padding.items()}
        out = {
            "kind": self.kind,
            "wall_s": wall,
            "steps": steps,
            "device_s": device_s,
            "goodput_fraction": (device_s / wall if wall > 0 and device_s
                                 else None),
            "flops_per_step": flops_step,
            "flops_per_second": None,
            "mfu": None,
            "padding": {k: _padding_entry(r, p)
                        for k, (r, p) in padding.items()},
        }
        if flops_step and steps and wall > 0:
            fps = flops_step * steps / wall
            out["flops_per_second"] = fps
            peak = resolve_peak_flops()
            if peak:
                mfu = fps / peak
                if 0.0 < mfu <= 1.0:  # never publish impossible MFU
                    out["mfu"] = mfu
        return out

    def phase_totals(self) -> Dict[str, dict]:
        with self._lock:
            return {k: {"seconds": v[0], "count": v[1]}
                    for k, v in sorted(self._phases.items())}

    # -------------------------------------------------------------- closing
    def _finish(self, status: str) -> RunReport:
        from deeplearning4j_tpu.observability import metrics as _m
        wall = time.perf_counter() - self._t0
        compile_run = _m.compile_delta(self._compile0)
        live = self.live()
        with self._lock:
            attributed = self._attributed_s
            known = RunReport.__dataclass_fields__
            extra = {k: v for k, v in self._annotations.items()
                     if k in known}
        tracer = self._tracer
        dropped = 0
        if tracer is not None:
            dropped = max(0, tracer.dropped - self._dropped0)
        peak = resolve_peak_flops()
        fps = live["flops_per_second"]
        try:
            from deeplearning4j_tpu.observability.distributed import \
                get_identity
            ident = get_identity()
            identity = {"run_id": ident.run_id, "instance": ident.instance,
                        "incarnation": ident.incarnation}
        except Exception:
            identity = {}
        report = RunReport(
            **identity,
            kind=self.kind,
            status=status,
            wall_s=wall,
            steps=live["steps"],
            phases=self.phase_totals(),
            attributed_s=attributed,
            untracked_s=max(0.0, wall - attributed),
            device_s=live["device_s"],
            goodput_fraction=live["goodput_fraction"],
            flops_per_step=live["flops_per_step"],
            flops_per_second=fps,
            mfu=live["mfu"],
            peak_flops=peak,
            compile_count=compile_run["count"],
            compile_seconds=compile_run["seconds"],
            xla_cache_hits=compile_run["cache_hits"],
            xla_cache_misses=compile_run["cache_misses"],
            device_memory_peak_bytes=_m.memory_watermark_bytes(),
            padding=live["padding"],
            trace_dropped_spans=dropped,
        )
        for k, v in extra.items():  # annotations override measured fields
            setattr(report, k, v)
        return report


class _NullLedger:
    """Returned by start_run when the engine is disabled: every method
    is a no-op so call sites need no branching."""

    kind = "disabled"
    closed = True

    def _on_span(self, span):
        pass

    def observe_steps(self, n):
        pass

    def set_flops_per_step(self, flops):
        pass

    def record_padding(self, source, real, padded):
        pass

    def annotate(self, **fields):
        pass

    def rebase_compile(self, snapshot):
        pass

    def live(self):
        return {}


_NULL = _NullLedger()


def _padding_entry(real: int, padded: int) -> dict:
    total = real + padded
    return {"real": real, "padded": padded,
            "waste_fraction": (padded / total if total else 0.0)}


# ---------------------------------------------------------------------------
# run lifecycle
# ---------------------------------------------------------------------------

def start_run(kind: str, net=None):
    """Open an efficiency ledger for one run ("fit" | "resilient_fit" |
    "serving"). The ledger immediately feeds the live gauges; close it
    with :func:`end_run`. Returns a no-op ledger when disabled."""
    if not enabled():
        return _NULL
    from deeplearning4j_tpu.observability import metrics as _m
    from deeplearning4j_tpu.observability.trace import get_tracer
    ledger = EfficiencyLedger(kind)
    ledger._compile0 = _m.compile_snapshot()
    _m.update_memory_watermark()
    tracer = get_tracer()
    ledger._tracer = tracer
    ledger._dropped0 = tracer.dropped
    tracer.add_sink(ledger._on_span)
    with _lock:
        _ACTIVE.append(ledger)
    # a net that already derived FLOPs (earlier fit, same step) seeds
    # the new run so MFU is live from step one
    if net is not None:
        ledger.set_flops_per_step(getattr(net, "flops_per_step", None))
    return ledger


def end_run(ledger, status: str = "completed",
            save_to: Optional[str] = None) -> Optional[RunReport]:
    """Close a ledger opened by :func:`start_run` and build its
    RunReport (also kept as :func:`last_report` for post-run scrapes).
    ``save_to``/``DL4J_TPU_RUN_REPORT_DIR`` write the JSON artifact."""
    global _LAST_REPORT
    if ledger is None or isinstance(ledger, _NullLedger) or ledger.closed:
        return None
    from deeplearning4j_tpu.observability import metrics as _m
    _m.update_memory_watermark()
    if ledger._tracer is not None:
        ledger._tracer.remove_sink(ledger._on_span)
    report = ledger._finish(status)
    ledger._closed = True
    with _lock:
        try:
            _ACTIVE.remove(ledger)
        except ValueError:
            pass
        _LAST_REPORT = report
    path = save_to
    if path is None:
        out_dir = os.environ.get("DL4J_TPU_RUN_REPORT_DIR")
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"run_report_{ledger.kind}_{int(time.time())}.json")
    if path:
        try:
            report.save(path)
        except OSError:
            pass
    return report


def current_ledger() -> Optional[EfficiencyLedger]:
    """The innermost active ledger (live gauges read it)."""
    with _lock:
        return _ACTIVE[-1] if _ACTIVE else None


def last_report() -> Optional[RunReport]:
    with _lock:
        return _LAST_REPORT


# ---------------------------------------------------------------------------
# runtime feeding (fit loops / batcher / datapipe call these)
# ---------------------------------------------------------------------------

def observe_steps(n: int = 1) -> None:
    """Count n dispatched training steps: feeds every active ledger AND
    the runtime ``dl4j_fit_steps_total`` counter (one call site per
    dispatch — a chunked ``lax.scan`` dispatch of k batches counts k)."""
    from deeplearning4j_tpu.observability import metrics as _m
    _m.observe_step(n)
    with _lock:
        active = list(_ACTIVE)
    for ledger in active:
        ledger.observe_steps(n)


def observe_flops(flops: Optional[float]) -> None:
    if not flops:
        return
    with _lock:
        active = list(_ACTIVE)
    for ledger in active:
        ledger.set_flops_per_step(flops)


def record_padding(source: str, real: int, padded: int) -> None:
    """Padding-waste accounting: ``real`` productive rows/cells vs
    ``padded`` filler in the same device op (serving bucket forwards,
    bucket_batch collation)."""
    if padded < 0:
        padded = 0
    with _lock:
        active = list(_ACTIVE)
    for ledger in active:
        ledger.record_padding(source, real, padded)


# ---------------------------------------------------------------------------
# live gauges (registered by install_runtime_metrics)
# ---------------------------------------------------------------------------

def live_snapshot() -> dict:
    """The /api/goodput payload: the active ledger's live view, or the
    last finished report (tagged by ``source``)."""
    ledger = current_ledger()
    if ledger is not None:
        out = ledger.live()
        out["phases"] = ledger.phase_totals()
        out["source"] = "live"
        return out
    report = last_report()
    if report is not None:
        out = report.to_dict()
        out["source"] = "last_report"
        return out
    return {"source": "none"}


def goodput_collector() -> list:
    """Render-time collector for the ``dl4j_goodput_*`` / ``dl4j_mfu``
    families — reads the active ledger (live) or the last report, so a
    scrape right after ``fit`` returns still sees the run."""
    from deeplearning4j_tpu.observability.metrics import MetricFamily
    ledger = current_ledger()
    if ledger is not None:
        snap = ledger.live()
        phases = ledger.phase_totals()
    else:
        report = last_report()
        if report is None:
            return []
        snap = report.to_dict()
        phases = report.phases
    L = {"run": snap.get("kind", "unknown")}
    fams = [
        MetricFamily("dl4j_run_wall_seconds", "gauge",
                     "Wall-clock seconds of the current (or last) "
                     "instrumented run").add(snap.get("wall_s") or 0.0, L),
    ]
    gp = snap.get("goodput_fraction")
    fams.append(MetricFamily(
        "dl4j_goodput_fraction", "gauge",
        "Productive device seconds (device_step/device_compute) over "
        "total wall seconds for the current or last run"
        ).add(gp if gp is not None else 0.0, L))
    fps = snap.get("flops_per_second")
    if fps is not None:
        fams.append(MetricFamily(
            "dl4j_flops_per_second", "gauge",
            "Achieved FLOP/s (auto-derived per-step FLOPs x steps / "
            "wall)").add(fps, L))
    mfu = snap.get("mfu")
    if mfu is not None:
        fams.append(MetricFamily(
            "dl4j_mfu", "gauge",
            "Model FLOPs utilization: achieved FLOP/s over device peak "
            "(PEAK_FLOPS table or DL4J_TPU_PEAK_FLOPS)").add(mfu, L))
    if phases:
        fam = MetricFamily(
            "dl4j_goodput_phase_seconds", "gauge",
            "Wall-time ledger: cumulative seconds per traced phase "
            "over the current or last run")
        for name, ent in phases.items():
            fam.add(round(ent["seconds"], 6), {**L, "phase": name})
        fams.append(fam)
    padding = snap.get("padding") or {}
    if padding:
        fam = MetricFamily(
            "dl4j_padding_waste_fraction", "gauge",
            "Padded rows/cells over total per padding source (serving "
            "bucket ladder, datapipe bucket_batch)")
        for source, ent in padding.items():
            fam.add(ent["waste_fraction"], {**L, "source": source})
        fams.append(fam)
    return fams
