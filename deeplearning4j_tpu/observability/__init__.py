"""Unified observability core: span tracing + cross-runtime metrics.

``trace`` answers "where did step N spend its time" (bounded-ring span
tracer, Chrome-trace/JSONL export, per-thread Perfetto lanes);
``metrics`` is the single registry every runtime feeds (Prometheus text
exposition + JSON snapshot). See OBSERVABILITY.md.
"""

from deeplearning4j_tpu.observability.trace import (  # noqa: F401
    Span, Tracer, get_tracer, set_tracer, span, trace_span,
    trace_timeline_component, export_trace_html, span_color,
)
from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    MetricFamily, MetricsRegistry, get_registry, set_registry,
    install_runtime_metrics, observe_step, observe_dispatch_lag,
    compile_stats,
)

__all__ = [
    "Span", "Tracer", "get_tracer", "set_tracer", "span", "trace_span",
    "trace_timeline_component", "export_trace_html", "span_color",
    "MetricFamily", "MetricsRegistry", "get_registry", "set_registry",
    "install_runtime_metrics", "observe_step", "observe_dispatch_lag",
    "compile_stats",
]
