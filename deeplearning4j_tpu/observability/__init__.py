"""Unified observability core: span tracing + cross-runtime metrics +
goodput attribution.

``trace`` answers "where did step N spend its time" (bounded-ring span
tracer, Chrome-trace/JSONL export, per-thread Perfetto lanes);
``metrics`` is the single registry every runtime feeds (Prometheus text
exposition + JSON snapshot); ``goodput`` turns both into efficiency
accounting — a per-run wall-time ledger, live MFU/goodput gauges with
auto-derived FLOPs, padding-waste fractions, and the RunReport JSON
artifact that scripts/check_budgets.py gates CI on; ``distributed``
extends the plane across processes — stable run/instance identity,
X-DL4J-Trace-Id propagation, metrics federation with fleet rollups and
the health scoreboard; ``flightrec`` is the crash flight recorder
flushed on SIGTERM/NaN/preemption/crash. See OBSERVABILITY.md.
"""

from deeplearning4j_tpu.observability.trace import (  # noqa: F401
    Span, Tracer, get_tracer, set_tracer, span, trace_span,
    trace_timeline_component, export_trace_html, span_color,
)
from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    MetricFamily, MetricsRegistry, get_registry, set_registry,
    install_runtime_metrics, observe_step, observe_rate,
    observe_dispatch_lag, compile_stats, update_memory_watermark,
    memory_watermark_bytes,
)
from deeplearning4j_tpu.observability.goodput import (  # noqa: F401
    EfficiencyLedger, RunReport, start_run, end_run, current_ledger,
    last_report, record_padding, live_snapshot, goodput_collector,
)
from deeplearning4j_tpu.observability.distributed import (  # noqa: F401
    MetricsFederation, ProcessIdentity, TRACE_HEADER, bump_incarnation,
    export_snapshot, get_identity, new_trace_id, push_snapshot,
    reset_identity, set_identity, stamp_run_marker,
)
from deeplearning4j_tpu.observability.flightrec import (  # noqa: F401
    FlightRecorder, get_flight_recorder, install_flight_recorder,
    uninstall_flight_recorder,
)

__all__ = [
    "Span", "Tracer", "get_tracer", "set_tracer", "span", "trace_span",
    "trace_timeline_component", "export_trace_html", "span_color",
    "MetricFamily", "MetricsRegistry", "get_registry", "set_registry",
    "install_runtime_metrics", "observe_step", "observe_rate",
    "observe_dispatch_lag", "compile_stats", "update_memory_watermark",
    "memory_watermark_bytes",
    "EfficiencyLedger", "RunReport", "start_run", "end_run",
    "current_ledger", "last_report", "record_padding", "live_snapshot",
    "goodput_collector",
    "MetricsFederation", "ProcessIdentity", "TRACE_HEADER",
    "bump_incarnation", "export_snapshot", "get_identity", "new_trace_id",
    "push_snapshot", "reset_identity", "set_identity", "stamp_run_marker",
    "FlightRecorder", "get_flight_recorder", "install_flight_recorder",
    "uninstall_flight_recorder",
]
