"""Declarative SLOs with sliding-window attainment and burn rate.

ROADMAP item 1 (the SLO-aware traffic engine) needs an *objective* to
steer by: "p99 under flash crowd" only means something relative to a
target, and an autoscaler that cannot answer "how fast am I spending my
error budget" can only react to raw gauges. This module is the SRE
error-budget layer over the signals the serving tier already emits:

- An :class:`SLO` is a declaration — ``SLO("predict_p99",
  metric="latency_p99_ms", objective=0.99, bound=250.0)`` reads "in 99%
  of observation slices, predict p99 stays at or under 250 ms";
  ``SLO("availability", metric="availability", objective=0.999)`` reads
  "99.9% of concluded requests succeed".
- The :class:`SLOEngine` ingests ``ServingStats.snapshot()`` dicts (or
  per-instance federation rows) and keeps timestamped good/total
  observations per SLO in a bounded ring, evaluated over several
  sliding windows at once (multi-window burn alerting needs both the
  fast window that trips pages and the slow one that filters blips).
- Exports ride everything the registry already has: :meth:`attach`
  registers a render-time collector producing the
  ``dl4j_slo_attainment`` / ``dl4j_slo_burn_rate`` /
  ``dl4j_slo_budget_remaining`` gauge families labeled ``{slo,
  window}`` — JSON ``/metrics``, Prometheus text, and the federation
  push wire all see them for free — and :meth:`report` produces the
  JSON blob ``ModelServer.stop()`` stamps onto the drain RunReport's
  ``slo`` field.

The math (per SLO, per window): ``attainment = good / total`` over the
observations inside the window; the error budget is ``1 − objective``;
``burn_rate = (1 − attainment) / (1 − objective)`` — 1.0 means failures
arrive exactly at the sustainable rate, N means the budget for this
window burns N× too fast; ``budget_remaining = 1 − burn_rate`` (how
much of the window's budget is left at the observed failure rate —
negative once overspent, deliberately unclamped so a gate can see *how*
overspent). The clock is injectable so every one of these numbers is
pinnable in tests without sleeping.

Two metric modes:

- ``metric="availability"`` — request-ratio mode. Good/total come from
  *cumulative counter deltas* between successive ingests per source:
  ``total = Δrequests + Δerrors + Δtimeouts``, ``good = Δrequests``
  (accepted, successfully answered requests; 503 admission rejections
  are intentional load shedding and stay out of the ratio — shedding
  under backpressure is the system working, not failing). A counter
  going backwards (process restart) is treated as a reset, the new
  value standing as the delta.
- any numeric metric with ``bound`` set — threshold mode. Each ingest
  contributes ONE observation slice: good iff the sampled value is at
  or under the bound. This is time-slice attainment (the fraction of
  scrape intervals in which the percentile honored its target), the
  standard shape for latency SLOs computed from pre-aggregated
  percentiles.

See OBSERVABILITY.md "Request tracing & SLOs".
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["SLO", "SLOEngine", "DEFAULT_WINDOWS_S", "default_serving_slos"]

#: evaluation windows (seconds): fast page-trip window, mid sanity
#: window, slow budget window — the classic multi-window burn setup.
DEFAULT_WINDOWS_S = (60.0, 300.0, 3600.0)


@dataclass(frozen=True)
class SLO:
    """One declared objective.

    ``metric`` is either the literal ``"availability"`` (request-ratio
    mode) or the name of a numeric field to resolve out of ingested
    snapshots (threshold mode, requires ``bound``): a top-level
    snapshot key, the shorthand ``latency_pNN_ms`` (resolved through
    the snapshot's ``latency_ms`` percentile dict), or a dotted path
    like ``"latency_ms.p99"``. ``objective`` is the target attainment
    fraction in (0, 1]; ``window_s`` names the SLO's *primary* window —
    the one :meth:`SLOEngine.report` surfaces as headline numbers
    (every configured window is still evaluated and exported)."""

    name: str
    metric: str
    objective: float
    window_s: float = 3600.0
    bound: Optional[float] = None

    def __post_init__(self):
        if not 0.0 < self.objective <= 1.0:
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1], "
                f"got {self.objective}")
        if self.metric != "availability" and self.bound is None:
            raise ValueError(
                f"SLO {self.name!r}: threshold metric {self.metric!r} "
                f"requires a bound")


def default_serving_slos(p99_bound_ms: float = 500.0) -> List[SLO]:
    """The stock serving pair: availability ≥ 99.9% and predict p99 at
    or under *p99_bound_ms* in 99% of observation slices."""
    return [
        SLO("availability", metric="availability", objective=0.999,
            window_s=3600.0),
        SLO("predict_p99", metric="latency_p99_ms", objective=0.99,
            window_s=3600.0, bound=float(p99_bound_ms)),
    ]


def _resolve_metric(snapshot: dict, metric: str) -> Optional[float]:
    """Pull one numeric value out of a ServingStats-shaped snapshot
    (top-level key, ``latency_pNN_ms`` shorthand, or dotted path);
    None when absent — an absent sample is no observation, never a
    failure."""
    v = snapshot.get(metric)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    if (metric.startswith("latency_p") and metric.endswith("_ms")
            and isinstance(snapshot.get("latency_ms"), dict)):
        v = snapshot["latency_ms"].get(metric[len("latency_"):-len("_ms")])
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    if "." in metric:
        node = snapshot
        for part in metric.split("."):
            if not isinstance(node, dict):
                return None
            node = node.get(part)
        if isinstance(node, (int, float)) and not isinstance(node, bool):
            return float(node)
    return None


class SLOEngine:
    """Sliding multi-window attainment + burn-rate computation over a
    set of :class:`SLO` declarations. Thread-safe; O(1) per ingest plus
    ring pruning; ``clock`` injectable for pinned tests."""

    #: counters whose deltas define the availability ratio
    _GOOD_COUNTER = "requests_total"
    _BAD_COUNTERS = ("errors_total", "timeouts_total")

    def __init__(self, slos: Sequence[SLO], *,
                 windows: Sequence[float] = DEFAULT_WINDOWS_S,
                 clock: Callable[[], float] = time.monotonic,
                 capacity: int = 4096):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.slos: List[SLO] = list(slos)
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows:
            raise ValueError("need at least one evaluation window")
        self._clock = clock
        self._lock = threading.Lock()
        # slo name -> ring of (t, good, total) observations
        self._obs: Dict[str, deque] = {
            s.name: deque(maxlen=int(capacity)) for s in self.slos}
        # (slo name, source) -> last cumulative counter values, for
        # availability deltas per pushing instance
        self._last: Dict[tuple, Dict[str, float]] = {}
        self._registry = None
        self._collector = None

    # --------------------------------------------------------------- ingest
    def ingest(self, snapshot: dict, source: str = "local") -> None:
        """Fold one ServingStats-shaped snapshot into every SLO's ring.
        ``source`` keys the counter-delta state — pass the pushing
        instance name when feeding federation rows so N hosts' counters
        never cross-contaminate."""
        if not isinstance(snapshot, dict):
            return
        now = self._clock()
        with self._lock:
            for slo in self.slos:
                if slo.metric == "availability":
                    self._ingest_availability(slo, snapshot, source, now)
                else:
                    v = _resolve_metric(snapshot, slo.metric)
                    if v is None:
                        continue
                    self._obs[slo.name].append(
                        (now, 1 if v <= slo.bound else 0, 1))

    def _ingest_availability(self, slo: SLO, snapshot: dict,
                             source: str, now: float) -> None:
        cur = {}
        for key in (self._GOOD_COUNTER,) + self._BAD_COUNTERS:
            v = snapshot.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                return          # not a counters-bearing snapshot
            cur[key] = float(v)
        prev = self._last.get((slo.name, source))
        self._last[(slo.name, source)] = cur
        if prev is None:
            return              # first sight of this source: baseline only
        deltas = {}
        for key, v in cur.items():
            d = v - prev.get(key, 0.0)
            deltas[key] = v if d < 0 else d   # counter reset ⇒ restart
        good = deltas[self._GOOD_COUNTER]
        bad = sum(deltas[k] for k in self._BAD_COUNTERS)
        if good + bad <= 0:
            return              # idle interval: no observation
        self._obs[slo.name].append((now, good, good + bad))

    def ingest_fed_rows(self, rows) -> None:
        """Feed per-instance federation rows (each a dict carrying an
        ``instance`` tag and either serving counters at top level or
        under a ``"serving"`` key) — the aggregator-side ingest path."""
        for row in rows or ():
            if not isinstance(row, dict):
                continue
            source = str(row.get("instance") or row.get("tag") or "fed")
            snap = row.get("serving")
            if not isinstance(snap, dict):
                health = row.get("health")
                if isinstance(health, dict):
                    snap = health.get("serving")
            self.ingest(snap if isinstance(snap, dict) else row, source)

    # ------------------------------------------------------------- evaluate
    def evaluate(self) -> Dict[str, Dict[str, dict]]:
        """Per SLO, per window: attainment, burn_rate, budget_remaining
        plus the raw good/total behind them. Windows with no data
        report ``attainment=None`` (unknown ≠ failing)."""
        now = self._clock()
        with self._lock:
            rings = {name: list(ring) for name, ring in self._obs.items()}
        out: Dict[str, Dict[str, dict]] = {}
        for slo in self.slos:
            per = {}
            for w in self.windows:
                good = total = 0.0
                for (t, g, n) in rings[slo.name]:
                    if now - t <= w:
                        good += g
                        total += n
                ent: dict = {"good": round(good, 3),
                             "total": round(total, 3)}
                if total <= 0:
                    ent.update(attainment=None, burn_rate=None,
                               budget_remaining=None)
                else:
                    att = good / total
                    budget = 1.0 - slo.objective
                    if budget <= 0.0:
                        burn = 0.0 if att >= 1.0 else float("inf")
                    else:
                        burn = (1.0 - att) / budget
                    ent.update(attainment=round(att, 6),
                               burn_rate=round(burn, 4)
                               if burn != float("inf") else burn,
                               budget_remaining=round(1.0 - burn, 4)
                               if burn != float("inf") else -float("inf"))
                per[f"{int(w)}s"] = ent
            out[slo.name] = per
        return out

    def report(self) -> dict:
        """The RunReport-stampable summary: full per-window evaluation
        plus each SLO's declaration and primary-window headline."""
        ev = self.evaluate()
        slos = {}
        for slo in self.slos:
            primary = min(self.windows,
                          key=lambda w: abs(w - slo.window_s))
            head = ev[slo.name][f"{int(primary)}s"]
            slos[slo.name] = {
                "metric": slo.metric,
                "objective": slo.objective,
                "bound": slo.bound,
                "window_s": primary,
                "attainment": head["attainment"],
                "burn_rate": head["burn_rate"],
                "budget_remaining": head["budget_remaining"],
                "windows": ev[slo.name],
            }
        return {"windows_s": list(self.windows), "slos": slos}

    # -------------------------------------------------------------- exports
    def families(self):
        """The three gauge families, one sample per (slo, window) with
        data. Rendered at scrape time by the registry collector, so
        JSON, Prometheus and the federation push all agree."""
        from deeplearning4j_tpu.observability.metrics import MetricFamily

        att = MetricFamily(
            "dl4j_slo_attainment", "gauge",
            "Good observations over total in the sliding window")
        burn = MetricFamily(
            "dl4j_slo_burn_rate", "gauge",
            "Error-budget burn multiplier ((1-attainment)/(1-objective)"
            "); 1.0 = spending exactly at the sustainable rate")
        rem = MetricFamily(
            "dl4j_slo_budget_remaining", "gauge",
            "Share of the window's error budget left at the observed "
            "failure rate (negative = overspent)")
        for name, per in self.evaluate().items():
            for window, ent in per.items():
                if ent["attainment"] is None:
                    continue
                L = {"slo": name, "window": window}
                att.add(ent["attainment"], L)
                burn.add(ent["burn_rate"], L)
                rem.add(ent["budget_remaining"], L)
        return [f for f in (att, burn, rem) if f.samples]

    def attach(self, registry=None):
        """Register the gauge families as a render-time collector on
        *registry* (default: the process-global one)."""
        from deeplearning4j_tpu.observability.metrics import get_registry

        self.detach()
        reg = registry if registry is not None else get_registry()
        reg.register_collector(self.families)
        self._registry, self._collector = reg, self.families
        return reg

    def detach(self):
        reg = self._registry
        if reg is not None:
            reg.unregister_collector(self._collector)
            self._registry = self._collector = None
