"""Cross-runtime metrics registry with Prometheus text exposition.

PRs 1-3 left four telemetry islands: ServingStats (JSON snapshot),
ResilienceStats (counters), TrainingStatsCollector (phase events) and
StatsListener (UI reports). This module is the single registry they all
feed, rendered two ways: the existing JSON snapshots (unchanged, for
back-compat) and Prometheus text exposition for scrapers.

Two kinds of participants:

- **Direct instruments** — ``registry.counter(...)``/``gauge``/
  ``histogram`` families with ``.labels(...)`` children, owned by the
  registry. Used for the runtime metrics that exist nowhere else
  (XLA compile count/seconds, device memory, steps/sec, dispatch lag).
- **Collectors** — callables registered with ``register_collector``
  that return metric families at render time. ServingStats and
  ResilienceStats keep their own lock-guarded counters (their JSON
  snapshots and tests stay untouched) and attach a collector view, so
  there is one source of truth and zero double bookkeeping.

Naming follows Prometheus conventions: ``dl4j_`` prefix, ``_total``
suffix on counters, base units (seconds, bytes).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricFamily", "MetricsRegistry",
    "Sample", "get_registry", "set_registry", "sample_key",
    "install_runtime_metrics", "observe_step", "observe_dispatch_lag",
    "wants_prometheus", "PROMETHEUS_CONTENT_TYPE",
]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def wants_prometheus(accept: str, query: str = "") -> bool:
    """/metrics content negotiation: Prometheus text when the client
    asks for it (scrapers send ``Accept: text/plain`` or an openmetrics
    type, or ``?format=prometheus`` forces it); JSON otherwise — the
    pre-existing payload stays the default for ``Accept: */*``."""
    if "format=prometheus" in (query or ""):
        return True
    a = (accept or "").lower()
    return "text/plain" in a or "openmetrics" in a

_VALID_KINDS = ("counter", "gauge", "histogram")

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, float("inf"))


def _escape_label_value(v: str) -> str:
    # Exposition-format escaping: backslash, double-quote, newline.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def sample_key(name: str, labels: Optional[Dict[str, str]] = None,
               suffix: str = "") -> str:
    """The canonical identity of one sample: exactly the series string
    the exposition format renders (`name{k="escaped"}`), labels sorted,
    values exposition-escaped. Both the Prometheus renderer and the
    federation JSON wire format key samples by this, so a label value
    containing `"` or a newline can never be encoded two different ways
    on the two paths."""
    if not labels:
        return f"{name}{suffix}"
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{suffix}{{{inner}}}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


class Sample(Tuple):
    """(suffix, labels, value) — suffix is appended to the family name
    ("" for the plain sample, "_bucket"/"_sum"/"_count" for histograms)."""

    def __new__(cls, suffix: str, labels: Dict[str, str], value: float):
        return super().__new__(cls, (suffix, labels, value))

    @property
    def suffix(self):
        return self[0]

    @property
    def labels(self):
        return self[1]

    @property
    def value(self):
        return self[2]


class MetricFamily:
    """One named metric + HELP/TYPE + its samples. Collectors return
    lists of these; direct instruments render themselves into these."""

    def __init__(self, name: str, kind: str, help: str,
                 samples: Optional[List[Sample]] = None):
        if kind not in _VALID_KINDS:
            raise ValueError(f"metric kind must be one of {_VALID_KINDS}")
        self.name = name
        self.kind = kind
        self.help = help
        self.samples: List[Sample] = samples if samples is not None else []

    def add(self, value: float, labels: Optional[Dict[str, str]] = None,
            suffix: str = ""):
        self.samples.append(Sample(suffix, labels or {}, value))
        return self

    def render(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        for s in self.samples:
            lines.append(f"{sample_key(self.name, s.labels, s.suffix)} "
                         f"{_fmt_value(s.value)}")
        return "\n".join(lines)

    def to_json(self):
        if len(self.samples) == 1 and not self.samples[0].labels \
                and not self.samples[0].suffix:
            return self.samples[0].value
        return [{"labels": s.labels, "value": s.value,
                 **({"suffix": s.suffix} if s.suffix else {})}
                for s in self.samples]


class _Child:
    """One labeled child of a family; value updates are lock-guarded by
    the owning registry's lock (coarse, but these are cold-ish paths —
    the span tracer owns the per-step hot path)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0


class _CounterChild(_Child):
    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value


class _GaugeChild(_Child):
    __slots__ = ("_fn",)

    def __init__(self, lock):
        super().__init__(lock)
        self._fn = None

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]):
        """Lazily evaluated at render time (queue depths, clock-derived
        rates)."""
        self._fn = fn

    @property
    def value(self):
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count")

    def __init__(self, lock, buckets):
        self._lock = lock
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        with self._lock:
            self._sum += value
            self._count += 1
            # per-bucket counts; collect() accumulates into the
            # cumulative le-series the exposition format wants
            for i, b in enumerate(self._buckets):
                if value <= b:
                    self._counts[i] += 1
                    break


class _Family:
    def __init__(self, registry, name, kind, help, labelnames, buckets=None):
        self._registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self._children: Dict[Tuple[str, ...], object] = {}
        # Label-less families get one implicit child so counter.inc()
        # works without .labels().
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        lock = self._registry._lock
        if self.kind == "counter":
            return _CounterChild(lock)
        if self.kind == "gauge":
            return _GaugeChild(lock)
        return _HistogramChild(lock, self.buckets)

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._registry._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    # label-less convenience passthroughs
    def inc(self, amount: float = 1.0):
        self._children[()].inc(amount)

    def set(self, value: float):
        self._children[()].set(value)

    def set_function(self, fn):
        self._children[()].set_function(fn)

    def observe(self, value: float):
        self._children[()].observe(value)

    @property
    def value(self):
        return self._children[()].value

    def collect(self) -> MetricFamily:
        fam = MetricFamily(self.name, self.kind, self.help)
        with self._registry._lock:
            items = list(self._children.items())
        for key, child in items:
            labels = dict(zip(self.labelnames, key))
            if self.kind == "histogram":
                cumulative = 0
                for b, c in zip(child._buckets, child._counts):
                    cumulative += c
                    fam.add(cumulative,
                            {**labels, "le": _fmt_value(b)}, "_bucket")
                fam.add(child._sum, labels, "_sum")
                fam.add(child._count, labels, "_count")
            else:
                fam.add(child.value, labels)
        return fam


# Public aliases so isinstance/typing reads naturally downstream.
Counter = Gauge = Histogram = _Family


class MetricsRegistry:
    """The central registry: direct instrument families + render-time
    collectors, rendered as Prometheus text or a JSON snapshot."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[[], Sequence[MetricFamily]]] = []

    # ----------------------------------------------------------- instruments
    def _family(self, name, kind, help, labelnames, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}")
                return fam
            fam = _Family(self, name, kind, help, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        buckets = tuple(buckets)
        if not buckets or buckets[-1] != float("inf"):
            buckets = buckets + (float("inf"),)
        return self._family(name, "histogram", help, labelnames, buckets)

    # ------------------------------------------------------------ collectors
    def register_collector(self, fn: Callable[[], Sequence[MetricFamily]]):
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)
        return fn

    def unregister_collector(self, fn):
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    # -------------------------------------------------------------- renderers
    def collect(self) -> List[MetricFamily]:
        with self._lock:
            families = list(self._families.values())
            collectors = list(self._collectors)
        out = [f.collect() for f in families]
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:
                # A broken collector must not take down the scrape
                # endpoint; its series simply go missing.
                continue
        return out

    def render_prometheus(self) -> str:
        return "\n".join(f.render() for f in self.collect()) + "\n"

    def snapshot(self) -> dict:
        """JSON view: {name: value | [{labels, value}...]}."""
        return {f.name: f.to_json() for f in self.collect()}


# --------------------------------------------------------------------------
# process-global registry
# --------------------------------------------------------------------------

_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _GLOBAL


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests). Returns the previous
    one. Runtime metrics (compile/memory/steps) re-install themselves
    into the new registry on next touch."""
    global _GLOBAL, _RUNTIME_INSTALLED_ON
    prev, _GLOBAL = _GLOBAL, registry
    with _runtime_lock:
        _RUNTIME_INSTALLED_ON = None
    return prev


# --------------------------------------------------------------------------
# runtime metrics: XLA compile events, device memory, async-loop rates
# --------------------------------------------------------------------------
#
# Compile accounting rides jax.monitoring's event-duration stream:
# every backend compile fires '/jax/core/compile/backend_compile_duration'
# (a user-visible jit may fire several — internal jits count too, which
# is exactly what a "are we recompiling?" alarm wants). The listener is
# registered once per process; jax.monitoring has no unregister API.

_runtime_lock = threading.Lock()
# Stamped at module import — the standard Prometheus process-identity
# anchor; the federation's health scoreboard keys heartbeat age off the
# companion dl4j_heartbeat_timestamp_seconds rendered per scrape.
_PROCESS_START_TIME = time.time()
_COMPILE = {"count": 0, "seconds": 0.0}
# persistent-compilation-cache traffic (compilecache/): hits are
# executables deserialized from the cache dir instead of compiled,
# misses are fresh compiles written INTO the cache. Both stay 0 when no
# cache dir is configured — jax only emits the events while a cache is
# active, which is exactly the "is the knob on and working" signal.
_CACHE = {"hits": 0, "misses": 0}
_COMPILE_LISTENER_ON = False
_RUNTIME_INSTALLED_ON: Optional[MetricsRegistry] = None
_STEPS = {"count": 0.0, "per_sec": 0.0, "dispatch_lag_s": 0.0}
# memory high-water marks, updated on every watermark sample
# (render-time scrape, observe_rate, goodput run start/end — never on
# the per-step hot path): device key -> peak bytes_in_use seen
_MEM_PEAK: dict = {}


def _on_jax_event_duration(event: str, duration: float, **kw):
    if event.endswith("backend_compile_duration"):
        with _runtime_lock:
            _COMPILE["count"] += 1
            _COMPILE["seconds"] += duration


def _on_jax_event(event: str, **kw):
    # persistent-cache traffic: '/jax/compilation_cache/cache_hits' per
    # executable deserialized from disk, '.../cache_misses' per fresh
    # compile written into an ACTIVE cache (no cache dir -> no events)
    if event.endswith("/cache_hits"):
        with _runtime_lock:
            _CACHE["hits"] += 1
    elif event.endswith("/cache_misses"):
        with _runtime_lock:
            _CACHE["misses"] += 1


def _ensure_compile_listener():
    global _COMPILE_LISTENER_ON
    if _COMPILE_LISTENER_ON:
        return
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _on_jax_event_duration)
        monitoring.register_event_listener(_on_jax_event)
        _COMPILE_LISTENER_ON = True
    except Exception:
        pass


def _runtime_collector() -> List[MetricFamily]:
    with _runtime_lock:
        compile_count = _COMPILE["count"]
        compile_secs = _COMPILE["seconds"]
        cache_hits = _CACHE["hits"]
        cache_misses = _CACHE["misses"]
        steps = dict(_STEPS)
    fams = [
        MetricFamily("dl4j_xla_compile_total", "counter",
                     "XLA backend compiles observed via jax.monitoring"
                     ).add(compile_count),
        MetricFamily("dl4j_xla_compile_seconds_total", "counter",
                     "Cumulative XLA backend compile wall-clock seconds"
                     ).add(compile_secs),
        MetricFamily("dl4j_xla_cache_hits_total", "counter",
                     "Executables loaded from the persistent compilation "
                     "cache instead of compiled (0 when no cache dir is "
                     "configured — see compilecache.configure)"
                     ).add(cache_hits),
        MetricFamily("dl4j_xla_cache_misses_total", "counter",
                     "Fresh compiles written into the active persistent "
                     "compilation cache").add(cache_misses),
        MetricFamily("dl4j_fit_steps_total", "counter",
                     "Training steps dispatched by the fit loop"
                     ).add(steps["count"]),
        MetricFamily("dl4j_fit_steps_per_second", "gauge",
                     "Recent fit-loop dispatch rate (steps/sec)"
                     ).add(steps["per_sec"]),
        MetricFamily("dl4j_fit_dispatch_lag_seconds", "gauge",
                     "Last observed host->device dispatch lag (time the "
                     "host waited on device results at a sync point)"
                     ).add(steps["dispatch_lag_s"]),
        MetricFamily("dl4j_process_start_time_seconds", "gauge",
                     "Unix time the observability runtime was imported "
                     "(standard process-identity family)"
                     ).add(_PROCESS_START_TIME),
        MetricFamily("dl4j_heartbeat_timestamp_seconds", "gauge",
                     "Unix time of this render — liveness heartbeat; the "
                     "fleet scoreboard derives heartbeat age from it"
                     ).add(time.time()),
    ]
    try:
        from deeplearning4j_tpu.observability.distributed import get_identity
        fams.append(MetricFamily(
            "dl4j_instance_info", "gauge",
            "Process identity as labels (run_id/instance/incarnation/"
            "pid); always 1").add(1.0, get_identity().labels()))
    except Exception:
        pass
    mem = MetricFamily(
        "dl4j_device_memory_bytes", "gauge",
        "Per-device memory from jax.local_devices()[i].memory_stats(); "
        "backends that do not report (e.g. CPU) fall back to one "
        "process-wide kind=\"host_rss_bytes\" sample")
    reported = False
    try:
        import jax
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            dev = f"{d.platform}:{d.id}"
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit", "bytes_reserved"):
                if key in stats:
                    mem.add(stats[key], {"device": dev, "kind": key})
                    reported = True
    except Exception:
        pass
    if not reported:
        rss = _host_rss_bytes()
        if rss is not None:
            mem.add(rss, {"device": "process", "kind": "host_rss_bytes"})
    if mem.samples:
        fams.append(mem)
    update_memory_watermark()
    with _runtime_lock:
        peaks = dict(_MEM_PEAK)
    if peaks:
        peak_fam = MetricFamily(
            "dl4j_device_memory_peak_bytes", "gauge",
            "High-water memory mark per device: max peak_bytes_in_use "
            "from Device.memory_stats() across watermark samples; CPU "
            "falls back to the process VmHWM RSS high-water mark")
        for dev, v in sorted(peaks.items()):
            peak_fam.add(v, {"device": dev})
        fams.append(peak_fam)
    fams.extend(_trace_drop_families())
    return fams


def _trace_drop_families() -> List[MetricFamily]:
    """dl4j_trace_dropped_spans_total: ring-buffer data loss made
    visible — per evicted/sampled span name, plus the process total."""
    try:
        from deeplearning4j_tpu.observability.trace import get_tracer
        tracer = get_tracer()
        total = tracer.dropped
        by_name = tracer.dropped_spans()
    except Exception:
        return []
    if not total and not by_name:
        return []
    fam = MetricFamily(
        "dl4j_trace_dropped_spans_total", "counter",
        "Spans lost to tracer ring eviction or sampling, by span name "
        "(the 'total' label-less sample is the process-wide count)")
    fam.add(total)
    for name, n in sorted(by_name.items()):
        fam.add(n, {"span": name})
    return [fam]


def _host_rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    except OSError:
        return None
    return None


def _host_hwm_bytes() -> Optional[float]:
    """Kernel-tracked RSS high-water mark (VmHWM) — the honest host
    watermark, no sampling cadence required."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return float(line.split()[1]) * 1024.0
    except OSError:
        return None
    return None


def update_memory_watermark() -> None:
    """Fold the current device memory state into the high-water table.
    Called at scrape time, epoch boundaries and goodput run start/end —
    deliberately NOT per-step (a /proc read per step would eat the
    trace-overhead budget)."""
    reported = False
    try:
        import jax
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
            if peak is None:
                continue
            dev = f"{d.platform}:{d.id}"
            with _runtime_lock:
                if peak > _MEM_PEAK.get(dev, 0.0):
                    _MEM_PEAK[dev] = float(peak)
            reported = True
    except Exception:
        pass
    if reported:
        return
    hwm = _host_hwm_bytes() or _host_rss_bytes()
    if hwm is not None:
        with _runtime_lock:
            if hwm > _MEM_PEAK.get("process", 0.0):
                _MEM_PEAK["process"] = float(hwm)


def memory_watermark_bytes() -> Optional[float]:
    """The single-number memory watermark (max across devices) the
    RunReport records. Samples current state first."""
    update_memory_watermark()
    with _runtime_lock:
        return max(_MEM_PEAK.values()) if _MEM_PEAK else None


def install_runtime_metrics(
        registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Idempotently attach the runtime collector (compile count/seconds,
    device memory, steps/sec, dispatch lag) + the jax.monitoring compile
    listener to *registry* (default: the global one). Called by the fit
    loops and both servers, so any surfaced registry carries these."""
    global _RUNTIME_INSTALLED_ON
    reg = registry or get_registry()
    _ensure_compile_listener()
    with _runtime_lock:
        if _RUNTIME_INSTALLED_ON is reg:
            return reg
        _RUNTIME_INSTALLED_ON = reg
    reg.register_collector(_runtime_collector)
    try:  # the goodput gauges ride along wherever runtime metrics go
        from deeplearning4j_tpu.observability.goodput import goodput_collector
        reg.register_collector(goodput_collector)
    except Exception:
        pass
    return reg


def observe_step(n: int = 1, wall_s: Optional[float] = None):
    """Fit loops report dispatched steps; steps/sec derives from the
    wall-clock the caller measured for those n steps."""
    with _runtime_lock:
        _STEPS["count"] += n
        if wall_s and wall_s > 0:
            _STEPS["per_sec"] = n / wall_s


def observe_rate(n: int, wall_s: Optional[float]):
    """Update the steps/sec gauge WITHOUT advancing steps_total — the
    fit loops count steps per dispatch (k per lax.scan chunk) via
    goodput.observe_steps and report the epoch-level rate here."""
    with _runtime_lock:
        if wall_s and wall_s > 0:
            _STEPS["per_sec"] = n / wall_s


def observe_dispatch_lag(seconds: float):
    """Record the latest host->device sync wait (e.g. a score_sync)."""
    with _runtime_lock:
        _STEPS["dispatch_lag_s"] = float(seconds)


def compile_stats() -> dict:
    with _runtime_lock:
        return dict(_COMPILE)


def cache_stats() -> dict:
    """Persistent-compilation-cache traffic since process start:
    ``{"hits", "misses"}``. Both 0 unless a cache dir is configured
    (compilecache.configure / DL4J_TPU_COMPILE_CACHE) — jax only emits
    the hit/miss events while a cache is active."""
    with _runtime_lock:
        return dict(_CACHE)


def compile_snapshot() -> dict:
    """Baseline snapshot for :func:`compile_delta` — the documented
    per-run seam over the process-global compile/cache counters.

    ``_COMPILE`` and ``_CACHE`` are process-cumulative (jax.monitoring
    has no unregister, and a counter that resets under a live scrape
    would corrupt Prometheus rate()). Run-scoped numbers — what the
    goodput ledger puts in a RunReport — must therefore be DELTAS:
    snapshot at run start, subtract at run end. Nested or sequential
    ledgers each take their own snapshot, so two fits in one process
    report their own compiles, not each other's.

    Taking a snapshot also installs the jax.monitoring listener: a
    baseline is always taken BEFORE the compiles it scopes, so the
    events land in the counters even when nothing else wired metrics."""
    _ensure_compile_listener()
    with _runtime_lock:
        return {"count": _COMPILE["count"], "seconds": _COMPILE["seconds"],
                "cache_hits": _CACHE["hits"],
                "cache_misses": _CACHE["misses"]}


def compile_delta(baseline: dict) -> dict:
    """Compile/cache activity since *baseline* (a
    :func:`compile_snapshot`). Missing baseline keys count from 0, so a
    pre-PR-10 snapshot ({"count", "seconds"}) still subtracts clean."""
    now = compile_snapshot()
    return {k: (round(now[k] - baseline.get(k, 0), 6)
                if k == "seconds" else now[k] - baseline.get(k, 0))
            for k in now}


def process_start_unix() -> float:
    """Unix time this PROCESS started (kernel starttime via /proc, so
    it predates every import) — the cold-start clock's zero. Falls back
    to the module-import stamp where /proc is unavailable."""
    try:
        with open("/proc/self/stat") as f:
            after_comm = f.read().rsplit(")", 1)[1].split()
        ticks = float(after_comm[19])  # field 22: starttime
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        return time.time() - uptime + ticks / os.sysconf("SC_CLK_TCK")
    except Exception:
        return _PROCESS_START_TIME


def _monotonic() -> float:
    return time.perf_counter()
