"""Remote stats routing: N training processes -> one dashboard.

Parity: the reference decouples stats producers from the UI via
StatsStorageRouter (deeplearning4j-core api/storage/StatsStorageRouter
.java) and ships a remote poster
(deeplearning4j-ui-remote-iterationlisteners/.../RemoteFlowIterationListener
.java:42) so workers on other machines feed one Play server's remote
module. Here the router POSTs JSON reports to ui/server.py's
``/api/post`` endpoint; it quacks like a StatsStorage, so it plugs
straight into ``StatsListener(storage=RemoteStatsStorageRouter(url))`` —
exactly how a DP-2 (multi-process, parallel/distributed.py) run gives
every worker's curves to the process-0 dashboard.

Delivery is best-effort with a bounded retry queue (the reference's
remote listener is also fire-and-forget over HTTP): a dashboard restart
drops nothing up to ``max_pending`` reports, and a dead dashboard never
blocks the training loop.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Optional


class RemoteStatsStorageRouter:
    """POSTs StatsReports to a UIServer's /api/post endpoint."""

    def __init__(self, url: str, timeout: float = 2.0,
                 max_pending: int = 1000, retry_interval: float = 10.0):
        # accept ".../" or base host:port
        self.url = url.rstrip("/") + "/api/post"
        self.timeout = timeout
        self.retry_interval = retry_interval
        self._pending: deque = deque(maxlen=max_pending)
        self._last_failure: Optional[float] = None
        self._flush_lock = threading.Lock()
        self._retry_scheduled = False
        self.dropped = 0
        self.posted = 0

    # ------------------------------------------------- StatsStorage duck
    def put_update(self, report) -> None:
        self._enqueue({"type": "update", "report": report.to_dict()})

    def put_static_info(self, session_id: str, worker_id: str,
                        info: dict) -> None:
        self._enqueue({"type": "static_info", "session_id": session_id,
                       "worker_id": worker_id, "info": info})

    # ---------------------------------------------------------- delivery
    def _enqueue(self, payload: dict) -> None:
        if len(self._pending) == self._pending.maxlen:
            self.dropped += 1
        self._pending.append(payload)
        # a black-holed dashboard must not stall every training
        # iteration for the connect timeout: after a failure, buffer
        # silently and only re-probe every retry_interval seconds
        # (``flush()`` ignores the backoff for an explicit final drain)
        if (self._last_failure is None
                or time.monotonic() - self._last_failure
                >= self.retry_interval):
            # never let the training thread block behind a background
            # retry that is mid-timeout on a dead host: if the lock is
            # held, that retry (or its successor) will drain the queue
            self._flush(blocking=False)

    def flush(self) -> int:
        """Attempt delivery of everything pending; returns #delivered.
        Stops at the first failure (order-preserving). A failure with
        items still queued schedules a background retry so the queue's
        TAIL is never stranded when training stops emitting (the daemon
        timer dies with the process; an explicit final flush() remains
        the reliable end-of-run drain)."""
        return self._flush(blocking=True)

    def _flush(self, blocking: bool) -> int:
        if not self._flush_lock.acquire(blocking=blocking):
            return 0
        try:
            delivered = 0
            while self._pending:
                payload = self._pending[0]
                if not self._post(payload):
                    self._last_failure = time.monotonic()
                    self._schedule_retry()
                    break
                self._last_failure = None
                self._pending.popleft()
                delivered += 1
                self.posted += 1
            return delivered
        finally:
            self._flush_lock.release()

    def _schedule_retry(self) -> None:
        # called under _flush_lock; a plain flag (NOT Timer.is_alive —
        # the currently-EXECUTING timer's thread is alive, which would
        # suppress re-arming from within its own failed retry)
        if self._retry_scheduled:
            return
        self._retry_scheduled = True

        def fire():
            with self._flush_lock:
                self._retry_scheduled = False
            self._flush(blocking=True)

        t = threading.Timer(self.retry_interval, fire)
        t.daemon = True
        t.start()

    def _post(self, payload: dict) -> bool:
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return 200 <= r.status < 300
        except (urllib.error.URLError, OSError):
            return False

    @property
    def pending(self) -> int:
        return len(self._pending)
