"""Standalone dashboard server: ``python -m deeplearning4j_tpu.ui``.

Parity: the reference ships the UI as an executable with a port flag
(PlayUIServer.java:53, JCommander ``--uiPort``). Two ways to feed it:
- ``--file run.jsonl``: attach persisted FileStatsStorage logs (crash-
  tolerant JSONL written by a training run) — the post-mortem viewer;
- remote mode is always on: training processes post live through
  ``RemoteStatsStorageRouter(url)`` (ui/router.py).
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.ui",
        description="deeplearning4j-tpu training dashboard")
    ap.add_argument("--port", type=int, default=9000,
                    help="HTTP port (0 = ephemeral); PlayUIServer --uiPort")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--file", action="append", default=[],
                    help="attach a FileStatsStorage JSONL (repeatable)")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.ui import FileStatsStorage, UIServer
    server = UIServer.get_instance(port=args.port, host=args.host)
    for path in args.file:
        server.attach(FileStatsStorage(path))
    print(f"dashboard: {server.url}  "
          f"(POST /api/post for remote stats; Ctrl-C to stop)", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
