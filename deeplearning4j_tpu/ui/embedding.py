"""Embedding (t-SNE) publishing for the dashboard — the reference UI's
tsne module (deeplearning4j-ui-parent/.../ui/module/tsne/) rendered
TPU-native: project vectors to 2-D with plot/tsne.py and attach the
labeled scatter to a session; the dashboard's embedding tab renders it.

Works locally (any attached StatsStorage) and remotely
(RemoteStatsStorageRouter.put_static_info posts through /api/post), so a
word2vec worker can ship its vocabulary map to the cluster dashboard:

    from deeplearning4j_tpu.ui.embedding import publish_embedding
    publish_embedding(storage_or_router, "session_1",
                      w2v.lookup.syn0, vocab_labels)
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

EMBEDDING_KEY = "__embedding__"


def publish_embedding(storage, session_id: str, vectors,
                      labels: Sequence[str],
                      *, perplexity: float = 15.0, iterations: int = 300,
                      max_points: int = 2000,
                      seed: int = 0) -> np.ndarray:
    """Project ``vectors`` [n, d] to 2-D with t-SNE (d<=2 inputs are
    zero-padded and passed through verbatim) and publish {labels, xy} as
    the session's embedding. Returns the coordinates."""
    x = np.asarray(vectors, np.float32)
    labels = [str(l) for l in labels]
    if len(labels) != len(x):
        raise ValueError(f"{len(labels)} labels for {len(x)} vectors")
    if len(x) > max_points:
        x, labels = x[:max_points], labels[:max_points]
    if x.shape[1] <= 2:
        xy = np.pad(x, [(0, 0), (0, 2 - x.shape[1])])
    else:
        from deeplearning4j_tpu.plot.tsne import Tsne
        # Tsne clamps perplexity to the point count internally
        xy = np.asarray(Tsne(n_components=2, perplexity=perplexity,
                             max_iter=iterations,
                             seed=seed).fit_transform(x))
    import time
    storage.put_static_info(session_id, EMBEDDING_KEY, {
        "labels": labels,
        "xy": [[float(a), float(b)] for a, b in xy],
        # version stamp: the dashboard re-fetches/re-renders only when a
        # NEW publish lands (re-published embeddings must not be served
        # from the client cache forever)
        "version": time.time(),
    })
    return xy


def get_embedding(storages, session_id: str) -> Optional[dict]:
    """Find a published embedding for ``session_id`` across storages."""
    for s in storages:
        info = s.get_static_info(session_id, EMBEDDING_KEY)
        if info:
            return info
    return None
