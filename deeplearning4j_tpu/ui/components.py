"""Standalone UI component library: build reports from typed components,
render to self-contained HTML (inline SVG, zero external assets).

Parity: deeplearning4j-ui-parent/deeplearning4j-ui-components — the
reference's reusable report tier (api/Component.java, api/Style.java,
text/ComponentText.java, table/ComponentTable.java,
component/ComponentDiv.java, decorator/DecoratorAccordion.java,
chart/ChartLine|Scatter|Histogram|HorizontalBar|StackedArea|Timeline.java,
standalone/StaticPageUtil.java). The reference serializes components to
JSON and renders them client-side with d3; in a zero-egress TPU pod there
is no CDN, so here components render SERVER-side to inline SVG — same
component model, same composition (EvaluationTools and the distributed
training timeline both emit through it), different rendering backend.
Each component also round-trips ``to_dict``/``from_dict`` (the
ComponentObject serialization surface).
"""

from __future__ import annotations

import html as _html
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# default categorical palette (d3.schemeCategory10 — what the reference's
# client-side charts use by default)
PALETTE = ("#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
           "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf")


@dataclass
class Style:
    """Visual style (api/Style.java + the chart/text/table/div style
    subclasses, collapsed into one flat bag — px units only)."""
    width: float = 560.0
    height: float = 340.0
    margin_top: float = 28.0
    margin_bottom: float = 40.0
    margin_left: float = 50.0
    margin_right: float = 16.0
    background_color: str = "#ffffff"
    color: str = "#222222"
    font_size: float = 12.0
    stroke_width: float = 1.8
    point_size: float = 3.0
    header_color: str = "#f0f0f4"
    border_color: str = "#cccccc"

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    @classmethod
    def from_dict(cls, d):
        s = cls()
        for k, v in (d or {}).items():
            if hasattr(s, k):
                setattr(s, k, v)
        return s


class Component:
    """Base component (api/Component.java): typed, stylable, renderable."""

    component_type = "component"

    def __init__(self, style: Optional[Style] = None):
        self.style = style or Style()

    def render(self) -> str:
        raise NotImplementedError

    def _payload(self) -> dict:
        raise NotImplementedError

    def to_dict(self) -> dict:
        return {"componentType": self.component_type,
                "style": self.style.to_dict(), **self._payload()}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "Component":
        ct = d.get("componentType")
        cls = _REGISTRY.get(ct)
        if cls is None:
            raise ValueError(f"Unknown componentType '{ct}'")
        return cls._from_payload(d, Style.from_dict(d.get("style")))


# ---------------------------------------------------------------------------
# text / table / div / decorator
# ---------------------------------------------------------------------------

class ComponentText(Component):
    """text/ComponentText.java."""

    component_type = "ComponentText"

    def __init__(self, text: str, style: Optional[Style] = None):
        super().__init__(style)
        self.text = text

    def render(self) -> str:
        st = self.style
        return (f'<p style="color:{st.color};font-size:{st.font_size}px">'
                f"{_html.escape(self.text)}</p>")

    def _payload(self):
        return {"text": self.text}

    @classmethod
    def _from_payload(cls, d, style):
        return cls(d["text"], style)


class ComponentTable(Component):
    """table/ComponentTable.java: header + rows of strings."""

    component_type = "ComponentTable"

    def __init__(self, header: Sequence[str], content: Sequence[Sequence],
                 style: Optional[Style] = None, title: str = "",
                 highlight_cells: Sequence[Tuple[int, int]] = ()):
        super().__init__(style)
        self.title = title
        self.header = [str(h) for h in header]
        self.content = [[str(c) for c in row] for row in content]
        self.highlight_cells = {(int(r), int(c))
                                for r, c in highlight_cells}

    def render(self) -> str:
        st = self.style
        head = "".join(
            f'<th style="background:{st.header_color};border:1px solid '
            f'{st.border_color};padding:4px 9px">{_html.escape(h)}</th>'
            for h in self.header)
        rows = []
        for r, row in enumerate(self.content):
            cells = []
            for c, cell in enumerate(row):
                hl = ("background:#e4efe4;font-weight:600;"
                      if (r, c) in self.highlight_cells else "")
                cells.append(
                    f'<td style="{hl}border:1px solid {st.border_color};'
                    f'padding:4px 9px;text-align:right">'
                    f"{_html.escape(cell)}</td>")
            rows.append(f"<tr>{''.join(cells)}</tr>")
        title = (f"<h3>{_html.escape(self.title)}</h3>" if self.title else "")
        return (f'{title}<table style="border-collapse:collapse;'
                f'font-size:{st.font_size + 1}px;margin:8px 0">'
                f"<tr>{head}</tr>{''.join(rows)}</table>")

    def _payload(self):
        return {"title": self.title, "header": self.header,
                "content": self.content,
                "highlight": sorted(self.highlight_cells)}

    @classmethod
    def _from_payload(cls, d, style):
        return cls(d["header"], d["content"], style, d.get("title", ""),
                   d.get("highlight", ()))


class ComponentDiv(Component):
    """component/ComponentDiv.java: container composing child components."""

    component_type = "ComponentDiv"

    def __init__(self, *children: Component, style: Optional[Style] = None,
                 flex: bool = True):
        super().__init__(style)
        self.children = list(children)
        self.flex = flex

    def add(self, child: Component) -> "ComponentDiv":
        self.children.append(child)
        return self

    def render(self) -> str:
        disp = ("display:flex;flex-wrap:wrap;gap:22px;align-items:flex-start"
                if self.flex else "")
        inner = "\n".join(c.render() for c in self.children)
        return f'<div style="{disp}">{inner}</div>'

    def _payload(self):
        return {"flex": self.flex,
                "children": [c.to_dict() for c in self.children]}

    @classmethod
    def _from_payload(cls, d, style):
        kids = [Component.from_dict(c) for c in d.get("children", [])]
        return cls(*kids, style=style, flex=d.get("flex", True))


class DecoratorAccordion(Component):
    """decorator/DecoratorAccordion.java: collapsible section (native
    <details>, no JS)."""

    component_type = "DecoratorAccordion"

    def __init__(self, title: str, *children: Component,
                 default_collapsed: bool = False,
                 style: Optional[Style] = None):
        super().__init__(style)
        self.title = title
        self.children = list(children)
        self.default_collapsed = default_collapsed

    def render(self) -> str:
        inner = "\n".join(c.render() for c in self.children)
        op = "" if self.default_collapsed else " open"
        return (f"<details{op}><summary style=\"cursor:pointer;"
                f"font-weight:600\">{_html.escape(self.title)}</summary>"
                f"{inner}</details>")

    def _payload(self):
        return {"title": self.title,
                "defaultCollapsed": self.default_collapsed,
                "children": [c.to_dict() for c in self.children]}

    @classmethod
    def _from_payload(cls, d, style):
        kids = [Component.from_dict(c) for c in d.get("children", [])]
        return cls(d["title"], *kids,
                   default_collapsed=d.get("defaultCollapsed", False),
                   style=style)


# ---------------------------------------------------------------------------
# charts (chart/Chart.java subclasses)
# ---------------------------------------------------------------------------

def _nice_ticks(lo: float, hi: float, n: int = 5):
    """~n rounded tick positions covering [lo, hi]."""
    if not np.isfinite(lo) or not np.isfinite(hi):
        lo, hi = 0.0, 1.0
    if hi <= lo:
        hi = lo + 1.0
    raw = (hi - lo) / max(n, 1)
    mag = 10.0 ** np.floor(np.log10(raw))
    for m in (1, 2, 2.5, 5, 10):
        if raw <= m * mag:
            step = m * mag
            break
    t0 = np.ceil(lo / step) * step
    ticks = []
    t = t0
    while t <= hi + 1e-9 * step:
        ticks.append(round(t, 10))
        t += step
    return ticks or [lo, hi]


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1e4 or abs(v) < 1e-3:
        return f"{v:.1e}"
    return f"{v:g}"


class Chart(Component):
    """Shared axes/frame machinery (chart/Chart.java + StyleChart)."""

    def __init__(self, title: str, style: Optional[Style] = None,
                 xlabel: str = "", ylabel: str = ""):
        super().__init__(style)
        self.title = title
        self.xlabel = xlabel
        self.ylabel = ylabel

    # -- frame ------------------------------------------------------------
    def _frame(self, x_lo, x_hi, y_lo, y_hi, body: str,
               legend: Sequence[Tuple[str, str]] = (),
               x_ticks=None, y_ticks=None) -> str:
        st = self.style
        w, h = st.width, st.height
        il, it = st.margin_left, st.margin_top
        iw = w - st.margin_left - st.margin_right
        ih = h - st.margin_top - st.margin_bottom

        xt = x_ticks if x_ticks is not None else _nice_ticks(x_lo, x_hi)
        yt = y_ticks if y_ticks is not None else _nice_ticks(y_lo, y_hi)
        sx = iw / (x_hi - x_lo) if x_hi > x_lo else 1.0
        sy = ih / (y_hi - y_lo) if y_hi > y_lo else 1.0

        def X(v):
            return il + (v - x_lo) * sx

        def Y(v):
            return it + ih - (v - y_lo) * sy

        grid = []
        for v in xt:
            if x_lo <= v <= x_hi:
                grid.append(
                    f'<line x1="{X(v):.1f}" y1="{it}" x2="{X(v):.1f}" '
                    f'y2="{it + ih}" stroke="#eee"/>'
                    f'<text x="{X(v):.1f}" y="{it + ih + 15}" '
                    f'font-size="10" text-anchor="middle">{_fmt(v)}</text>')
        for v in yt:
            if y_lo <= v <= y_hi:
                grid.append(
                    f'<line x1="{il}" y1="{Y(v):.1f}" x2="{il + iw}" '
                    f'y2="{Y(v):.1f}" stroke="#eee"/>'
                    f'<text x="{il - 6}" y="{Y(v) + 3:.1f}" font-size="10" '
                    f'text-anchor="end">{_fmt(v)}</text>')
        leg = []
        lx = il + 8
        for i, (name, color) in enumerate(legend):
            leg.append(
                f'<rect x="{lx}" y="{it + 6 + 14 * i}" width="10" '
                f'height="10" fill="{color}"/>'
                f'<text x="{lx + 14}" y="{it + 15 + 14 * i}" '
                f'font-size="10">{_html.escape(name)}</text>')
        xl = (f'<text x="{il + iw / 2}" y="{h - 6}" text-anchor="middle" '
              f'font-size="11">{_html.escape(self.xlabel)}</text>'
              if self.xlabel else "")
        yl = (f'<text x="12" y="{it + ih / 2}" font-size="11" '
              f'text-anchor="middle" transform="rotate(-90 12 '
              f'{it + ih / 2})">{_html.escape(self.ylabel)}</text>'
              if self.ylabel else "")
        return (
            f'<svg width="{w:.0f}" height="{h:.0f}" '
            f'style="background:{st.background_color};border:1px solid '
            f'{st.border_color}">'
            f'<text x="{w / 2}" y="17" text-anchor="middle" font-size="13" '
            f'font-weight="600">{_html.escape(self.title)}</text>'
            f'{"".join(grid)}'
            f'<rect x="{il}" y="{it}" width="{iw}" height="{ih}" '
            f'fill="none" stroke="#999"/>'
            f"{body}{''.join(leg)}{xl}{yl}</svg>")

    def _scales(self, x_lo, x_hi, y_lo, y_hi):
        st = self.style
        iw = st.width - st.margin_left - st.margin_right
        ih = st.height - st.margin_top - st.margin_bottom
        sx = iw / (x_hi - x_lo) if x_hi > x_lo else 1.0
        sy = ih / (y_hi - y_lo) if y_hi > y_lo else 1.0
        return (lambda v: st.margin_left + (v - x_lo) * sx,
                lambda v: st.margin_top + ih - (v - y_lo) * sy)


def _series_extent(series):
    xs = np.concatenate([np.asarray(x, float) for _n, x, _y in series]) \
        if series else np.array([0.0, 1.0])
    ys = np.concatenate([np.asarray(y, float) for _n, _x, y in series]) \
        if series else np.array([0.0, 1.0])
    xs = xs[np.isfinite(xs)]
    ys = ys[np.isfinite(ys)]
    if xs.size == 0:
        xs = np.array([0.0, 1.0])
    if ys.size == 0:
        ys = np.array([0.0, 1.0])
    pad_y = 0.05 * (ys.max() - ys.min() or 1.0)
    return (float(xs.min()), float(xs.max()),
            float(ys.min() - pad_y), float(ys.max() + pad_y))


class ChartLine(Chart):
    """chart/ChartLine.java: named (x, y) series as polylines."""

    component_type = "ChartLine"

    def __init__(self, title: str, style: Optional[Style] = None, **kw):
        super().__init__(title, style, **kw)
        self.series: List[Tuple[str, list, list]] = []

    def add_series(self, name: str, x, y) -> "ChartLine":
        self.series.append((str(name), [float(v) for v in x],
                            [float(v) for v in y]))
        return self

    def render(self) -> str:
        x_lo, x_hi, y_lo, y_hi = _series_extent(self.series)
        X, Y = self._scales(x_lo, x_hi, y_lo, y_hi)
        body, legend = [], []
        for i, (name, xs, ys) in enumerate(self.series):
            color = PALETTE[i % len(PALETTE)]
            pts = " ".join(f"{X(x):.1f},{Y(y):.1f}"
                           for x, y in zip(xs, ys)
                           if np.isfinite(x) and np.isfinite(y))
            body.append(f'<polyline points="{pts}" fill="none" '
                        f'stroke="{color}" '
                        f'stroke-width="{self.style.stroke_width}"/>')
            legend.append((name, color))
        return self._frame(x_lo, x_hi, y_lo, y_hi, "".join(body),
                           legend if len(legend) > 1 else ())

    def _payload(self):
        return {"title": self.title, "xlabel": self.xlabel,
                "ylabel": self.ylabel,
                "series": [{"name": n, "x": x, "y": y}
                           for n, x, y in self.series]}

    @classmethod
    def _from_payload(cls, d, style):
        c = cls(d["title"], style, xlabel=d.get("xlabel", ""),
                ylabel=d.get("ylabel", ""))
        for s in d.get("series", []):
            c.add_series(s["name"], s["x"], s["y"])
        return c


class ChartScatter(ChartLine):
    """chart/ChartScatter.java: same series model, point marks."""

    component_type = "ChartScatter"

    def render(self) -> str:
        x_lo, x_hi, y_lo, y_hi = _series_extent(self.series)
        X, Y = self._scales(x_lo, x_hi, y_lo, y_hi)
        body, legend = [], []
        for i, (name, xs, ys) in enumerate(self.series):
            color = PALETTE[i % len(PALETTE)]
            body.extend(
                f'<circle cx="{X(x):.1f}" cy="{Y(y):.1f}" '
                f'r="{self.style.point_size}" fill="{color}" '
                f'fill-opacity="0.75"/>'
                for x, y in zip(xs, ys)
                if np.isfinite(x) and np.isfinite(y))
            legend.append((name, color))
        return self._frame(x_lo, x_hi, y_lo, y_hi, "".join(body),
                           legend if len(legend) > 1 else ())


class ChartStackedArea(Chart):
    """chart/ChartStackedArea.java: shared x, stacked named y series."""

    component_type = "ChartStackedArea"

    def __init__(self, title: str, x: Sequence[float] = (),
                 style: Optional[Style] = None, **kw):
        super().__init__(title, style, **kw)
        self.x = [float(v) for v in x]
        self.series: List[Tuple[str, list]] = []

    def add_series(self, name: str, y) -> "ChartStackedArea":
        y = [float(v) for v in y]
        if len(y) != len(self.x):
            raise ValueError(f"series '{name}' length {len(y)} != x length "
                             f"{len(self.x)}")
        self.series.append((str(name), y))
        return self

    def render(self) -> str:
        if not self.x or not self.series:
            return self._frame(0, 1, 0, 1, "")
        stack = np.zeros(len(self.x))
        tops = []
        for _name, y in self.series:
            stack = stack + np.asarray(y)
            tops.append(stack.copy())
        x_lo, x_hi = min(self.x), max(self.x)
        y_lo, y_hi = 0.0, float(stack.max() or 1.0) * 1.05
        X, Y = self._scales(x_lo, x_hi, y_lo, y_hi)
        body, legend = [], []
        prev = np.zeros(len(self.x))
        for i, ((name, _y), top) in enumerate(zip(self.series, tops)):
            color = PALETTE[i % len(PALETTE)]
            fwd = [f"{X(x):.1f},{Y(t):.1f}" for x, t in zip(self.x, top)]
            back = [f"{X(x):.1f},{Y(p):.1f}"
                    for x, p in zip(reversed(self.x), reversed(prev))]
            body.append(f'<polygon points="{" ".join(fwd + back)}" '
                        f'fill="{color}" fill-opacity="0.8"/>')
            legend.append((name, color))
            prev = top
        return self._frame(x_lo, x_hi, y_lo, y_hi, "".join(body), legend)

    def _payload(self):
        return {"title": self.title, "x": self.x,
                "series": [{"name": n, "y": y} for n, y in self.series]}

    @classmethod
    def _from_payload(cls, d, style):
        c = cls(d["title"], d.get("x", ()), style)
        for s in d.get("series", []):
            c.add_series(s["name"], s["y"])
        return c


class ChartHistogram(Chart):
    """chart/ChartHistogram.java: explicit (low, high, count) bins."""

    component_type = "ChartHistogram"

    def __init__(self, title: str, style: Optional[Style] = None, **kw):
        super().__init__(title, style, **kw)
        self.bins: List[Tuple[float, float, float]] = []

    def add_bin(self, low: float, high: float, count: float):
        self.bins.append((float(low), float(high), float(count)))
        return self

    @classmethod
    def of(cls, values, n_bins: int = 30, title: str = "histogram",
           style: Optional[Style] = None):
        v = np.asarray(values, float).ravel()
        v = v[np.isfinite(v)]
        if v.size == 0:
            return cls(title, style)
        counts, edges = np.histogram(v, bins=n_bins)
        c = cls(title, style)
        for i, n in enumerate(counts):
            c.add_bin(edges[i], edges[i + 1], float(n))
        return c

    def render(self) -> str:
        if not self.bins:
            return self._frame(0, 1, 0, 1, "")
        x_lo = min(b[0] for b in self.bins)
        x_hi = max(b[1] for b in self.bins)
        y_hi = max(b[2] for b in self.bins) * 1.05 or 1.0
        X, Y = self._scales(x_lo, x_hi, 0.0, y_hi)
        body = [
            f'<rect x="{X(lo):.1f}" y="{Y(n):.1f}" '
            f'width="{max(X(hi) - X(lo) - 0.5, 0.5):.1f}" '
            f'height="{max(Y(0) - Y(n), 0):.1f}" fill="{PALETTE[0]}" '
            f'fill-opacity="0.85"/>'
            for lo, hi, n in self.bins]
        return self._frame(x_lo, x_hi, 0.0, y_hi, "".join(body))

    def _payload(self):
        return {"title": self.title,
                "bins": [{"low": a, "high": b, "count": c}
                         for a, b, c in self.bins]}

    @classmethod
    def _from_payload(cls, d, style):
        c = cls(d["title"], style)
        for b in d.get("bins", []):
            c.add_bin(b["low"], b["high"], b["count"])
        return c


class ChartHorizontalBar(Chart):
    """chart/ChartHorizontalBar.java: labeled horizontal bars."""

    component_type = "ChartHorizontalBar"

    def __init__(self, title: str, style: Optional[Style] = None, **kw):
        super().__init__(title, style, **kw)
        self.values: List[Tuple[str, float]] = []

    def add_value(self, label: str, value: float):
        self.values.append((str(label), float(value)))
        return self

    def render(self) -> str:
        if not self.values:
            return self._frame(0, 1, 0, 1, "")
        st = self.style
        # both bounds clamp through 0 so all-negative values keep
        # x_lo < 0 <= x_hi (bars grow leftward from the zero line)
        x_hi = max(0.0, max(v for _l, v in self.values) * 1.05)
        x_lo = min(0.0, min(v for _l, v in self.values) * 1.05)
        if x_hi == x_lo:  # all zeros
            x_hi = 1.0
        it = st.margin_top
        ih = st.height - st.margin_top - st.margin_bottom
        bar_h = ih / len(self.values)
        X, _ = self._scales(x_lo, x_hi, 0.0, 1.0)
        body = []
        for i, (label, v) in enumerate(self.values):
            y = it + i * bar_h
            color = PALETTE[i % len(PALETTE)]
            body.append(
                f'<rect x="{X(min(0.0, v)):.1f}" y="{y + 2:.1f}" '
                f'width="{abs(X(v) - X(0)):.1f}" '
                f'height="{max(bar_h - 4, 1):.1f}" fill="{color}" '
                f'fill-opacity="0.85"/>'
                f'<text x="{st.margin_left - 6}" '
                f'y="{y + bar_h / 2 + 3:.1f}" font-size="10" '
                f'text-anchor="end">{_html.escape(label)}</text>')
        return self._frame(x_lo, x_hi, 0.0, 1.0, "".join(body), y_ticks=[])

    def _payload(self):
        return {"title": self.title,
                "values": [{"label": l, "value": v}
                           for l, v in self.values]}

    @classmethod
    def _from_payload(cls, d, style):
        c = cls(d["title"], style)
        for v in d.get("values", []):
            c.add_value(v["label"], v["value"])
        return c


class ChartTimeline(Chart):
    """chart/ChartTimeline.java: lanes of colored [start, end) entries —
    the Spark training-phase timeline surface
    (spark/stats/StatsUtils.java exportStatsAsHtml renders EventStats
    through exactly this chart)."""

    component_type = "ChartTimeline"

    def __init__(self, title: str, style: Optional[Style] = None, **kw):
        super().__init__(title, style, **kw)
        # lane -> list of (start, end, label, color)
        self.lanes: List[Tuple[str, List[Tuple[float, float, str, str]]]] = []

    def add_lane(self, name: str,
                 entries: Sequence[Tuple[float, float, str, str]]):
        self.lanes.append((str(name),
                           [(float(s), float(e), str(l), str(c))
                            for s, e, l, c in entries]))
        return self

    def render(self) -> str:
        if not self.lanes:
            return self._frame(0, 1, 0, 1, "")
        st = self.style
        all_entries = [e for _n, es in self.lanes for e in es]
        if not all_entries:
            return self._frame(0, 1, 0, 1, "")
        x_lo = min(e[0] for e in all_entries)
        x_hi = max(e[1] for e in all_entries) or 1.0
        it = st.margin_top
        ih = st.height - st.margin_top - st.margin_bottom
        lane_h = ih / len(self.lanes)
        X, _ = self._scales(x_lo, x_hi, 0.0, 1.0)
        body = []
        for i, (name, entries) in enumerate(self.lanes):
            y = it + i * lane_h
            body.append(
                f'<text x="{st.margin_left - 6}" '
                f'y="{y + lane_h / 2 + 3:.1f}" font-size="10" '
                f'text-anchor="end">{_html.escape(name)}</text>')
            for s, e, label, color in entries:
                wdt = max(X(e) - X(s), 0.8)
                body.append(
                    f'<rect x="{X(s):.1f}" y="{y + 3:.1f}" '
                    f'width="{wdt:.1f}" height="{max(lane_h - 6, 2):.1f}" '
                    f'fill="{color}" fill-opacity="0.85">'
                    f'<title>{_html.escape(label)} '
                    f'[{_fmt(s)}, {_fmt(e)}]</title></rect>')
        return self._frame(x_lo, x_hi, 0.0, 1.0, "".join(body), y_ticks=[])

    def _payload(self):
        return {"title": self.title,
                "lanes": [{"name": n,
                           "entries": [{"start": s, "end": e, "label": l,
                                        "color": c}
                                       for s, e, l, c in es]}
                          for n, es in self.lanes]}

    @classmethod
    def _from_payload(cls, d, style):
        c = cls(d["title"], style)
        for lane in d.get("lanes", []):
            c.add_lane(lane["name"],
                       [(e["start"], e["end"], e["label"], e["color"])
                        for e in lane.get("entries", [])])
        return c


_REGISTRY: Dict[str, type] = {
    c.component_type: c
    for c in (ComponentText, ComponentTable, ComponentDiv,
              DecoratorAccordion, ChartLine, ChartScatter, ChartHistogram,
              ChartHorizontalBar, ChartStackedArea, ChartTimeline)
}


# ---------------------------------------------------------------------------
# standalone page rendering (standalone/StaticPageUtil.java)
# ---------------------------------------------------------------------------

_PAGE_STYLE = """
body{font-family:system-ui,sans-serif;margin:18px;color:#222}
h2{color:#1a237e} h3{margin:18px 0 6px;font-size:15px;color:#444}
details{margin:10px 0}
"""


def render_components_to_html(components: Sequence[Component],
                              title: str = "Report") -> str:
    """StaticPageUtil.renderHTML parity: one self-contained page."""
    body = "\n".join(c.render() for c in components)
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(title)}</title>"
            f"<style>{_PAGE_STYLE}</style></head><body>"
            f"<h2>{_html.escape(title)}</h2>{body}</body></html>")


def render_components_to_file(components: Sequence[Component], path: str,
                              title: str = "Report") -> None:
    with open(path, "w") as f:
        f.write(render_components_to_html(components, title))
