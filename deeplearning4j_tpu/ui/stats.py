"""Stats collection.

Parity: deeplearning4j-ui-model stats/BaseStatsListener.java (:287
iterationDone gathers score, parameter/update histograms and
mean-magnitudes, memory + timing) with StatsUpdateConfiguration-style
knobs. One divergence, by design: the reference reads gradients off the
stateful layers; here forward+backward+update fuse into one XLA step, so
the listener records parameter UPDATE statistics (param delta between
iterations — what LayerUpdater applied), which is what the reference's
update charts show. Collection is O(params) host work — use
``frequency`` to sample.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import jax
import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


@dataclass
class StatsReport:
    session_id: str
    worker_id: str
    timestamp: float
    iteration: int
    epoch: int
    score: float
    iteration_ms: Optional[float] = None
    examples_per_sec: Optional[float] = None
    memory_rss_mb: Optional[float] = None
    param_stats: Dict[str, dict] = field(default_factory=dict)
    update_stats: Dict[str, dict] = field(default_factory=dict)
    activation_stats: Dict[str, dict] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "timestamp": self.timestamp,
            "iteration": self.iteration,
            "epoch": self.epoch,
            "score": self.score,
            "iteration_ms": self.iteration_ms,
            "examples_per_sec": self.examples_per_sec,
            "memory_rss_mb": self.memory_rss_mb,
            "param_stats": self.param_stats,
            "update_stats": self.update_stats,
            "activation_stats": self.activation_stats,
        }

    @staticmethod
    def from_dict(d: dict) -> "StatsReport":
        return StatsReport(**d)


def _array_stats(a: np.ndarray, histograms: bool, bins: int) -> dict:
    if a.size == 0:
        # zero-size tensors (scalar-free layers, an empty probe output)
        # must produce a well-formed report, not a ValueError out of
        # a.min()/np.histogram mid-training
        out = {"mean": None, "std": None, "mean_magnitude": None,
               "min": None, "max": None}
        if histograms:
            out["histogram"] = {"counts": [], "min": None, "max": None}
        return out
    out = {
        "mean": float(a.mean()),
        "std": float(a.std()),
        "mean_magnitude": float(np.abs(a).mean()),
        "min": float(a.min()),
        "max": float(a.max()),
    }
    if histograms:
        counts, edges = np.histogram(a, bins=bins)
        out["histogram"] = {"counts": counts.tolist(),
                            "min": float(edges[0]), "max": float(edges[-1])}
    return out


def _rss_mb() -> Optional[float]:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        return None
    return None


class StatsListener(TrainingListener):
    """Collects a StatsReport every ``frequency`` iterations and routes it
    to a StatsStorage (BaseStatsListener parity).

    ``net.score_value`` is only materialized (device sync) on the report
    cadence — between reports only wall-clock timing is recorded, keeping
    the lazy-score fit loop un-stalled."""

    # real per-step wall-clock (iteration_ms) + pre-report param snapshots
    needs_per_iteration = True

    def __init__(self, storage, frequency: int = 10, histograms: bool = True,
                 bins: int = 20, session_id: Optional[str] = None,
                 worker_id: str = "worker_0", collect_updates: bool = True,
                 activation_probe=None):
        """``activation_probe``: optional features array (or list of
        arrays for graphs); when given, each sampled iteration runs a
        forward pass on it and records per-layer ACTIVATION statistics —
        the reference's activation histograms (BaseStatsListener gathers
        them from stateful layers; the functional step stores none, so an
        explicit probe batch is the honest equivalent; keep it small)."""
        self.storage = storage
        self.frequency = max(1, frequency)
        self.histograms = histograms
        self.bins = bins
        self.session_id = session_id or f"session_{int(time.time())}"
        self.worker_id = worker_id
        self.collect_updates = collect_updates
        self.activation_probe = activation_probe
        self._probe_warned = False
        self._model_posted = False
        self._prev_params = None
        self._last_time = None

    def _post_model_info(self, net):
        """Once per run: describe the model topology for the dashboard's
        flow view (the reference UI's flow/model tabs render exactly
        this: layer boxes with types/param counts, wired by the graph)."""
        if self._model_posted:
            return
        self._model_posted = True
        try:
            layers = []
            params = net.params or {}
            is_graph = hasattr(net, "topo")
            if is_graph:
                for name in net.topo:
                    kind = net.vertex_kind.get(name)
                    if kind == "layer":
                        ltype = type(net._layer_by_name[name]).__name__
                    else:
                        ltype = type(net._resolved_confs[name]).__name__
                    n_params = int(sum(
                        np.asarray(v).size
                        for v in params.get(name, {}).values()))
                    layers.append({
                        "name": str(name), "type": ltype,
                        "params": n_params,
                        "inputs": [str(i) for i in
                                   net.conf.vertex_inputs.get(name, [])],
                    })
                inputs = [str(i) for i in net.conf.network_inputs]
            else:
                prev = None
                for layer in net.layers:
                    n_params = int(sum(
                        np.asarray(v).size
                        for v in params.get(layer.name, {}).values()))
                    layers.append({
                        "name": str(layer.name),
                        "type": type(layer).__name__,
                        "params": n_params,
                        "inputs": [prev] if prev else [],
                    })
                    prev = str(layer.name)
                inputs = []
            self.storage.put_static_info(self.session_id, self.worker_id, {
                "model": {"layers": layers, "network_inputs": inputs},
            })
        except Exception as e:
            # must never break training — but must be DIAGNOSABLE (the
            # flow tab silently missing is a debugging dead end)
            import warnings
            warnings.warn(
                f"StatsListener model-topology post failed "
                f"({type(e).__name__}: {e}) — the dashboard flow view "
                f"will be empty for this run", UserWarning)

    def _activation_stats(self, net) -> Dict[str, dict]:
        if self.activation_probe is None:
            return {}
        probe = self.activation_probe
        try:
            if isinstance(probe, (list, tuple)):   # ComputationGraph
                acts = net.feed_forward(*probe)
            else:
                acts = net.feed_forward(probe)
        except Exception as e:
            # a misconfigured probe (wrong feature width, wrong arity)
            # must be DIAGNOSABLE, not silently absent from the dashboard
            if not self._probe_warned:
                import warnings
                warnings.warn(
                    f"StatsListener activation_probe forward failed "
                    f"({type(e).__name__}: {e}) — activation stats "
                    f"disabled for this run", UserWarning)
                self._probe_warned = True
            return {}
        if isinstance(acts, dict):
            # graph feed_forward seeds the dict with the raw INPUTS —
            # exclude them, they are probe data, not layer activations
            inputs = set(getattr(getattr(net, "conf", None),
                                 "network_inputs", ()) or ())
            named = [(k, v) for k, v in acts.items() if k not in inputs]
        else:
            names = [getattr(l, "name", f"layer_{i}")
                     for i, l in enumerate(net.layers)]
            named = list(zip(names, acts))
        return {str(k): _array_stats(np.asarray(v), self.histograms,
                                     self.bins)
                for k, v in named}

    def iteration_done(self, net, iteration, epoch):
        self._post_model_info(net)
        now = time.perf_counter()
        iter_ms = None
        if self._last_time is not None:
            iter_ms = 1000.0 * (now - self._last_time)
        self._last_time = now
        if iteration % self.frequency != 0:
            if (self.collect_updates
                    and (iteration + 1) % self.frequency == 0):
                # host-copy params one iteration before the next sample so
                # the update delta spans exactly one step (a host copy is
                # required: the jitted step donates the old device buffers)
                self._prev_params = jax.tree_util.tree_map(
                    np.asarray, net.params)
            return
        flat = jax.tree_util.tree_flatten_with_path(net.params)[0]
        param_stats, update_stats = {}, {}
        for kp, leaf in flat:
            key = jax.tree_util.keystr(kp)
            a = np.asarray(leaf)
            param_stats[key] = _array_stats(a, self.histograms, self.bins)
        if self.collect_updates and self._prev_params is not None:
            prev = jax.tree_util.tree_flatten_with_path(self._prev_params)[0]
            for (kp, leaf), (_, prev_leaf) in zip(flat, prev):
                key = jax.tree_util.keystr(kp)
                delta = np.asarray(leaf) - np.asarray(prev_leaf)
                update_stats[key] = _array_stats(delta, self.histograms,
                                                 self.bins)
        if self.collect_updates and self.frequency == 1:
            self._prev_params = jax.tree_util.tree_map(np.asarray, net.params)
        else:
            self._prev_params = None
        eps = None
        n = getattr(net, "last_batch_examples", 0)
        if iter_ms and n:
            eps = 1000.0 * n / iter_ms
        report = StatsReport(
            session_id=self.session_id,
            worker_id=self.worker_id,
            timestamp=time.time(),
            iteration=iteration,
            epoch=epoch,
            score=float(net.score_value),
            iteration_ms=iter_ms,
            examples_per_sec=eps,
            memory_rss_mb=_rss_mb(),
            param_stats=param_stats,
            update_stats=update_stats,
            activation_stats=self._activation_stats(net),
        )
        self.storage.put_update(report)
