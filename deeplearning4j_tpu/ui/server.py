"""Training dashboard web server.

Parity: deeplearning4j-play PlayUIServer.java (:53, singleton
``getInstance`` :24, ``--uiPort`` flag) + the train-module charts. The
reference runs a Play 2.x app polling StatsStorage; here a stdlib
ThreadingHTTPServer serves a self-contained HTML/JS page (no external
assets — works in zero-egress environments) that polls JSON endpoints
backed by any attached ``BaseStatsStorage``:

- ``GET /``                                    dashboard page
- ``GET /api/sessions``                        session/worker inventory
- ``GET /api/updates?session=S[&after=T]``     score/timing series
- ``GET /api/model?session=S``                 latest param/update stats
- ``GET /metrics``                             unified registry (JSON;
  Prometheus text with ``Accept: text/plain`` or ``?format=prometheus``)
- ``GET /api/trace``                           Chrome trace-event JSON of
  the process-global span tracer (loadable in Perfetto)
- ``GET /api/traces``                          trace ids with pushed
  request-scoped spans (the waterfall panel's inventory)
- ``GET /api/trace/<id>``                      stitched cross-process
  waterfall for one request trace (OBSERVABILITY.md §Request tracing)

Use::

    server = UIServer.get_instance(port=9000)
    server.attach(storage)     # any InMemory/FileStatsStorage
    ...
    server.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import BaseStatsStorage

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>deeplearning4j-tpu training UI</title>
<style>
body{font-family:system-ui,sans-serif;margin:0;background:#fafafa;color:#222}
header{background:#1a237e;color:#fff;padding:10px 18px;font-size:18px}
.row{display:flex;flex-wrap:wrap;gap:14px;padding:14px}
.card{background:#fff;border:1px solid #ddd;border-radius:6px;padding:12px;
      min-width:420px;flex:1}
h3{margin:2px 0 8px;font-size:14px;color:#444}
svg{width:100%;height:220px}
table{border-collapse:collapse;font-size:12px;width:100%}
td,th{border-bottom:1px solid #eee;padding:3px 6px;text-align:right}
th:first-child,td:first-child{text-align:left}
select{margin-left:12px}
.stat{font-size:22px;font-weight:600}
.label{font-size:11px;color:#777}
</style></head><body>
<header>deeplearning4j-tpu — training dashboard
<select id="session"></select></header>
<div class="row">
 <div class="card"><h3>Score vs iteration</h3><svg id="score"></svg></div>
 <div class="card"><h3>Iteration time (ms) / examples-sec</h3>
   <svg id="perf"></svg></div>
</div>
<div class="row">
 <div class="card"><h3>Latest</h3><div id="latest"></div></div>
 <div class="card"><h3>Parameter mean magnitudes (latest)</h3>
   <div id="model"></div></div>
</div>
<div class="row">
 <div class="card"><h3>Parameter histogram
   <select id="histparam"></select>
   <select id="histkind"><option value="param">weights</option>
     <option value="update">updates</option>
     <option value="activation">activations</option></select></h3>
   <svg id="hist"></svg></div>
 <div class="card" id="embcard" style="display:none">
   <h3>Embedding map (t-SNE)</h3><svg id="emb" style="height:320px"></svg>
 </div>
 <div class="card" id="flowcard" style="display:none">
   <h3>Model flow</h3><svg id="flow" style="height:auto"></svg>
 </div>
</div>
<div class="row">
 <div class="card" id="phasecard" style="display:none">
   <h3>Phase timeline (per worker)</h3><svg id="phases"
    style="height:auto"></svg><div id="phaselegend" class="label"></div>
 </div>
</div>
<div class="row">
 <div class="card" id="tracecard" style="display:none">
   <h3>Runtime trace (per thread, recent window)</h3><svg id="trace"
    style="height:auto"></svg><div id="tracelegend" class="label"></div>
 </div>
</div>
<div class="row">
 <div class="card" id="goodputcard" style="display:none">
   <h3>Goodput &amp; efficiency <span id="goodputsrc" class="label"></span>
   </h3><div id="goodputstats"></div><svg id="goodputbar"
    style="height:34px"></svg><div id="goodputlegend" class="label"></div>
 </div>
</div>
<div class="row">
 <div class="card" id="fleetcard" style="display:none">
   <h3>Fleet health <span id="fleetsummary" class="label"></span></h3>
   <div id="fleettable"></div>
 </div>
</div>
<div class="row">
 <div class="card" id="wfcard" style="display:none">
   <h3>Request waterfall <select id="wfselect"></select>
     <span id="wfmeta" class="label"></span></h3>
   <svg id="wfsvg" style="height:auto"></svg>
   <div id="wflegend" class="label"></div>
 </div>
</div>
<script>
const COLORS=["#1a73e8","#e8710a","#188038","#d93025","#9334e6","#12858d"];
function esc(s){ return String(s).replace(/&/g,"&amp;").replace(/</g,"&lt;")
  .replace(/>/g,"&gt;").replace(/"/g,"&quot;"); }
function lines(svg, seriesList){
  // seriesList: [{xs, ys, color, label}] — one polyline per worker
  const el = document.getElementById(svg); el.innerHTML = "";
  const allx=[], ally=[];
  seriesList.forEach(s=>{ s.xs.forEach((x,i)=>{
    if(Number.isFinite(s.ys[i])){ allx.push(x); ally.push(s.ys[i]); }});});
  if (allx.length < 2) return;
  const W = el.clientWidth || 480, H = el.clientHeight || 220, P = 30;
  const xmin=Math.min(...allx), xmax=Math.max(...allx);
  const ymin=Math.min(...ally), ymax=Math.max(...ally);
  const sx=x=>P+(W-2*P)*(x-xmin)/Math.max(xmax-xmin,1e-9);
  const sy=y=>H-P-(H-2*P)*(y-ymin)/Math.max(ymax-ymin,1e-9);
  let html =
   `<line x1="${P}" y1="${H-P}" x2="${W-P}" y2="${H-P}" stroke="#bbb"/>`+
   `<line x1="${P}" y1="${P}" x2="${P}" y2="${H-P}" stroke="#bbb"/>`+
   `<text x="${P}" y="${P-6}" font-size="10" fill="#888">`+
     `${ymax.toPrecision(4)}</text>`+
   `<text x="${P}" y="${H-P+12}" font-size="10" fill="#888">`+
     `${ymin.toPrecision(4)}</text>`;
  seriesList.forEach((s, k)=>{
    let d="";
    s.xs.forEach((x,i)=>{ if(Number.isFinite(s.ys[i]))
        d += (d?"L":"M")+sx(x).toFixed(1)+","+sy(s.ys[i]).toFixed(1); });
    html += `<path d="${d}" fill="none" stroke="${s.color}"`+
            ` stroke-width="1.6"/>`;
    if (s.label) html += `<text x="${W-P-70}" y="${P+12*(k+1)}"`+
        ` font-size="10" fill="${s.color}">${esc(s.label)}</text>`;
  });
  el.innerHTML = html;
}
function workerSeries(u, field){
  const ws = Object.keys(u.workers || {}).sort();
  if (ws.length > 1)
    return ws.map((w,k)=>({xs:u.workers[w].iterations,
      ys:u.workers[w][field], color:COLORS[k%COLORS.length], label:w}));
  return [{xs:u.iterations, ys:u[field==="scores"?"scores":"iteration_ms"],
           color:COLORS[field==="scores"?0:1]}];
}
async function refresh(){
  await refreshFleet();   // fleet scoreboard lives without any session
  await refreshWaterfall();  // so does the request-trace waterfall
  const sess = document.getElementById("session").value;
  if (!sess) return;
  const u = await (await fetch("/api/updates?session="+
                   encodeURIComponent(sess))).json();
  lines("score", workerSeries(u, "scores"));
  lines("perf", workerSeries(u, "iteration_ms"));
  const last = u.latest;
  if (last) document.getElementById("latest").innerHTML =
    `<span class="stat">${Number(last.score).toPrecision(5)}</span>
     <span class="label">score</span> &nbsp;
     <span class="stat">${last.iteration}</span>
     <span class="label">iteration</span> &nbsp;
     <span class="stat">${last.examples_per_sec ?
        Math.round(last.examples_per_sec) : "—"}</span>
     <span class="label">examples/sec</span> &nbsp;
     <span class="stat">${last.memory_rss_mb ?
        Math.round(last.memory_rss_mb) : "—"}</span>
     <span class="label">host MB</span>`;
  const m = await (await fetch("/api/model?session="+
                   encodeURIComponent(sess))).json();
  let rows = "<table><tr><th>parameter</th><th>mean |w|</th>" +
             "<th>mean |Δw|</th><th>Δ ratio</th></tr>";
  for (const [k, v] of Object.entries(m.param_stats || {})){
    const up = (m.update_stats||{})[k] || {};
    const ratio = up.mean_magnitude && v.mean_magnitude ?
      (up.mean_magnitude/v.mean_magnitude).toExponential(2) : "—";
    rows += `<tr><td>${esc(k)}</td><td>${v.mean_magnitude.toExponential(3)}</td>
      <td>${up.mean_magnitude ? up.mean_magnitude.toExponential(3) : "—"}</td>
      <td>${ratio}</td></tr>`;
  }
  document.getElementById("model").innerHTML = rows + "</table>";
  renderHistogram(m);
  await refreshEmbedding(sess, m.embedding_version ?? null);
  await refreshFlow(sess, m.activation_stats || {});
  await refreshPhases(sess);
  await refreshTrace();
  await refreshGoodput();
}
async function refreshGoodput(){
  // the efficiency ledger next to the trace timeline: headline gauges
  // (goodput %, MFU, FLOP/s, steps) + a single stacked wall-time bar
  // attributing the run across traced phases (/api/goodput serves the
  // live ledger during a run, the last RunReport after it)
  const g = await (await fetch("/api/goodput")).json();
  const card = document.getElementById("goodputcard");
  if (!g || g.source === "none" || !g.wall_s){
    card.style.display = "none"; return; }
  card.style.display = "";
  document.getElementById("goodputsrc").textContent =
    `(${g.kind || "run"} · ${g.source === "live" ? "live" : "last run"})`;
  const pct = v => v == null ? "—" : (100*v).toFixed(1)+"%";
  const num = v => v == null ? "—" : Number(v).toPrecision(3);
  document.getElementById("goodputstats").innerHTML =
    `<span class="stat">${pct(g.goodput_fraction)}</span>
     <span class="label">goodput</span> &nbsp;
     <span class="stat">${pct(g.mfu)}</span>
     <span class="label">MFU</span> &nbsp;
     <span class="stat">${g.flops_per_second ?
        num(g.flops_per_second/1e9)+" G" : "—"}</span>
     <span class="label">FLOP/s</span> &nbsp;
     <span class="stat">${g.steps ?? "—"}</span>
     <span class="label">steps</span> &nbsp;
     <span class="stat">${num(g.wall_s)}s</span>
     <span class="label">wall</span>`;
  const phases = g.phases || {};
  const names = Object.keys(phases).sort(
    (a,b)=>phases[b].seconds - phases[a].seconds);
  const el = document.getElementById("goodputbar");
  if (!names.length){ el.innerHTML = "";
    document.getElementById("goodputlegend").innerHTML = ""; return; }
  const W = el.clientWidth || 760, H = 34;
  el.setAttribute("viewBox", `0 0 ${W} ${H}`);
  let x = 0, html = "";
  const total = Math.max(g.wall_s, 1e-9);
  names.forEach(n=>{
    const w = W * phases[n].seconds / total;
    html += `<rect x="${x.toFixed(1)}" y="4" width="${Math.max(w,0.5)
      .toFixed(1)}" height="${H-8}" fill="${spanColor(n)}"`+
      ` fill-opacity="0.85"><title>${esc(n)} ${phases[n].seconds
      .toFixed(3)}s</title></rect>`;
    x += w;
  });
  if (x < W) html += `<rect x="${x.toFixed(1)}" y="4" width="${(W-x)
    .toFixed(1)}" height="${H-8}" fill="#ddd">`+
    `<title>untracked</title></rect>`;
  el.innerHTML = html;
  document.getElementById("goodputlegend").innerHTML =
    names.map(n=>`<span style="color:${spanColor(n)}">&#9632; `+
      `${esc(n)} ${phases[n].seconds.toFixed(2)}s</span>`).join(" &nbsp;")+
    ' <span style="color:#999">&#9632; untracked</span>';
}
async function refreshFleet(){
  // /api/fleet health scoreboard: one row per pushing instance —
  // liveness from heartbeat age, readiness from the pushed health
  // flags, queue depth + fit-step progress for routing decisions
  const f = await (await fetch("/api/fleet")).json();
  const card = document.getElementById("fleetcard");
  const rows = f.instances || [];
  if (!rows.length){ card.style.display = "none"; return; }
  card.style.display = "";
  document.getElementById("fleetsummary").textContent =
    `(${f.ready}/${rows.length} ready, stale after ${f.stale_after_s}s)`;
  const dot = ok => `<span style="color:${ok?'#188038':'#d93025'}">`+
    `${ok?'&#9679;':'&#9675;'}</span>`;
  let html = "<table><tr><th>instance</th><th>live</th><th>ready</th>"+
    "<th>heartbeat age s</th><th>queue</th><th>steps</th>"+
    "<th>progress age s</th><th>pushes</th></tr>";
  rows.forEach(r=>{
    html += `<tr><td>${esc(r.instance)}</td><td>${dot(r.live)}</td>`+
      `<td>${dot(r.ready)}</td><td>${r.heartbeat_age_s}</td>`+
      `<td>${r.queue_depth ?? "—"}</td>`+
      `<td>${r.steps_total ?? "—"}</td>`+
      `<td>${r.last_progress_age_s ?? "—"}</td>`+
      `<td>${r.pushes}</td></tr>`;
  });
  html += "</table>";
  // cross-host routing table: a FrontDoorRouter pushing here carries
  // its per-host routing rows in the health payload (serving/router.py)
  const routers = rows.filter(
    r => r.health && Array.isArray(r.health.routing));
  routers.forEach(R=>{
    html += `<h4 style="margin:8px 0 4px">Routing table `+
      `<span class="label">(router ${esc(R.instance)})</span></h4>`+
      "<table><tr><th>host</th><th>routable</th><th>queue</th>"+
      "<th>in flight</th><th>picks</th><th>retry-after s</th>"+
      "<th>heartbeat age s</th></tr>";
    R.health.routing.forEach(h=>{
      html += `<tr><td>${esc(h.instance || h.url)}</td>`+
        `<td>${dot(h.routable)}</td><td>${h.queue_depth ?? "—"}</td>`+
        `<td>${h.in_flight}</td><td>${h.picks}</td>`+
        `<td>${h.retry_after_s ?? "—"}</td>`+
        `<td>${h.heartbeat_age_s ?? "—"}</td></tr>`;
    });
    html += "</table>";
  });
  document.getElementById("fleettable").innerHTML = html;
}
async function refreshWaterfall(){
  // stitched per-request waterfall: trace ids arrive with the hosts'
  // span pushes (/api/traces inventory); /api/trace/<id> serves the
  // clock-skew-rebased segment list (queue_wait / batch_assembly /
  // device_compute / network) this card draws as one horizontal lane
  // per segment on the request's own time axis
  const card = document.getElementById("wfcard");
  let ids = [];
  try {
    const t = await (await fetch("/api/traces")).json();
    ids = (t.traces || []).slice().reverse();  // most recent first
  } catch (e) { ids = []; }
  if (!ids.length){ card.style.display = "none"; return; }
  card.style.display = "";
  const sel = document.getElementById("wfselect");
  const cur = Array.from(sel.options).map(o=>o.value);
  if (JSON.stringify(cur) !== JSON.stringify(ids)){
    const v = sel.value;
    sel.innerHTML = ids.map(x=>`<option>${esc(x)}</option>`).join("");
    sel.value = ids.includes(v) ? v : ids[0];
  }
  const wf = await (await fetch("/api/trace/"+
      encodeURIComponent(sel.value))).json();
  if (!wf.found){ card.style.display = "none"; return; }
  document.getElementById("wfmeta").textContent =
    `(${(wf.instances||[]).join(", ")} · total ${wf.total_ms} ms)`;
  const segs = wf.segments || [];
  const el = document.getElementById("wfsvg");
  const W = el.clientWidth || 760, LH = 18, P = 200, TP = 4;
  const H = TP*2 + segs.length*LH + 16;
  el.setAttribute("viewBox", `0 0 ${W} ${H}`);
  el.style.height = H + "px";
  const total = Math.max(wf.total_ms, 1e-9);
  const sx = ms=>P + (W - P - 10) * ms / total;
  let html = "";
  segs.forEach((s, i)=>{
    const y = TP + i*LH;
    html += `<text x="${P-6}" y="${y+LH/2+3}" font-size="9"`+
      ` text-anchor="end">${esc(s.instance+" · "+s.name)}</text>`+
      `<rect x="${sx(s.start_ms).toFixed(1)}" y="${y+2}"`+
      ` width="${Math.max(sx(s.start_ms+s.dur_ms)-sx(s.start_ms),0.8)
        .toFixed(1)}" height="${LH-5}"`+
      ` fill="${spanColor(s.name)}" fill-opacity="0.85">`+
      `<title>${esc(s.name)} ${s.dur_ms.toFixed(2)} ms `+
      `(${esc(s.instance)})</title></rect>`;
  });
  html += `<text x="${P}" y="${H-2}" font-size="10" fill="#888">`+
    `0 ms</text>`+
    `<text x="${W-80}" y="${H-2}" font-size="10" fill="#888">`+
    `${total.toFixed(1)} ms</text>`;
  el.innerHTML = html;
  document.getElementById("wflegend").innerHTML =
    Object.entries(wf.summary_ms || {}).map(([n, ms])=>
      `<span style="color:${spanColor(n)}">&#9632; ${esc(n)} `+
      `${ms.toFixed(2)} ms</span>`).join(" &nbsp;");
}
document.getElementById("wfselect").onchange = refreshWaterfall;
const TRACE_PALETTE=["#1f77b4","#ff7f0e","#2ca02c","#d93025","#9334e6",
  "#8c564b","#e377c2","#7f7f7f","#bcbd22","#12858d"];
function spanColor(name){
  let h = 0;
  for (let i = 0; i < name.length; i++) h = (h*31 + name.charCodeAt(i))>>>0;
  return TRACE_PALETTE[h % TRACE_PALETTE.length];
}
async function refreshTrace(){
  // per-thread span lanes from the process-global tracer (/api/trace is
  // the same Chrome trace-event payload Perfetto loads: "M" metadata
  // events carry thread names, "X" events carry ts/dur in microseconds)
  const t = await (await fetch("/api/trace")).json();
  const evs = (t.traceEvents || []);
  const names = {}, byTid = {};
  evs.forEach(e=>{
    if (e.ph === "M" && e.name === "thread_name")
      names[e.tid] = e.args.name;
    else if (e.ph === "X")
      (byTid[e.tid] = byTid[e.tid] || []).push(e);
  });
  const tids = Object.keys(byTid).sort(
    (a,b)=>(names[a]||a).localeCompare(names[b]||b));
  const card = document.getElementById("tracecard");
  if (!tids.length){ card.style.display = "none"; return; }
  card.style.display = "";
  // render only the recent window — the ring can hold 64k spans
  let tmax = 0;
  tids.forEach(tid=>byTid[tid].forEach(e=>{
    tmax = Math.max(tmax, e.ts + e.dur); }));
  const WINDOW_US = 10e6;
  const tmin = Math.max(0, tmax - WINDOW_US);
  const el = document.getElementById("trace");
  const W = el.clientWidth || 760, LH = 30, P = 150, TP = 6;
  const H = TP*2 + tids.length*LH + 16;
  el.setAttribute("viewBox", `0 0 ${W} ${H}`);
  el.style.height = H + "px";
  const sx = us=>P + (W - P - 10) * (us - tmin) / Math.max(tmax - tmin, 1);
  let html = "";
  const seen = new Set();
  tids.forEach((tid, i)=>{
    const y = TP + i*LH;
    html += `<text x="${P-6}" y="${y+LH/2+3}" font-size="10"`+
      ` text-anchor="end">${esc(names[tid] || ("thread-"+tid))}</text>`;
    byTid[tid].forEach(e=>{
      if (e.ts + e.dur < tmin) return;
      seen.add(e.name);
      const x0 = sx(Math.max(e.ts, tmin)), x1 = sx(e.ts + e.dur);
      html += `<rect x="${x0.toFixed(1)}" y="${y+3}"`+
        ` width="${Math.max(x1-x0, 0.8).toFixed(1)}" height="${LH-8}"`+
        ` fill="${spanColor(e.name)}" fill-opacity="0.85">`+
        `<title>${esc(e.name)} ${(e.dur/1000).toFixed(2)} ms</title>`+
        `</rect>`;
    });
  });
  html += `<text x="${P}" y="${H-2}" font-size="10" fill="#888">`+
    `${(tmin/1e6).toFixed(2)}s</text>`+
    `<text x="${W-60}" y="${H-2}" font-size="10" fill="#888">`+
    `${(tmax/1e6).toFixed(2)}s</text>`;
  el.innerHTML = html;
  document.getElementById("tracelegend").innerHTML =
    Array.from(seen).map(n=>`<span style="color:${spanColor(n)}">`+
      `&#9632; ${esc(n)}</span>`).join(" &nbsp;");
}
async function refreshPhases(sess){
  // per-worker training-phase lanes (the Spark timeline tier): the
  // distributed trainers post EventStats as static info "phase_stats";
  // the phase->color map rides in the payload (one canonical source,
  // parallel/stats.py PHASE_COLORS)
  const p = await (await fetch("/api/phases?session="+
                   encodeURIComponent(sess))).json();
  const PHASE_COLORS = p.colors || {};
  const ws = Object.keys(p.workers || {}).sort();
  const card = document.getElementById("phasecard");
  if (!ws.length){ card.style.display = "none"; return; }
  card.style.display = "";
  const el = document.getElementById("phases");
  const W = el.clientWidth || 760, LH = 30, P = 64, TP = 6;
  const H = TP*2 + ws.length*LH + 16;
  el.setAttribute("viewBox", `0 0 ${W} ${H}`);
  el.style.height = H + "px";
  let tmax = 0;
  ws.forEach(w=>p.workers[w].forEach(e=>{
    tmax = Math.max(tmax, e.start + e.duration_ms/1000); }));
  if (tmax <= 0) tmax = 1;
  const sx = t=>P + (W - P - 10) * t / tmax;
  let html = "";
  const seen = new Set();
  ws.forEach((w, i)=>{
    const y = TP + i*LH;
    html += `<text x="${P-6}" y="${y+LH/2+3}" font-size="10"`+
      ` text-anchor="end">${esc(w)}</text>`;
    p.workers[w].forEach(e=>{
      seen.add(e.phase);
      const x0 = sx(e.start), x1 = sx(e.start + e.duration_ms/1000);
      html += `<rect x="${x0.toFixed(1)}" y="${y+3}"`+
        ` width="${Math.max(x1-x0, 1).toFixed(1)}" height="${LH-8}"`+
        ` fill="${PHASE_COLORS[e.phase]||"#7f7f7f"}" fill-opacity="0.85">`+
        `<title>${esc(e.phase)} ${e.duration_ms.toFixed(1)} ms</title>`+
        `</rect>`;
    });
  });
  html += `<text x="${P}" y="${H-2}" font-size="10" fill="#888">0s</text>`+
    `<text x="${W-40}" y="${H-2}" font-size="10" fill="#888">`+
    `${tmax.toFixed(2)}s</text>`;
  el.innerHTML = html;
  document.getElementById("phaselegend").innerHTML =
    Array.from(seen).map(ph=>`<span style="color:${
      PHASE_COLORS[ph]||"#7f7f7f"}">&#9632; ${esc(ph)}</span>`).join(" &nbsp;");
}
let lastModel = null;
function renderHistogram(m){
  if (m) lastModel = m; else m = lastModel;
  if (!m) return;
  const psel = document.getElementById("histparam");
  const kind = document.getElementById("histkind").value;
  // the selector lists the names of the CHOSEN kind (activation stats
  // use layer names, parameter/update stats use parameter paths)
  const stats = kind === "update" ? (m.update_stats||{}) :
    kind === "activation" ? (m.activation_stats||{}) :
    (m.param_stats||{});
  const names = Object.keys(stats);
  const current = Array.from(psel.options).map(o=>o.value);
  if (JSON.stringify(current) !== JSON.stringify(names)){
    const cur = psel.value;
    psel.innerHTML = names.map(n=>`<option>${esc(n)}</option>`).join("");
    if (names.includes(cur)) psel.value = cur;
  }
  const st = stats[psel.value];
  const el = document.getElementById("hist"); el.innerHTML = "";
  if (!st || !st.histogram) return;
  const h = st.histogram, counts = h.counts;
  const W = el.clientWidth || 480, H = el.clientHeight || 220, P = 30;
  const cmax = Math.max(...counts, 1);
  const bw = (W - 2*P) / counts.length;
  let html = `<line x1="${P}" y1="${H-P}" x2="${W-P}" y2="${H-P}"`+
             ` stroke="#bbb"/>`;
  counts.forEach((c, i)=>{
    const bh = (H - 2*P) * c / cmax;
    html += `<rect x="${(P+i*bw).toFixed(1)}" y="${(H-P-bh).toFixed(1)}"`+
      ` width="${Math.max(bw-1,1).toFixed(1)}" height="${bh.toFixed(1)}"`+
      ` fill="#1a73e8"/>`;
  });
  html += `<text x="${P}" y="${H-P+12}" font-size="10" fill="#888">`+
    `${Number(h.min).toPrecision(3)}</text>`+
    `<text x="${W-P-40}" y="${H-P+12}" font-size="10" fill="#888">`+
    `${Number(h.max).toPrecision(3)}</text>`+
    `<text x="${P}" y="${P-6}" font-size="10" fill="#888">max bin `+
    `${cmax}</text>`;
  el.innerHTML = html;
}
document.getElementById("histparam").onchange = ()=>renderHistogram();
document.getElementById("histkind").onchange = ()=>renderHistogram();
let flowCache = null;
async function refreshFlow(sess, actStats){
  // topology is static per session: fetch once (but keep re-fetching
  // while null — the model info may be posted after the first poll)
  if (flowCache !== sess || !window._flowModel){
    const f = await (await fetch("/api/flow?session="+
                     encodeURIComponent(sess))).json();
    flowCache = sess;
    window._flowModel = f.model;
  }
  const model = window._flowModel;
  const card = document.getElementById("flowcard");
  if (!model || !model.layers || !model.layers.length){
    card.style.display = "none"; return;
  }
  card.style.display = "";
  const el = document.getElementById("flow");
  const BW = 190, BH = 34, GAP = 14, P = 10;
  const layers = model.layers;
  const H = P*2 + layers.length*(BH+GAP);
  el.setAttribute("viewBox", `0 0 420 ${H}`);
  el.style.height = Math.min(H, 600) + "px";
  const ypos = {};
  layers.forEach((l, i)=>{ ypos[l.name] = P + i*(BH+GAP); });
  // color boxes by activation mean |a| when the probe publishes it
  const mags = {};
  let mmax = 0;
  for (const [k, v] of Object.entries(actStats || {})){
    mags[k] = v.mean_magnitude; mmax = Math.max(mmax, v.mean_magnitude);
  }
  let html = "";
  layers.forEach((l)=>{
    const y = ypos[l.name];
    (l.inputs||[]).forEach(src=>{
      if (src in ypos)
        html += `<line x1="${P+BW/2}" y1="${ypos[src]+BH}"`+
          ` x2="${P+BW/2}" y2="${y}" stroke="#999"`+
          ` marker-end="none"/>`;
    });
    const m = mags[l.name];
    const shade = (m != null && mmax > 0) ?
      Math.round(235 - 140*(m/mmax)) : 235;
    html += `<rect x="${P}" y="${y}" width="${BW}" height="${BH}" rx="5"`+
      ` fill="rgb(${shade},${shade},255)" stroke="#1a237e"/>`+
      `<text x="${P+8}" y="${y+14}" font-size="11" font-weight="600">`+
      `${esc(l.name)}</text>`+
      `<text x="${P+8}" y="${y+27}" font-size="10" fill="#555">`+
      `${esc(l.type)} · ${l.params.toLocaleString()} params`+
      `${m != null ? " · |a| "+Number(m).toPrecision(3) : ""}</text>`;
  });
  el.innerHTML = html;
}
let embCache = {sess: null, version: null};
async function refreshEmbedding(sess, version){
  // fetch + rebuild the scatter only when a (re)published embedding's
  // version changes — /api/model carries the version on every poll
  if (embCache.sess === sess && embCache.version === version) return;
  embCache = {sess: sess, version: version};
  const card = document.getElementById("embcard");
  if (version == null){ card.style.display = "none"; return; }
  const e = await (await fetch("/api/embedding?session="+
                   encodeURIComponent(sess))).json();
  if (!e.xy || e.xy.length === 0){ card.style.display = "none"; return; }
  card.style.display = "";
  const el = document.getElementById("emb"); el.innerHTML = "";
  const W = el.clientWidth || 480, H = el.clientHeight || 320, P = 20;
  const xs = e.xy.map(p=>p[0]), ys = e.xy.map(p=>p[1]);
  const xmin=Math.min(...xs), xmax=Math.max(...xs);
  const ymin=Math.min(...ys), ymax=Math.max(...ys);
  const sx=x=>P+(W-2*P)*(x-xmin)/Math.max(xmax-xmin,1e-9);
  const sy=y=>H-P-(H-2*P)*(y-ymin)/Math.max(ymax-ymin,1e-9);
  let html = "";
  e.xy.forEach((p, i)=>{
    const c = COLORS[i % COLORS.length];
    html += `<circle cx="${sx(p[0]).toFixed(1)}" cy="${sy(p[1]).toFixed(1)}"`+
      ` r="2.5" fill="${c}"/>`;
    if (e.labels[i]) html += `<text x="${(sx(p[0])+4).toFixed(1)}"`+
      ` y="${(sy(p[1])+3).toFixed(1)}" font-size="9" fill="#555">`+
      `${esc(e.labels[i])}</text>`;
  });
  el.innerHTML = html;
}
async function init(){
  const s = await (await fetch("/api/sessions")).json();
  const sel = document.getElementById("session");
  sel.innerHTML = s.sessions.map(x=>`<option>${esc(x)}</option>`).join("");
  sel.onchange = refresh;
  await refresh();
  setInterval(refresh, 2000);
}
init();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4j-tpu-ui/1.0"

    def log_message(self, *args):  # quiet
        pass

    def _send(self, body: bytes, ctype: str, code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200) -> None:
        self._send(json.dumps(obj).encode(), "application/json", code)

    def do_GET(self):  # noqa: N802 (stdlib API)
        ui: "UIServer" = self.server.ui_server  # type: ignore[attr-defined]
        url = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        if url.path == "/":
            self._send(_PAGE.encode(), "text/html; charset=utf-8")
        elif url.path == "/api/sessions":
            self._json(ui.sessions_payload())
        elif url.path == "/api/updates":
            sess = q.get("session", "")
            after = float(q.get("after", "-inf"))
            self._json(ui.updates_payload(sess, after))
        elif url.path == "/api/model":
            self._json(ui.model_payload(q.get("session", "")))
        elif url.path == "/api/embedding":
            self._json(ui.embedding_payload(q.get("session", "")))
        elif url.path == "/api/flow":
            self._json(ui.flow_payload(q.get("session", "")))
        elif url.path == "/api/phases":
            self._json(ui.phases_payload(q.get("session", "")))
        elif url.path == "/metrics":
            from deeplearning4j_tpu.observability import metrics as om
            if "format=snapshot" in url.query:
                from deeplearning4j_tpu.observability import (
                    distributed as dist)
                self._json(dist.export_snapshot())
            elif om.wants_prometheus(self.headers.get("Accept", ""),
                                     url.query):
                if ui.federation.instance_count():
                    # fleet members have pushed: render the merged view
                    # (this process folded in as one more instance)
                    from deeplearning4j_tpu.observability import (
                        distributed as dist)
                    body = ui.federation.render_prometheus(
                        local=(dist.get_identity().tag,
                               om.get_registry().collect()))
                else:
                    body = om.get_registry().render_prometheus()
                self._send(body.encode(), om.PROMETHEUS_CONTENT_TYPE)
            else:
                self._json(om.get_registry().snapshot())
        elif url.path == "/api/fleet":
            self._json(ui.federation.fleet_payload())
        elif url.path == "/api/traces":
            self._json({"traces": ui.trace_store.trace_ids(),
                        "store": ui.trace_store.describe()})
        elif url.path.startswith("/api/trace/"):
            tid = url.path[len("/api/trace/"):].strip("/")
            wf = ui.trace_store.waterfall(tid)
            self._json(wf, 200 if wf.get("found") else 404)
        elif url.path == "/api/trace":
            from deeplearning4j_tpu.observability.trace import get_tracer
            self._json(get_tracer().to_chrome_trace())
        elif url.path == "/api/goodput":
            from deeplearning4j_tpu.observability import goodput
            self._json(goodput.live_snapshot())
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):  # noqa: N802 (stdlib API)
        # remote stats receiver (the reference UI's remote module:
        # workers post through a StatsStorageRouter — ui/router.py) plus
        # the metrics-federation push endpoint
        ui: "UIServer" = self.server.ui_server  # type: ignore[attr-defined]
        path = urlparse(self.path).path
        if path not in ("/api/post", "/api/metrics_push"):
            self._json({"error": "not found"}, 404)
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n).decode())
            if path == "/api/metrics_push":
                tag = ui.federation.ingest(payload)
                # same push, second consumer: any request-scoped span
                # batch riding the snapshot lands in the trace store
                ui.trace_store.ingest_snapshot(payload)
                self._json({"status": "ok", "instance": tag,
                            "instances": ui.federation.instance_count()})
            else:
                ui.receive_post(payload)
                self._json({"status": "ok"})
        except Exception as e:  # malformed post must not kill the server
            self._json({"error": f"{type(e).__name__}: {e}"}, 400)


class UIServer:
    """Singleton dashboard server over attached StatsStorage instances."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000, host: str = "127.0.0.1"):
        self.storages: List[BaseStatsStorage] = []
        self._remote_storage: Optional[BaseStatsStorage] = None
        self._remote_lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.ui_server = self  # type: ignore[attr-defined]
        # /metrics serves the unified registry — make sure the runtime
        # collector (compile count, device memory, steps/sec) is on it
        from deeplearning4j_tpu.observability.metrics import (
            install_runtime_metrics)
        install_runtime_metrics()
        # fleet aggregator: child processes push export_snapshot() to
        # /api/metrics_push; /metrics re-exports the merged view and
        # /api/fleet serves the health scoreboard
        from deeplearning4j_tpu.observability.distributed import (
            MetricsFederation, TraceStore)
        self.federation = MetricsFederation()
        # request-scoped span index: span batches riding the same
        # /api/metrics_push wire land here; /api/trace/<id> serves the
        # stitched waterfall the dashboard panel renders
        self.trace_store = TraceStore()
        self.port = self._httpd.server_address[1]  # resolved if port=0
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-ui-server")
        self._thread.start()

    # PlayUIServer.getInstance() parity
    @classmethod
    def get_instance(cls, port: int = 9000,
                     host: str = "127.0.0.1") -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port=port, host=host)
        return cls._instance

    def attach(self, storage: BaseStatsStorage) -> None:
        if storage not in self.storages:
            self.storages.append(storage)

    def receive_post(self, payload: dict) -> None:
        """Store a remotely-posted report (lazily creating the receiving
        storage on first post — the reference's remote-module role)."""
        from deeplearning4j_tpu.ui.stats import StatsReport
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        with self._remote_lock:
            # handler threads race the first post: exactly ONE receiving
            # storage may ever be attached or early reports strand in an
            # orphan the dashboard resolves first
            if self._remote_storage is None:
                self._remote_storage = InMemoryStatsStorage()
                self.attach(self._remote_storage)
        kind = payload.get("type")
        if kind == "update":
            self._remote_storage.put_update(
                StatsReport.from_dict(payload["report"]))
        elif kind == "static_info":
            self._remote_storage.put_static_info(
                payload["session_id"], payload["worker_id"],
                payload["info"])
        else:
            raise ValueError(f"unknown post type {kind!r}")

    def detach(self, storage: BaseStatsStorage) -> None:
        self.storages = [s for s in self.storages if s is not storage]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        if UIServer._instance is self:
            UIServer._instance = None

    # ------------------------------------------------------ JSON payloads
    def _find(self, session_id: str) -> Optional[BaseStatsStorage]:
        for s in self.storages:
            if session_id in s.list_session_ids():
                return s
        return None

    def sessions_payload(self) -> dict:
        sessions = []
        for s in self.storages:
            sessions.extend(s.list_session_ids())
        return {"sessions": sorted(set(sessions))}

    def updates_payload(self, session_id: str, after: float) -> dict:
        storage = self._find(session_id)
        if storage is None:
            return {"iterations": [], "scores": [], "iteration_ms": [],
                    "examples_per_sec": [], "latest": None}
        reports = storage.get_all_updates_after(session_id, after)
        latest = reports[-1].to_dict() if reports else None
        if latest:
            latest.pop("param_stats", None)
            latest.pop("update_stats", None)
            latest.pop("activation_stats", None)
        # per-worker series: a multi-process (DP-2) run posts through the
        # remote router and every worker renders as its own curve
        workers: dict = {}
        for r in reports:
            w = workers.setdefault(r.worker_id, {"iterations": [],
                                                 "scores": [],
                                                 "iteration_ms": []})
            w["iterations"].append(r.iteration)
            w["scores"].append(r.score)
            w["iteration_ms"].append(r.iteration_ms)
        return {
            "iterations": [r.iteration for r in reports],
            "scores": [r.score for r in reports],
            "iteration_ms": [r.iteration_ms for r in reports],
            "examples_per_sec": [r.examples_per_sec for r in reports],
            "workers": workers,
            "latest": latest,
        }

    def flow_payload(self, session_id: str) -> dict:
        """Model topology for the flow view (the reference UI's
        flow/model tabs): first worker's posted static model info."""
        for s in self.storages:
            for wid in s.list_worker_ids_for_session(session_id):
                info = s.get_static_info(session_id, wid)
                if info and "model" in info:
                    return {"model": info["model"], "worker": wid}
        return {"model": None, "worker": None}

    def phases_payload(self, session_id: str) -> dict:
        """Per-worker phase EventStats for the timeline card (the Spark
        timeline surface — ParameterAveragingTrainingMasterStats /
        StatsUtils.exportStatsAsHtml; the distributed trainers post
        ``phase_stats`` via TrainingStatsCollector.post_to)."""
        from deeplearning4j_tpu.parallel.stats import PHASE_COLORS
        workers = {}
        for s in self.storages:
            for wid in s.list_worker_ids_for_session(session_id):
                info = s.get_static_info(session_id, wid)
                if info and "phase_stats" in info:
                    workers[wid] = info["phase_stats"]
        # colors ride in the payload so the live dashboard and the
        # exported timeline HTML stay on ONE canonical phase->color map
        return {"workers": workers, "colors": PHASE_COLORS}

    def embedding_payload(self, session_id: str) -> dict:
        """Published 2-D embedding scatter for the session (the reference
        UI's tsne tab — ui/embedding.py publishes it)."""
        from deeplearning4j_tpu.ui.embedding import get_embedding
        info = get_embedding(self.storages, session_id)
        return info or {"labels": [], "xy": []}

    def model_payload(self, session_id: str) -> dict:
        storage = self._find(session_id)
        latest = storage.get_latest_update(session_id) if storage else None
        from deeplearning4j_tpu.ui.embedding import get_embedding
        emb = get_embedding(self.storages, session_id) or {}
        if latest is None:
            return {"param_stats": {}, "update_stats": {},
                    "activation_stats": {},
                    "embedding_version": emb.get("version")}
        return {"param_stats": latest.param_stats,
                "update_stats": latest.update_stats,
                "activation_stats": getattr(latest, "activation_stats", {}),
                # lets the page detect a (re)published embedding without
                # downloading the full scatter every poll
                "embedding_version": emb.get("version")}
