"""Stats storage backends.

Parity: deeplearning4j-ui-model storage/ — the StatsStorage API
(BaseCollectionStatsStorage.java) decouples stat producers (listeners)
from consumers (the web server): sessions hold per-worker streams of
timestamped updates plus one static-info record. `InMemoryStatsStorage`
keeps everything in maps (InMemoryStatsStorage.java parity);
`FileStatsStorage` persists every record so a dashboard can be pointed at
a finished/crashed run (FileStatsStorage.java parity — MapDB there,
append-only JSONL here: human-greppable, crash-safe, no native deps).

Storage listeners receive (event_type, session_id, worker_id) callbacks
(StatsStorageListener analogue) so a live server can push/poll updates.
"""

from __future__ import annotations

import json
import os
import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.ui.stats import StatsReport

# storage event types (StatsStorageListener.EventType analogue)
NEW_SESSION = "new_session"
NEW_WORKER = "new_worker"
POST_UPDATE = "post_update"
POST_STATIC = "post_static"


class BaseStatsStorage:
    """Session -> worker -> ordered updates, plus per-session static info."""

    def __init__(self):
        self._lock = threading.RLock()
        # {session: {worker: [StatsReport, ...]}}
        self._updates: Dict[str, Dict[str, List[StatsReport]]] = (
            defaultdict(dict))
        # {session: {worker: dict}} — model/config metadata posted once
        self._static: Dict[str, Dict[str, dict]] = defaultdict(dict)
        self._listeners: List[Callable[[str, str, str], None]] = []

    # ----------------------------------------------------------- producers
    def put_update(self, report: StatsReport) -> None:
        with self._lock:
            sess, worker = report.session_id, report.worker_id
            new_session = sess not in self._updates
            new_worker = not new_session and worker not in self._updates[sess]
            self._updates[sess].setdefault(worker, []).append(report)
            self._persist_update(report)
        if new_session:
            self._notify(NEW_SESSION, sess, worker)
        if new_session or new_worker:
            self._notify(NEW_WORKER, sess, worker)
        self._notify(POST_UPDATE, sess, worker)

    def put_static_info(self, session_id: str, worker_id: str,
                        info: dict) -> None:
        with self._lock:
            # MERGE by key: independent producers share one worker slot
            # (StatsListener posts {"model": ...}, the distributed
            # trainers post {"phase_stats": ...}; replacement would make
            # them clobber each other)
            merged = dict(self._static[session_id].get(worker_id) or {})
            merged.update(info)
            self._static[session_id][worker_id] = merged
            self._persist_static(session_id, worker_id, info)
        self._notify(POST_STATIC, session_id, worker_id)

    # ----------------------------------------------------------- consumers
    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted(set(self._updates) | set(self._static))

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted(set(self._updates.get(session_id, {}))
                          | set(self._static.get(session_id, {})))

    def get_all_updates(self, session_id: str,
                        worker_id: Optional[str] = None) -> List[StatsReport]:
        with self._lock:
            workers = self._updates.get(session_id, {})
            if worker_id is not None:
                return list(workers.get(worker_id, []))
            out: List[StatsReport] = []
            for reports in workers.values():
                out.extend(reports)
            out.sort(key=lambda r: (r.iteration, r.timestamp))
            return out

    def get_all_updates_after(self, session_id: str, timestamp: float,
                              worker_id: Optional[str] = None
                              ) -> List[StatsReport]:
        return [r for r in self.get_all_updates(session_id, worker_id)
                if r.timestamp > timestamp]

    def get_latest_update(self, session_id: str,
                          worker_id: Optional[str] = None
                          ) -> Optional[StatsReport]:
        updates = self.get_all_updates(session_id, worker_id)
        return updates[-1] if updates else None

    def get_static_info(self, session_id: str,
                        worker_id: str) -> Optional[dict]:
        with self._lock:
            return self._static.get(session_id, {}).get(worker_id)

    def num_updates(self, session_id: str,
                    worker_id: Optional[str] = None) -> int:
        return len(self.get_all_updates(session_id, worker_id))

    # ----------------------------------------------------------- listeners
    def register_listener(self,
                          fn: Callable[[str, str, str], None]) -> None:
        self._listeners.append(fn)

    def deregister_listener(self,
                            fn: Callable[[str, str, str], None]) -> None:
        self._listeners = [l for l in self._listeners if l is not fn]

    def _notify(self, event: str, session_id: str, worker_id: str) -> None:
        for fn in list(self._listeners):
            fn(event, session_id, worker_id)

    # ------------------------------------------------------- persistence
    def _persist_update(self, report: StatsReport) -> None:
        pass

    def _persist_static(self, session_id: str, worker_id: str,
                        info: dict) -> None:
        pass

    def close(self) -> None:
        pass


class InMemoryStatsStorage(BaseStatsStorage):
    """Purely in-memory (InMemoryStatsStorage.java parity)."""


class FileStatsStorage(BaseStatsStorage):
    """Append-only JSONL-backed storage. Records survive process death and
    an existing file is fully reloaded on open, so a dashboard can attach
    to a past run (FileStatsStorage.java capability parity)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._file = None
        if os.path.exists(path):
            self._load()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._file = open(path, "a", encoding="utf-8")

    def _load(self) -> None:
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write after a crash
                kind = rec.get("kind")
                if kind == "update":
                    r = StatsReport.from_dict(rec["report"])
                    self._updates[r.session_id].setdefault(
                        r.worker_id, []).append(r)
                elif kind == "static":
                    # merge-by-key replay, matching put_static_info's
                    # semantics — records are persisted PARTIAL (one
                    # producer's keys each), so replacement would let the
                    # last producer clobber the others on reload
                    slot = self._static[rec["session_id"]]
                    merged = dict(slot.get(rec["worker_id"]) or {})
                    merged.update(rec["info"])
                    slot[rec["worker_id"]] = merged

    def _write(self, rec: dict) -> None:
        if self._file is None:
            return
        self._file.write(json.dumps(rec) + "\n")
        self._file.flush()

    def _persist_update(self, report: StatsReport) -> None:
        self._write({"kind": "update", "report": report.to_dict()})

    def _persist_static(self, session_id: str, worker_id: str,
                        info: dict) -> None:
        self._write({"kind": "static", "session_id": session_id,
                     "worker_id": worker_id, "info": dict(info)})

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
