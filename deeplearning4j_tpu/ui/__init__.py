"""Observability / UI (parity: deeplearning4j-ui-parent, ~24.9k LoC —
SURVEY.md §2.11): StatsListener -> StatsStorage -> web dashboard."""

from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
)
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.router import RemoteStatsStorageRouter
from deeplearning4j_tpu.ui.embedding import publish_embedding
