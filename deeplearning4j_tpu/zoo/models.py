"""Canonical model builders for the baseline configs (BASELINE.md #1-#3).

These are the TPU-native renderings of the reference's flagship example
nets: LeNet on MNIST (MultiLayerNetwork.fit path,
deeplearning4j-nn/.../MultiLayerNetwork.java:947), ResNet-v1 bottleneck
graphs (ComputationGraph.fit path, ComputationGraph.java:701 + the
CudnnConvolutionHelper.java:49 conv stack), and a GravesLSTM char-RNN
(LSTMHelpers.java:57,271). All convs are NHWC (TPU-preferred layout; the
lowering handles it — the reference is NCHW at the API only).

By default conv/LSTM models use bf16 compute with f32 master params — the
MXU-native dtype policy.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.core import DtypePolicy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer, Dense,
                                               Output)
from deeplearning4j_tpu.nn.conf.layers_conv import (
    BatchNorm,
    Convolution2D,
    GlobalPooling,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.layers_recurrent import GravesLSTM, RnnOutput
from deeplearning4j_tpu.nn.conf.vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Nesterovs

BF16 = DtypePolicy(param_dtype="float32", compute_dtype="bfloat16")
F32 = DtypePolicy(param_dtype="float32", compute_dtype="float32")
# f16 compute implies dynamic loss scaling (DtypePolicy loss_scale="auto"
# resolves to dynamic for float16) — see PRECISION.md
F16 = DtypePolicy(param_dtype="float32", compute_dtype="float16")


def mnist_mlp(seed: int = 42, dtype: Optional[DtypePolicy] = None
              ) -> MultiLayerNetwork:
    """784-256-128-10 MLP (the round-1 smoke/bench model)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(1e-3)).activation("relu")
            .dtype(dtype or F32)
            .list()
            .layer(Dense(n_out=256))
            .layer(Dense(n_out=128))
            .layer(Output(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    return MultiLayerNetwork(conf).init()


def lenet(seed: int = 42, n_classes: int = 10,
          dtype: Optional[DtypePolicy] = None) -> MultiLayerNetwork:
    """LeNet MNIST (baseline config #1): conv5x5x20 -> maxpool2 ->
    conv5x5x50 -> maxpool2 -> dense500 -> softmax (the canonical DL4J
    LeNet example topology)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Nesterovs(0.01, 0.9)).activation("relu")
            .dtype(dtype or BF16)
            .list()
            .layer(Convolution2D(n_out=20, kernel=(5, 5), stride=(1, 1),
                                 activation="identity"))
            .layer(Subsampling(kernel=(2, 2), stride=(2, 2), pooling="max"))
            .layer(Convolution2D(n_out=50, kernel=(5, 5), stride=(1, 1),
                                 activation="identity"))
            .layer(Subsampling(kernel=(2, 2), stride=(2, 2), pooling="max"))
            .layer(Dense(n_out=500, activation="relu"))
            .layer(Output(n_out=n_classes, loss="mcxent",
                          activation="softmax"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def _conv_bn(g, name: str, n_out: int, kernel, stride, inputs: str,
             activation: str = "relu"):
    g.add_layer(f"{name}_conv",
                Convolution2D(n_out=n_out, kernel=kernel, stride=stride,
                              mode="same", has_bias=False,
                              activation="identity"),
                inputs)
    # activation must be EXPLICIT identity: a bare BatchNorm() inherits
    # the global default activation (sigmoid, reference parity), which
    # would squash every BN output — the round-1..3 zoo had exactly that
    # bug, silently training (and benchmarking) a sigmoid-gated ResNet
    g.add_layer(f"{name}_bn", BatchNorm(activation="identity"),
                f"{name}_conv")
    if activation != "identity":
        g.add_layer(f"{name}_act", ActivationLayer(activation=activation),
                    f"{name}_bn")
        return f"{name}_act"
    return f"{name}_bn"


def _bottleneck(g, name: str, inputs: str, filters: int, stride: int,
                project: bool) -> str:
    """ResNet-v1 bottleneck: 1x1 (reduce) -> 3x3 -> 1x1 (expand, x4), with
    an identity or projection shortcut."""
    x = _conv_bn(g, f"{name}_a", filters, (1, 1), (stride, stride), inputs)
    x = _conv_bn(g, f"{name}_b", filters, (3, 3), (1, 1), x)
    x = _conv_bn(g, f"{name}_c", filters * 4, (1, 1), (1, 1), x,
                 activation="identity")
    if project:
        shortcut = _conv_bn(g, f"{name}_proj", filters * 4, (1, 1),
                            (stride, stride), inputs, activation="identity")
    else:
        shortcut = inputs
    g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
    g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                f"{name}_add")
    return f"{name}_out"


def _basic_block(g, name: str, inputs: str, filters: int, stride: int,
                 project: bool) -> str:
    """ResNet-v1 basic block (3x3 -> 3x3) for ResNet-18/34."""
    x = _conv_bn(g, f"{name}_a", filters, (3, 3), (stride, stride), inputs)
    x = _conv_bn(g, f"{name}_b", filters, (3, 3), (1, 1), x,
                 activation="identity")
    if project:
        shortcut = _conv_bn(g, f"{name}_proj", filters, (1, 1),
                            (stride, stride), inputs, activation="identity")
    else:
        shortcut = inputs
    g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
    g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                f"{name}_add")
    return f"{name}_out"


def _resnet(stage_blocks, block_fn, bottleneck: bool, *, image_size: int,
            n_classes: int, seed: int, dtype: Optional[DtypePolicy],
            updater=None) -> ComputationGraph:
    g = (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater or Nesterovs(0.1, 0.9))
         .dtype(dtype or BF16)
         .graph_builder()
         .add_inputs("img"))
    x = _conv_bn(g, "stem", 64, (7, 7), (2, 2), "img")
    g.add_layer("stem_pool",
                Subsampling(kernel=(3, 3), stride=(2, 2), pooling="max",
                            mode="same"),
                x)
    x = "stem_pool"
    filters = 64
    in_ch = 64
    for stage, n_blocks in enumerate(stage_blocks):
        out_ch = filters * 4 if bottleneck else filters
        for b in range(n_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            # projection shortcut only where shapes change (canonical
            # ResNet: identity everywhere else)
            project = b == 0 and (stride != 1 or in_ch != out_ch)
            x = block_fn(g, f"s{stage}b{b}", x, filters, stride, project)
            in_ch = out_ch
        filters *= 2
    g.add_layer("head_pool", GlobalPooling(pooling="avg"), x)
    g.add_layer("fc", Output(n_out=n_classes, loss="mcxent",
                             activation="softmax"), "head_pool")
    conf = (g.set_outputs("fc")
            .set_input_types(InputType.convolutional(image_size, image_size,
                                                     3))
            .build())
    return ComputationGraph(conf).init()



def resnet50(seed: int = 42, n_classes: int = 1000, image_size: int = 224,
             dtype: Optional[DtypePolicy] = None,
             updater=None) -> ComputationGraph:
    """ResNet-50 v1 (baseline config #2): bottleneck stages [3, 4, 6, 3]."""
    return _resnet([3, 4, 6, 3], _bottleneck, True, image_size=image_size,
                   n_classes=n_classes, seed=seed, dtype=dtype,
                   updater=updater)


def resnet18(seed: int = 42, n_classes: int = 10, image_size: int = 32,
             dtype: Optional[DtypePolicy] = None,
             updater=None) -> ComputationGraph:
    """ResNet-18 (baseline config #5's CIFAR-10 model): basic-block stages
    [2, 2, 2, 2]; defaults sized for CIFAR."""
    return _resnet([2, 2, 2, 2], _basic_block, False, image_size=image_size,
                   n_classes=n_classes, seed=seed, dtype=dtype,
                   updater=updater)


def char_rnn(vocab_size: int = 80, hidden: int = 512, n_layers: int = 2,
             seed: int = 42, dtype: Optional[DtypePolicy] = None
             ) -> MultiLayerNetwork:
    """GravesLSTM char-RNN (baseline config #3): stacked LSTMs ->
    per-timestep softmax (the reference's LSTMHelpers example shape)."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(Adam(2e-3)).dtype(dtype or BF16)
         .list())
    for _ in range(n_layers):
        b = b.layer(GravesLSTM(n_out=hidden, activation="tanh"))
    conf = (b.layer(RnnOutput(n_out=vocab_size, loss="mcxent",
                              activation="softmax"))
            .set_input_type(InputType.recurrent(vocab_size))
            .build())
    return MultiLayerNetwork(conf).init()


def gpt_mini(vocab_size: int = 80, width: int = 256, n_layers: int = 4,
             n_heads: int = 4, max_len: int = 256,
             max_cache_len: Optional[int] = None, seed: int = 42,
             dtype: Optional[DtypePolicy] = None) -> MultiLayerNetwork:
    """GPT-style decoder-only LM (ROADMAP item 1's workload): one-hot
    tokens -> GptEmbedding (learned positions) -> ``n_layers`` pre-LN
    TransformerBlocks -> streaming-exact softmax head. Serving decode
    carries a fixed-extent KV cache of ``max_cache_len`` (defaults to
    ``max_len``) per block — see nn/layers/attention.py for the decode
    bit-identity contract."""
    from deeplearning4j_tpu.nn.conf.layers_attention import (
        GptEmbedding, GptOutput, TransformerBlock)
    cache = int(max_cache_len or max_len)
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(Adam(3e-4)).dtype(dtype or BF16)
         .list()
         .layer(GptEmbedding(n_out=width, max_len=max_len)))
    for _ in range(n_layers):
        b = b.layer(TransformerBlock(n_heads=n_heads, activation="gelu",
                                     max_cache_len=cache))
    conf = (b.layer(GptOutput(n_out=vocab_size, loss="mcxent",
                              activation="softmax"))
            .set_input_type(InputType.recurrent(vocab_size))
            .build())
    return MultiLayerNetwork(conf).init()


def gpt_mini_draft(vocab_size: int = 80, width: int = 128,
                   n_layers: int = 2, n_heads: int = 2, max_len: int = 256,
                   max_cache_len: Optional[int] = None, seed: int = 43,
                   dtype: Optional[DtypePolicy] = None) -> MultiLayerNetwork:
    """Draft-sized companion to ``gpt_mini`` for speculative decode
    (serving/decode.py): the SAME vocab/tokenizer contract — acceptance
    is exact argmax match against the target, so the two nets must index
    the same token space — at half the width and depth, so a draft
    forward costs a fraction of a target forward. Pass the target's
    ``vocab_size``/``max_cache_len`` when building the pair; the decode
    engine rejects a vocab mismatch at construction."""
    return gpt_mini(vocab_size=vocab_size, width=width, n_layers=n_layers,
                    n_heads=n_heads, max_len=max_len,
                    max_cache_len=max_cache_len, seed=seed, dtype=dtype)


def gpt_mini_tp_rules():
    """Tensor-parallel placement for ``gpt_mini`` (regex form,
    parallel/tensor.py match semantics, first match wins): column-parallel
    QKV + MLP up-projection (last axis on "model"), row-parallel output
    projection + MLP down-projection (first axis on "model"); embeddings
    and the LM head shard column-wise; norms/biases replicate via the
    default rule."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"\['W[qkv]'\]", P(None, "model")),
        (r"\['W1'\]", P(None, "model")),
        (r"\['Wo'\]", P("model", None)),
        (r"\['W2'\]", P("model", None)),
        (r"\['W(tok|pos)'\]", P(None, "model")),
    ]


def vgg16(seed: int = 42, n_classes: int = 1000, image_size: int = 224,
          dtype: Optional[DtypePolicy] = None,
          updater=None) -> MultiLayerNetwork:
    """VGG-16 (TrainedModels.java VGG16 parity: the reference ships the
    architecture + preprocessing for its pretrained zoo entry
    deeplearning4j-modelimport/.../trainedmodels/TrainedModels.java).
    Pretrained ImageNet weights enter through the Keras importer
    (modelimport/keras.py) — this builder provides the canonical
    architecture; ``vgg16_preprocess`` the matching input pipeline."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater or Nesterovs(0.01, 0.9))
         .dtype(dtype or BF16).activation("relu")
         .list())
    blocks = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    for n_convs, ch in blocks:
        for _ in range(n_convs):
            b = b.layer(Convolution2D(n_out=ch, kernel=(3, 3), mode="same",
                                      activation="relu"))
        b = b.layer(Subsampling(kernel=(2, 2), stride=(2, 2),
                                pooling="max"))
    conf = (b.layer(Dense(n_out=4096, activation="relu"))
            .layer(Dense(n_out=4096, activation="relu"))
            .layer(Output(n_out=n_classes, loss="mcxent",
                          activation="softmax"))
            .set_input_type(InputType.convolutional(image_size, image_size,
                                                    3))
            .build())
    return MultiLayerNetwork(conf).init()


# VGG16 per-channel ImageNet means, RGB order (TrainedModels.java
# VGG16.getPreProcessor parity: subtract these from RGB inputs)
VGG16_MEAN_RGB = (123.68, 116.779, 103.939)


def vgg16_preprocess(images):
    """[b, h, w, 3] RGB uint8/float -> mean-subtracted float32 (the
    reference's VGG16 pre-processor semantics, NHWC)."""
    import numpy as np
    x = np.asarray(images, np.float32)
    return x - np.asarray(VGG16_MEAN_RGB, np.float32)
