"""Model zoo: canonical network builders (reference parity:
TrainedModels.java / ModelGuesser.java model-zoo hooks, and the configs
BASELINE.md measures — LeNet-MNIST, ResNet-50, GravesLSTM char-RNN)."""

from deeplearning4j_tpu.zoo.models import (
    BF16,
    F32,
    VGG16_MEAN_RGB,
    char_rnn,
    gpt_mini,
    gpt_mini_draft,
    gpt_mini_tp_rules,
    lenet,
    mnist_mlp,
    resnet18,
    resnet50,
    vgg16,
    vgg16_preprocess,
)

__all__ = ["BF16", "F32", "VGG16_MEAN_RGB", "char_rnn", "gpt_mini",
           "gpt_mini_draft", "gpt_mini_tp_rules", "lenet", "mnist_mlp",
           "resnet18", "resnet50", "vgg16", "vgg16_preprocess"]
