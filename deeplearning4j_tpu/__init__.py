"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A brand-new framework with the capability surface of Deeplearning4j
(builder-style configuration -> init() -> fit()/output()/evaluate(), a full
layer zoo, DAG computation graphs, updaters, listeners, early stopping,
checkpointing, Keras import, embeddings, and distributed training), designed
idiomatically for TPUs on JAX/XLA/Pallas:

- configs are pure data (dataclasses with JSON round-trip),
- parameters and optimizer state are pytrees,
- ``fit()`` compiles ONE jitted train step (forward + backward + update fused
  into a single XLA program),
- device-side loops (LSTM time steps) are ``lax.scan``,
- parallelism is expressed as shardings over a ``jax.sharding.Mesh`` with XLA
  collectives over ICI/DCN (replacing ParallelWrapper / Spark parameter
  averaging / Aeron in the reference).

Reference capability map: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.ops import activations, losses, initializers
from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import ComputationGraph
