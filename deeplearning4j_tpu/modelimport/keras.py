"""Keras (1.x and 2.x) JSON+HDF5 -> TPU-native network import.

Parity: KerasModelImport.java:48-231 (Sequential -> MultiLayerNetwork,
functional Model -> ComputationGraph), KerasModel.java /
KerasSequentialModel.java (config translation), KerasLayer.java (layer
registry + Theano/TensorFlow weight-layout permutations).

Supported layers: InputLayer, Dense, Activation, Dropout, Flatten,
Conv2D/Convolution2D, MaxPooling2D, AveragePooling2D, ZeroPadding2D,
GlobalMax/AveragePooling2D, BatchNormalization, Embedding, LSTM, and (for
functional graphs) Merge/Concatenate/Add/Multiply/Subtract.

Weight-layout conversions (KerasLayer.java analogue):
- Dense: kernel (in, out) -> W directly; channels_first models get their
  first post-Flatten Dense's rows permuted from (c, h, w) to our NHWC
  (h, w, c) flatten order.
- Conv2D: channels_last kernels are HWIO (ours); channels_first /
  Theano-ordered kernels (O, I, kh, kw) are transposed to HWIO.
- LSTM Keras 2: kernel/recurrent_kernel/bias are gate-ordered (i, f, c, o);
  ours is (i, f, o, g=c) — columns permuted. Keras 1 stores 12 per-gate
  arrays (W_i, U_i, b_i, W_c, ...) which are concatenated the same way.
  Peepholes (absent in Keras) are zero, which disables them exactly.
- BatchNormalization: (gamma, beta, moving_mean, moving_variance) ->
  params gamma/beta + state mean/var.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.layers_conv import (
    BatchNorm,
    Convolution2D,
    GlobalPooling,
    Subsampling,
    ZeroPadding,
)
from deeplearning4j_tpu.nn.conf.layers_recurrent import (
    GravesLSTM, RnnOutput, TimeDistributedDense)
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForward
from deeplearning4j_tpu.nn.conf.vertices import (
    ElementWiseVertex,
    MergeVertex,
    PreprocessorVertex,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class KerasImportError(Exception):
    pass


_ACTIVATIONS = {
    "linear": "identity",
    "relu": "relu",
    "tanh": "tanh",
    "sigmoid": "sigmoid",
    "softmax": "softmax",
    "hard_sigmoid": "hardsigmoid",
    "softplus": "softplus",
    "softsign": "softsign",
    "elu": "elu",
    "selu": "selu",
    "swish": "swish",
    "gelu": "gelu",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse",
    "mse": "mse",
    "mean_absolute_error": "mae",
    "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "hinge": "hinge",
    "squared_hinge": "squaredhinge",
    "kullback_leibler_divergence": "kldivergence",
    "poisson": "poisson",
    "cosine_proximity": "cosineproximity",
}


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if name not in _ACTIVATIONS:
        raise KerasImportError(f"Unsupported Keras activation '{name}'")
    return _ACTIVATIONS[name]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _padding_mode(cfg) -> str:
    mode = cfg.get("padding", cfg.get("border_mode", "valid"))
    if mode == "valid":
        return "truncate"
    if mode == "same":
        return "same"
    raise KerasImportError(f"Unsupported Keras padding mode '{mode}'")


def _channels_first(cfg, default: bool) -> bool:
    fmt = cfg.get("data_format", cfg.get("dim_ordering"))
    if fmt in ("channels_first", "th"):
        return True
    if fmt in ("channels_last", "tf"):
        return False
    return default


@dataclass
class _Ctx:
    """Translation context threaded through the layer walk."""

    channels_first: bool = False          # model-wide default ordering
    shape: Optional[Tuple[int, int, int]] = None   # (h, w, c) if conv-land
    flatten_cf: Optional[Tuple[int, int, int]] = None  # pending row permute
    loss: Optional[str] = None            # from training_config


@dataclass
class _Translated:
    conf: object                          # our layer/vertex conf (or None)
    keras_name: str
    loader: Optional[Callable] = None     # loader(net, our_name, arrays)
    is_vertex: bool = False
    preprocessor: object = None           # for Sequential flatten handling


# --------------------------------------------------------------- loaders
def _set_params(net, name, **arrays):
    import jax.numpy as jnp

    target = net.params.get(name)
    if target is None:
        raise KerasImportError(f"Layer '{name}' has no parameters to set")
    for k, v in arrays.items():
        if k not in target:
            raise KerasImportError(f"Layer '{name}' has no parameter '{k}'")
        if tuple(target[k].shape) != tuple(v.shape):
            raise KerasImportError(
                f"Layer '{name}' param '{k}': shape {v.shape} does not "
                f"match expected {tuple(target[k].shape)}")
        target[k] = jnp.asarray(v, target[k].dtype)


def _set_state(net, name, **arrays):
    import jax.numpy as jnp

    target = net.state.get(name)
    for k, v in arrays.items():
        target[k] = jnp.asarray(v, target[k].dtype)


def _dense_loader(ctx_flatten_cf):
    def load(net, name, arrays):
        if not arrays:
            return
        W = np.asarray(arrays[0])
        if ctx_flatten_cf is not None:
            h, w, c = ctx_flatten_cf
            if W.shape[0] == h * w * c:
                # rows stored in (c, h, w) flatten order -> our (h, w, c)
                perm = (np.arange(h * w * c)
                        .reshape(c, h, w).transpose(1, 2, 0).reshape(-1))
                W = W[perm]
        kw = {"W": W}
        if len(arrays) > 1:
            kw["b"] = np.asarray(arrays[1])
        _set_params(net, name, **kw)
    return load


def _conv_loader(theano_kernel):
    """Kernel layout conversion (KerasConvolution.java:108-137 parity).

    Keras 2 stores conv kernels HWIO regardless of data_format — ours is
    HWIO, so no transform. Keras 1 'tf' dim ordering is also HWIO. Keras 1
    'th' (Theano) kernels are (out, in, kh, kw) AND Theano rotates filters
    180 degrees before application (KerasConvolution.java:124-137), so the
    spatial window is flipped then transposed to HWIO."""
    def load(net, name, arrays):
        if not arrays:
            return
        K = np.asarray(arrays[0])
        if theano_kernel:
            # (out, in, kh, kw): rotate each filter 180deg, then -> HWIO
            K = K[:, :, ::-1, ::-1].transpose(2, 3, 1, 0)
        kw = {"W": K}
        if len(arrays) > 1:
            kw["b"] = np.asarray(arrays[1])
        _set_params(net, name, **kw)
    return load


def _lstm_permute_gates(a, n, axis):
    """Keras gate order (i, f, c, o) -> ours (i, f, o, g=c) along axis."""
    blocks = np.split(np.asarray(a), 4, axis=axis)
    i, f, c, o = blocks
    return np.concatenate([i, f, o, c], axis=axis)


def _lstm_loader():
    def load(net, name, arrays):
        if not arrays:
            return
        if len(arrays) == 3:        # Keras 2: kernel, recurrent, bias
            Wx, Wh, b = (np.asarray(a) for a in arrays)
        elif len(arrays) == 12:     # Keras 1: per-gate W/U/b in i,c,f,o
            Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = (
                np.asarray(a) for a in arrays)
            Wx = np.concatenate([Wi, Wf, Wc, Wo], axis=1)
            Wh = np.concatenate([Ui, Uf, Uc, Uo], axis=1)
            b = np.concatenate([bi, bf, bc, bo])
        else:
            raise KerasImportError(
                f"LSTM layer '{name}': expected 3 (Keras 2) or 12 (Keras 1)"
                f" weight arrays, got {len(arrays)}")
        n = Wh.shape[0]
        _set_params(net, name,
                    Wx=_lstm_permute_gates(Wx, n, 1),
                    Wh=_lstm_permute_gates(Wh, n, 1),
                    b=_lstm_permute_gates(b, n, 0),
                    p=np.zeros((3, n), np.float32))
    return load


def _bn_loader():
    def load(net, name, arrays):
        arrays = [np.asarray(a) for a in arrays]
        if len(arrays) == 4:
            gamma, beta, mean, var = arrays
        elif len(arrays) == 2:      # scale=False/center=False variants
            gamma, beta = arrays
            mean = var = None
        else:
            raise KerasImportError(
                f"BatchNormalization '{name}': unsupported weight count "
                f"{len(arrays)}")
        _set_params(net, name, gamma=gamma, beta=beta)
        if mean is not None:
            _set_state(net, name, mean=mean, var=var)
    return load


def _embedding_loader():
    def load(net, name, arrays):
        if arrays:
            _set_params(net, name, W=np.asarray(arrays[0]))
    return load


# ----------------------------------------------------------- translation
def _input_type_from_shape(shape, channels_first) -> Optional[InputType]:
    """batch_input_shape (without batch dim) -> InputType."""
    dims = [d for d in shape if d is not None]
    if len(dims) == 3:
        if channels_first:
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(f, t)
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    return None


def _update_shape_conv(ctx, kh, kw, sh, sw, mode, n_out=None, pad=(0, 0)):
    if ctx.shape is None:
        return
    from deeplearning4j_tpu.nn.conf.layers_conv import out_size
    h, w, c = ctx.shape
    ph, pw = pad
    ctx.shape = (out_size(h, kh, sh, ph, mode), out_size(w, kw, sw, pw, mode),
                 n_out if n_out is not None else c)


def _translate_layer(class_name: str, cfg: dict, ctx: _Ctx, *,
                     is_output: bool) -> List[_Translated]:
    """One Keras layer dict -> zero or more of our layer confs + loaders."""
    name = cfg.get("name", class_name.lower())
    out: List[_Translated] = []

    if class_name in ("InputLayer",):
        shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
        if shape is not None:
            cf = _channels_first(cfg, ctx.channels_first)
            it = _input_type_from_shape(shape[1:], cf)
            if it is not None and it.kind == "convolutional":
                ctx.shape = (it.height, it.width, it.channels)
        return out

    if class_name == "Dense":
        n_out = int(cfg.get("units", cfg.get("output_dim")))
        act = _act(cfg.get("activation", "linear"))
        flatten_cf = ctx.flatten_cf
        ctx.flatten_cf = None
        use_bias = bool(cfg.get("use_bias", cfg.get("bias", True)))
        if is_output:
            loss = ctx.loss or ("mcxent" if act == "softmax" else "mse")
            conf = L.Output(name=name, n_out=n_out, activation=act,
                            loss=loss, has_bias=use_bias)
        else:
            conf = L.Dense(name=name, n_out=n_out, activation=act,
                           has_bias=use_bias)
        out.append(_Translated(conf, name, _dense_loader(flatten_cf)))
        return out

    if class_name == "Activation":
        out.append(_Translated(
            L.ActivationLayer(name=name,
                              activation=_act(cfg.get("activation"))),
            name))
        return out

    if class_name == "Dropout":
        rate = float(cfg.get("rate", cfg.get("p", 0.5)))
        out.append(_Translated(L.Dropout(name=name, dropout=rate), name))
        return out

    if class_name == "Flatten":
        cf = _channels_first(cfg, ctx.channels_first)
        if cf and ctx.shape is not None:
            ctx.flatten_cf = ctx.shape
        # shape adapter inserted automatically (Sequential) or via
        # PreprocessorVertex (functional)
        if ctx.shape is not None:
            h, w, c = ctx.shape
            prep = CnnToFeedForward(h, w, c)
        else:
            prep = CnnToFeedForward()
        out.append(_Translated(None, name, preprocessor=prep))
        return out

    if class_name in ("Conv2D", "Convolution2D"):
        cf = _channels_first(cfg, ctx.channels_first)
        # Keras-1-only config keys identify a Keras 1 file; only Keras 1
        # Theano-ordered kernels need a layout transform (see _conv_loader)
        keras1 = "nb_filter" in cfg or "dim_ordering" in cfg
        theano_kernel = keras1 and cf
        n_out = int(cfg.get("filters", cfg.get("nb_filter")))
        if "kernel_size" in cfg:
            kh, kw = _pair(cfg["kernel_size"])
        else:
            kh, kw = int(cfg["nb_row"]), int(cfg["nb_col"])
        sh, sw = _pair(cfg.get("strides", cfg.get("subsample", (1, 1))))
        mode = _padding_mode(cfg)
        act = _act(cfg.get("activation", "linear"))
        use_bias = bool(cfg.get("use_bias", cfg.get("bias", True)))
        conf = Convolution2D(name=name, n_out=n_out, kernel=(kh, kw),
                             stride=(sh, sw), mode=mode, activation=act,
                             has_bias=use_bias)
        _update_shape_conv(ctx, kh, kw, sh, sw, mode, n_out)
        out.append(_Translated(conf, name, _conv_loader(theano_kernel)))
        return out

    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        kh, kw = _pair(cfg.get("pool_size", (2, 2)))
        strides = cfg.get("strides")
        sh, sw = _pair(strides) if strides else (kh, kw)
        mode = _padding_mode(cfg)
        pooling = "max" if class_name.startswith("Max") else "avg"
        conf = Subsampling(name=name, kernel=(kh, kw), stride=(sh, sw),
                           pooling=pooling, mode=mode)
        _update_shape_conv(ctx, kh, kw, sh, sw, mode)
        out.append(_Translated(conf, name))
        return out

    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and len(pad) == 2 \
                and all(isinstance(p, (list, tuple)) for p in pad):
            (pt, pb), (pl, pr) = pad
        else:
            ph, pw = _pair(pad)
            pt = pb = ph
            pl = pr = pw
        conf = ZeroPadding(name=name, pad=(int(pt), int(pb), int(pl),
                                           int(pr)))
        if ctx.shape is not None:
            h, w, c = ctx.shape
            ctx.shape = (h + pt + pb, w + pl + pr, c)
        out.append(_Translated(conf, name))
        return out

    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D"):
        pooling = "max" if "Max" in class_name else "avg"
        conf = GlobalPooling(name=name, pooling=pooling)
        if ctx.shape is not None:
            ctx.shape = None
        out.append(_Translated(conf, name))
        return out

    if class_name == "BatchNormalization":
        eps = float(cfg.get("epsilon", 1e-3))
        momentum = float(cfg.get("momentum", cfg.get("mode", 0.99))
                         if not isinstance(cfg.get("momentum"), dict)
                         else 0.99)
        conf = BatchNorm(name=name, eps=eps, decay=momentum,
                         activation="identity")
        out.append(_Translated(conf, name, _bn_loader()))
        return out

    if class_name == "Embedding":
        n_in = int(cfg.get("input_dim"))
        n_out = int(cfg.get("output_dim"))
        conf = L.Embedding(name=name, n_in=n_in, n_out=n_out)
        out.append(_Translated(conf, name, _embedding_loader()))
        return out

    if class_name in ("GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        # [b, t, f] -> [b, f] over the time axis (KerasLayer.java:225-230
        # maps these to GlobalPoolingLayer)
        pooling = "max" if "Max" in class_name else "avg"
        out.append(_Translated(GlobalPooling(name=name, pooling=pooling),
                               name))
        return out

    if class_name in ("TimeDistributedDense", "TimeDistributed"):
        # Keras 1 TimeDistributedDense, or the Keras 2 TimeDistributed
        # wrapper around a Dense (KerasLayer.java:206-212 maps both to
        # DenseLayer; here a first-class per-timestep dense)
        inner = cfg.get("layer")
        if class_name == "TimeDistributed":
            if not inner or inner.get("class_name") != "Dense":
                raise KerasImportError(
                    "TimeDistributed is only supported around Dense "
                    f"(got {inner and inner.get('class_name')})")
            dcfg = inner.get("config", {})
        else:
            dcfg = cfg
        n_out = int(dcfg.get("units", dcfg.get("output_dim")))
        act = _act(dcfg.get("activation", "linear"))
        use_bias = bool(dcfg.get("use_bias", dcfg.get("bias", True)))
        if is_output:
            loss = ctx.loss or ("mcxent" if act == "softmax" else "mse")
            conf = RnnOutput(name=name, n_out=n_out, activation=act,
                             loss=loss, has_bias=use_bias)
        else:
            conf = TimeDistributedDense(name=name, n_out=n_out,
                                        activation=act, has_bias=use_bias)
        out.append(_Translated(conf, name, _dense_loader(None)))
        return out

    if class_name == "Masking":
        # masking flows via the DataSet feature mask in this framework —
        # the layer itself is shape-identity, but silently processing
        # padded steps as data would diverge from the source model
        import warnings
        warnings.warn(
            f"Keras Masking layer '{name}' imported as identity: supply "
            "the padding pattern as a DataSet feature mask (features_mask) "
            "or padded timesteps WILL be processed as real data",
            UserWarning)
        return out

    if class_name == "LSTM":
        n_out = int(cfg.get("units", cfg.get("output_dim")))
        act = _act(cfg.get("activation", "tanh"))
        gate = _act(cfg.get("recurrent_activation",
                            cfg.get("inner_activation", "hard_sigmoid")))
        conf = GravesLSTM(name=name, n_out=n_out, activation=act,
                          gate_activation=gate)
        if not cfg.get("return_sequences", False):
            from deeplearning4j_tpu.nn.conf.layers_recurrent import (
                LastTimeStep)
            out.append(_Translated(conf, name, _lstm_loader()))
            out.append(_Translated(LastTimeStep(name=f"{name}_last",
                                                n_out=n_out),
                                   f"{name}_last"))
            return out
        out.append(_Translated(conf, name, _lstm_loader()))
        return out

    raise KerasImportError(f"Unsupported Keras layer type '{class_name}'")


def _parse_model_config(config) -> Tuple[str, list, dict]:
    """Returns (model_class, layer dicts, extras)."""
    if isinstance(config, str):
        config = json.loads(config)
    cls = config.get("class_name")
    cfg = config.get("config")
    if cls == "Sequential":
        layers = cfg if isinstance(cfg, list) else cfg.get("layers", [])
        return "Sequential", layers, {}
    if cls in ("Model", "Functional"):
        return "Model", cfg.get("layers", []), {
            "input_layers": cfg.get("input_layers", []),
            "output_layers": cfg.get("output_layers", []),
        }
    raise KerasImportError(f"Unsupported model class '{cls}'")


def _extract_loss(training_config: Optional[dict]) -> Optional[str]:
    if not training_config:
        return None
    loss = training_config.get("loss")
    if isinstance(loss, dict):
        loss = next(iter(loss.values()), None)
    if isinstance(loss, str):
        return _LOSSES.get(loss)
    return None


# ------------------------------------------------------------ sequential
def import_keras_sequential_model_and_weights(
        model_json, weights: Dict[str, List[np.ndarray]], *,
        training_loss: Optional[str] = None) -> MultiLayerNetwork:
    """Keras Sequential JSON + per-layer weight arrays -> trained
    MultiLayerNetwork (KerasModelImport.importKerasSequentialModelAndWeights
    parity)."""
    cls, layer_dicts, _ = _parse_model_config(model_json)
    if cls != "Sequential":
        raise KerasImportError(
            "Not a Sequential model; use import_keras_model_and_weights")

    ctx = _Ctx(loss=training_loss)
    translated: List[_Translated] = []
    input_type = None
    for i, ld in enumerate(layer_dicts):
        class_name = ld["class_name"]
        cfg = dict(ld.get("config", {}))
        if i == 0 or input_type is None:
            shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
            if shape is not None:
                cf = _channels_first(cfg, ctx.channels_first)
                it = _input_type_from_shape(shape[1:], cf)
                if it is not None and input_type is None:
                    input_type = it
                    if it.kind == "convolutional":
                        ctx.shape = (it.height, it.width, it.channels)
        is_output = (i == len(layer_dicts) - 1)
        translated.extend(
            _translate_layer(class_name, cfg, ctx, is_output=is_output))

    builder = NeuralNetConfiguration.builder().list()
    prep_for_next = None
    layer_idx = 0
    loaders: List[Tuple[str, str, Callable]] = []  # (keras, ours, loader)
    for t in translated:
        if t.conf is None:
            prep_for_next = t.preprocessor
            continue
        builder = builder.layer(t.conf)
        if prep_for_next is not None:
            builder = builder.input_preprocessor(layer_idx, prep_for_next)
            prep_for_next = None
        if t.loader is not None:
            loaders.append((t.keras_name, t.conf.name, t.loader))
        layer_idx += 1
    if input_type is not None:
        builder = builder.set_input_type(input_type)
    net = MultiLayerNetwork(builder.build()).init()

    for keras_name, our_name, loader in loaders:
        loader(net, our_name, weights.get(keras_name, []))
    return net


def import_keras_sequential_model(path: str) -> MultiLayerNetwork:
    """Import a full-model Keras HDF5 file (architecture + weights)."""
    with Hdf5Archive(path) as ar:
        config = ar.model_config()
        if config is None:
            raise KerasImportError(
                f"{path} has no model_config attribute (weights-only file? "
                "use import_keras_sequential_model_and_weights with a JSON)")
        loss = _extract_loss(ar.training_config())
        return import_keras_sequential_model_and_weights(
            config, ar.all_weights(), training_loss=loss)


# ------------------------------------------------------------ functional
def _inbound_names(layer_dict) -> List[str]:
    """Normalize Keras 1/2 inbound_nodes to a list of input layer names."""
    nodes = layer_dict.get("inbound_nodes", [])
    if not nodes:
        return []
    node = nodes[0]
    names = []
    if isinstance(node, dict):    # very new Keras: {"args": [...]}
        raise KerasImportError("Unsupported inbound_nodes format (dict)")
    for entry in node:
        if isinstance(entry, (list, tuple)):
            names.append(entry[0])
        else:
            names.append(entry)
    return names


def import_keras_model_and_weights(
        model_json, weights: Dict[str, List[np.ndarray]], *,
        training_loss: Optional[str] = None) -> ComputationGraph:
    """Keras functional-Model JSON + weights -> ComputationGraph
    (KerasModelImport.importKerasModelAndWeights parity)."""
    cls, layer_dicts, extras = _parse_model_config(model_json)
    if cls != "Model":
        raise KerasImportError(
            "Not a functional model; use "
            "import_keras_sequential_model_and_weights")

    out_names = {e[0] if isinstance(e, (list, tuple)) else e
                 for e in extras["output_layers"]}
    in_names = [e[0] if isinstance(e, (list, tuple)) else e
                for e in extras["input_layers"]]

    g = NeuralNetConfiguration.builder().graph_builder()
    g.add_inputs(*in_names)

    ctx = _Ctx(loss=training_loss)
    input_types = []
    loaders: List[Tuple[str, str, Callable]] = []
    # keras layer name -> final translated vertex name: when a layer's
    # translation ends in an extra vertex (e.g. LSTM with
    # return_sequences=False appends a LastTimeStep), later layers and
    # set_outputs must resolve the Keras name to that LAST vertex, not the
    # intermediate one (otherwise the full-sequence output leaks through)
    alias: Dict[str, str] = {}
    for ld in layer_dicts:
        class_name = ld["class_name"]
        cfg = dict(ld.get("config", {}))
        name = cfg.get("name", ld.get("name"))
        inputs = [alias.get(i, i) for i in _inbound_names(ld)]

        if class_name == "InputLayer":
            shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
            cf = _channels_first(cfg, ctx.channels_first)
            it = _input_type_from_shape(shape[1:], cf) if shape else None
            if it is None:
                raise KerasImportError(
                    f"InputLayer '{name}' has no batch_input_shape")
            input_types.append(it)
            if it.kind == "convolutional":
                ctx.shape = (it.height, it.width, it.channels)
            continue

        if class_name in ("Concatenate", "Merge"):
            mode = cfg.get("mode", "concat")
            if class_name == "Concatenate" or mode == "concat":
                g.add_vertex(name, MergeVertex(), *inputs)
            elif mode in ("sum", "add"):
                g.add_vertex(name, ElementWiseVertex(op="add"), *inputs)
            elif mode == "mul":
                g.add_vertex(name, ElementWiseVertex(op="product"), *inputs)
            else:
                raise KerasImportError(f"Unsupported Merge mode '{mode}'")
            continue
        if class_name == "Add":
            g.add_vertex(name, ElementWiseVertex(op="add"), *inputs)
            continue
        if class_name == "Multiply":
            g.add_vertex(name, ElementWiseVertex(op="product"), *inputs)
            continue
        if class_name == "Subtract":
            g.add_vertex(name, ElementWiseVertex(op="sub"), *inputs)
            continue

        translated = _translate_layer(class_name, cfg, ctx,
                                      is_output=name in out_names)
        prev = inputs
        for t in translated:
            if t.conf is None:
                g.add_vertex(t.keras_name,
                             PreprocessorVertex(
                                 preprocessor=t.preprocessor),
                             *prev)
                prev = [t.keras_name]
                continue
            g.add_layer(t.conf.name, t.conf, *prev)
            if t.loader is not None:
                loaders.append((t.keras_name, t.conf.name, t.loader))
            prev = [t.conf.name]
        if prev and prev[0] != name:
            alias[name] = prev[0]

    out_resolved = [alias.get(n, n) for n in
                    (e[0] if isinstance(e, (list, tuple)) else e
                     for e in extras["output_layers"])]
    # KerasLoss parity (modelimport KerasLoss.java): an output that is not
    # a loss-bearing layer (e.g. a merge vertex or bare activation) gets a
    # terminal LossLayer with the training loss appended — identity
    # activation, so inference outputs are unchanged but fit() works
    final_outputs = []
    for n in out_resolved:
        vconf = g.get_vertex(n)
        has_loss = hasattr(vconf, "loss") if vconf is not None else False
        if has_loss:
            final_outputs.append(n)
        else:
            loss_name = f"{n}_loss"
            g.add_layer(loss_name,
                        L.LossLayer(name=loss_name,
                                    loss=ctx.loss or "mse",
                                    activation="identity"), n)
            final_outputs.append(loss_name)
    g.set_outputs(*final_outputs)
    if input_types:
        g.set_input_types(*input_types)
    net = ComputationGraph(g.build()).init()

    for keras_name, our_name, loader in loaders:
        loader(net, our_name, weights.get(keras_name, []))
    return net


def import_keras_model(path: str) -> ComputationGraph:
    """Import a full functional-model Keras HDF5 file."""
    with Hdf5Archive(path) as ar:
        config = ar.model_config()
        if config is None:
            raise KerasImportError(f"{path} has no model_config attribute")
        loss = _extract_loss(ar.training_config())
        return import_keras_model_and_weights(
            config, ar.all_weights(), training_loss=loss)
