"""HDF5 archive reading for Keras model files.

Parity: deeplearning4j-modelimport Hdf5Archive.java (266 LoC, JavaCPP
libhdf5) — here h5py. Understands both full-model files (``model_config``
root attribute + ``model_weights`` group) and weights-only files (layer
groups at the root), Keras 1.x and 2.x.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np


def _decode(v):
    if isinstance(v, bytes):
        return v.decode("utf-8")
    if isinstance(v, np.ndarray):
        return [_decode(x) for x in v.tolist()]
    return v


class Hdf5Archive:
    """Read-only view of a Keras .h5 file."""

    def __init__(self, path: str):
        import h5py

        self._f = h5py.File(path, "r")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- config
    def read_attr(self, name: str) -> Optional[str]:
        if name not in self._f.attrs:
            return None
        return _decode(self._f.attrs[name])

    def model_config(self) -> Optional[dict]:
        raw = self.read_attr("model_config")
        return None if raw is None else json.loads(raw)

    def training_config(self) -> Optional[dict]:
        raw = self.read_attr("training_config")
        return None if raw is None else json.loads(raw)

    def keras_version(self) -> Optional[str]:
        v = self.read_attr("keras_version")
        if v is None and "model_weights" in self._f:
            v = _decode(self._f["model_weights"].attrs.get("keras_version",
                                                          b"")) or None
        return v

    # ------------------------------------------------------------ weights
    def _weight_root(self):
        return (self._f["model_weights"] if "model_weights" in self._f
                else self._f)

    def layer_names(self) -> List[str]:
        root = self._weight_root()
        if "layer_names" in root.attrs:
            return [_decode(n) for n in root.attrs["layer_names"]]
        return list(root.keys())

    def layer_weights(self, layer_name: str) -> List[np.ndarray]:
        """The layer's weight arrays in Keras's stored (build) order."""
        root = self._weight_root()
        if layer_name not in root:
            return []
        g = root[layer_name]
        if "weight_names" in g.attrs:
            names = [_decode(n) for n in g.attrs["weight_names"]]
        else:
            names = []
            g.visit(lambda n: names.append(n)
                    if hasattr(g[n], "shape") else None)
        return [np.asarray(g[n]) for n in names]

    def all_weights(self) -> Dict[str, List[np.ndarray]]:
        return {n: self.layer_weights(n) for n in self.layer_names()}
