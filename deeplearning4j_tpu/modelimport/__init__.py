"""Keras HDF5 model import (parity: deeplearning4j-modelimport, 5,405 LoC
— KerasModelImport.java:48-231 entry points, KerasModel.java config
translation, KerasLayer.java weight-layout permutations, Hdf5Archive.java
HDF5 reading)."""

from deeplearning4j_tpu.modelimport.keras import (
    KerasImportError,
    import_keras_model,
    import_keras_model_and_weights,
    import_keras_sequential_model,
    import_keras_sequential_model_and_weights,
)

__all__ = [
    "KerasImportError",
    "import_keras_model",
    "import_keras_model_and_weights",
    "import_keras_sequential_model",
    "import_keras_sequential_model_and_weights",
]
