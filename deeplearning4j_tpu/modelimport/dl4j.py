"""DL4J checkpoint (.zip) importer — reads the reference's ModelSerializer
format into a TPU-native network.

Format (util/ModelSerializer.java:79-95): a ZIP containing
``configuration.json`` (the Jackson-serialized MultiLayerConfiguration),
``coefficients.bin`` (ONE flattened parameter row vector written with
``Nd4j.write`` :99) and optionally ``updaterState.bin`` (:118). The flat
vector concatenates every layer's parameters in layer order, each layer
using its ParamInitializer's view layout:

- Dense/Output (DefaultParamInitializer.java:60-88):
  [W (nIn*nOut, 'f'-order [nIn, nOut]), b (nOut)]
- Convolution (ConvolutionParamInitializer.java:62-85): [b (nOut),
  W ('c'-order [nOut, nIn, kH, kW])] -> transposed to our HWIO
- BatchNorm (BatchNormalizationParamInitializer.java:56-70):
  [gamma, beta, mean, var] (each nOut; mean/var -> layer STATE here)
- GravesLSTM (GravesLSTMParamInitializer.java:88-96): [W_in ('f'
  [nLast, 4nL]), RW ('f' [nL, 4nL+3]), b (4nL)]. DL4J's gate column
  order is [g(candidate), f, o, i] with peephole columns
  [wFF, wOO, wGG] = [forget, output, input-gate] peepholes
  (LSTMHelpers.java:59-61,174-231); ours is [i, f, o, g] with
  p = [input, forget, output], so columns are permuted on load.

ND4J binary array layout (BaseDataBuffer.write of the 0.5-0.8 era): two
DataBuffers back to back — the shapeInfo int buffer then the data buffer —
each as {writeUTF(allocation mode), writeInt(length), writeUTF(type name),
big-endian elements}. The reader tolerates the allocation-mode header
being present or absent (it changed across point releases).

configuration.json field names vary across the reference's releases
(plain strings in 0.5/0.6, @class-wrapped activation/loss objects in
0.7/0.8); the translator accepts both (RegressionTest{050,060,071}.java
is the parity surface). The format is pinned two ways: a HAND-PACKED
golden fixture derived byte-by-byte from the Java write path
(tests/fixtures/build_dl4j_golden.py + dl4j_mlp_golden.zip,
tests/test_dl4j_golden.py — importer must read it and the writer must
reproduce its coefficients.bin byte-identically), plus symmetric
round-trip tests through write_dl4j_zip for the wider layer zoo.
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Optional

import numpy as np

_ALLOC_MODES = {"HEAP", "JAVACPP", "DIRECT", "WORKSPACE", "MIXED_DATA_TYPES",
                "LONG_SHAPE"}


# ----------------------------------------------------------- nd4j binary
def _read_utf(f) -> str:
    n = struct.unpack(">H", f.read(2))[0]
    return f.read(n).decode("utf-8")


def _write_utf(f, s: str):
    b = s.encode("utf-8")
    f.write(struct.pack(">H", len(b)))
    f.write(b)


_DTYPES = {"INT": (">i4", 4), "FLOAT": (">f4", 4), "DOUBLE": (">f8", 8),
           "LONG": (">i8", 8)}


def _read_databuffer(f):
    pos = f.tell()
    try:
        first = _read_utf(f)
        headered = first in _ALLOC_MODES
    except (UnicodeDecodeError, KeyError):
        # headerless variant: the probe read raw int/float bytes (large
        # buffers make the fake "UTF length" huge and non-UTF8)
        headered = False
    if headered:
        length = struct.unpack(">i", f.read(4))[0]
        type_name = _read_utf(f)
    else:
        f.seek(pos)
        length = struct.unpack(">i", f.read(4))[0]
        type_name = _read_utf(f)
    dt, size = _DTYPES[type_name]
    data = np.frombuffer(f.read(length * size), dtype=dt, count=length)
    return data


def _write_databuffer(f, arr: np.ndarray, type_name: str):
    _write_utf(f, "HEAP")
    f.write(struct.pack(">i", arr.size))
    _write_utf(f, type_name)
    f.write(arr.astype(_DTYPES[type_name][0]).tobytes())


def read_nd4j_array(f) -> np.ndarray:
    """Nd4j.read parity: shapeInfo buffer + data buffer."""
    shape_info = _read_databuffer(f).astype(np.int64)
    rank = int(shape_info[0])
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[-1]))
    data = _read_databuffer(f)
    return np.asarray(data).reshape(shape, order="F" if order == "f" else "C")


def write_nd4j_array(f, arr: np.ndarray, dtype: str = "FLOAT"):
    """Nd4j.write parity ('c'-order row vector, as ModelSerializer emits)."""
    arr = np.ascontiguousarray(arr)
    rank = arr.ndim
    shape = list(arr.shape)
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.insert(0, acc)
        acc *= s
    shape_info = np.asarray([rank] + shape + strides + [0, 1, ord("c")],
                            dtype=np.int64)
    _write_databuffer(f, shape_info, "INT")
    _write_databuffer(f, arr.reshape(-1), dtype)


# ------------------------------------------------------- json translation
def _first(d: dict, *names, default=None):
    for n in names:
        if n in d:
            return d[n]
    return default


def _activation_name(layer: dict) -> str:
    a = _first(layer, "activationFn", "activationFunction", "activation")
    if a is None:
        return "identity"
    if isinstance(a, str):
        return a.lower()
    if isinstance(a, dict):
        cls = a.get("@class", "")
        if cls:
            name = cls.rsplit(".", 1)[-1]
            return name.replace("Activation", "").lower()
        # wrapper-object form {"Tanh": {}}
        if len(a) == 1:
            return next(iter(a)).lower()
    return "identity"


_LOSS_MAP = {
    "MCXENT": "mcxent", "LossMCXENT": "mcxent",
    "MSE": "mse", "LossMSE": "mse", "LossL2": "l2",
    "NEGATIVELOGLIKELIHOOD": "mcxent", "LossNegativeLogLikelihood": "mcxent",
    "XENT": "xent", "LossBinaryXENT": "xent",
    "L1": "l1", "LossL1": "l1", "MAE": "mae", "LossMAE": "mae",
}


def _loss_name(layer: dict) -> str:
    lf = _first(layer, "lossFn", "lossFunction", "loss")
    if lf is None:
        return "mcxent"
    if isinstance(lf, str):
        return _LOSS_MAP.get(lf, lf.lower())
    if isinstance(lf, dict):
        cls = lf.get("@class", "")
        if cls:
            return _LOSS_MAP.get(cls.rsplit(".", 1)[-1], "mcxent")
        if len(lf) == 1:
            return _LOSS_MAP.get(next(iter(lf)), "mcxent")
    return "mcxent"


def _unwrap_layer(conf: dict):
    """A NeuralNetConfiguration JSON holds its layer either wrapper-object
    typed ({"layer": {"dense": {...}}}) or @class typed."""
    layer = conf.get("layer", conf)
    if "@class" in layer:
        cls = layer["@class"].rsplit(".", 1)[-1]
        return cls[0].lower() + cls[1:], layer
    if len(layer) == 1:
        k = next(iter(layer))
        if isinstance(layer[k], dict):
            return k, layer[k]
    return None, layer


def _pair(v, default):
    if v is None:
        return default
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def translate_layer(kind: str, ld: dict):
    """One DL4J layer JSON dict -> (our layer config, flat-vector loader).

    The loader takes (flat_segment, params_out, state_out) and fills our
    param/state dicts from the reference's view layout."""
    from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer, Dense,
                                                   Output)
    from deeplearning4j_tpu.nn.conf.layers_conv import (BatchNorm,
                                                        Convolution2D,
                                                        Subsampling)
    from deeplearning4j_tpu.nn.conf.layers_recurrent import (GravesLSTM,
                                                             RnnOutput)

    act = _activation_name(ld)
    n_in = _first(ld, "nin", "nIn", "NIn")
    n_out = _first(ld, "nout", "nOut", "NOut")
    n_in = None if n_in is None else int(n_in)
    n_out = None if n_out is None else int(n_out)

    def _require(**named):
        # fail loudly on a malformed layer dict: slicing with a None
        # bound would silently produce wrong-length parameter views
        missing = [k for k, v in named.items() if v is None]
        if missing:
            raise ValueError(
                f"DL4J-zip import: layer '{kind}' is missing required "
                f"field(s) {missing} in configuration.json")

    if kind in ("dense", "denseLayer"):
        _require(nIn=n_in, nOut=n_out)
        conf = Dense(n_in=n_in, n_out=n_out, activation=act)

        def load(seg, params, state):
            nw = n_in * n_out
            params["W"] = seg[:nw].reshape(n_in, n_out, order="F")
            params["b"] = seg[nw:nw + n_out]
        return conf, load, n_in * n_out + n_out

    if kind in ("output", "outputLayer"):
        _require(nIn=n_in, nOut=n_out)
        conf = Output(n_in=n_in, n_out=n_out, activation=act,
                      loss=_loss_name(ld))

        def load(seg, params, state):
            nw = n_in * n_out
            params["W"] = seg[:nw].reshape(n_in, n_out, order="F")
            params["b"] = seg[nw:nw + n_out]
        return conf, load, n_in * n_out + n_out

    if kind in ("rnnoutput", "rnnOutputLayer", "rnnOutput"):
        _require(nIn=n_in, nOut=n_out)
        conf = RnnOutput(n_in=n_in, n_out=n_out, activation=act,
                         loss=_loss_name(ld))

        def load(seg, params, state):
            nw = n_in * n_out
            params["W"] = seg[:nw].reshape(n_in, n_out, order="F")
            params["b"] = seg[nw:nw + n_out]
        return conf, load, n_in * n_out + n_out

    if kind in ("convolution", "convolutionLayer", "convolution2D"):
        _require(nIn=n_in, nOut=n_out)
        kh, kw = _pair(_first(ld, "kernelSize", "kernel"), (5, 5))
        sh, sw = _pair(_first(ld, "stride"), (1, 1))
        ph, pw = _pair(_first(ld, "padding"), (0, 0))
        mode = str(_first(ld, "convolutionMode", default="truncate")).lower()
        conf = Convolution2D(n_in=n_in, n_out=n_out, kernel=(kh, kw),
                             stride=(sh, sw), padding=(ph, pw),
                             mode=mode if mode in ("same", "strict",
                                                   "truncate") else "truncate",
                             activation=act)
        nw = n_out * n_in * kh * kw

        def load(seg, params, state):
            params["b"] = seg[:n_out]
            W = seg[n_out:n_out + nw].reshape(n_out, n_in, kh, kw, order="C")
            params["W"] = W.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        return conf, load, n_out + nw

    if kind in ("subsampling", "subsamplingLayer"):
        kh, kw = _pair(_first(ld, "kernelSize", "kernel"), (2, 2))
        sh, sw = _pair(_first(ld, "stride"), (2, 2))
        pool = str(_first(ld, "poolingType", default="MAX")).lower()
        conf = Subsampling(kernel=(kh, kw), stride=(sh, sw),
                           pooling="avg" if pool.startswith("avg") else pool)
        return conf, None, 0

    if kind in ("batchNormalization", "batchNorm"):
        if n_out is None and n_in is None:
            raise ValueError(
                "DL4J-zip import: batchNormalization layer carries neither "
                "nIn nor nOut in configuration.json — cannot size "
                "gamma/beta/mean/var")
        f = n_out if n_out else n_in
        conf = BatchNorm(eps=float(_first(ld, "eps", default=1e-5)),
                         decay=float(_first(ld, "decay", default=0.9)),
                         activation=act)

        def load(seg, params, state):
            params["gamma"] = seg[:f]
            params["beta"] = seg[f:2 * f]
            state["mean"] = seg[2 * f:3 * f]
            state["var"] = seg[3 * f:4 * f]
        return conf, load, 4 * f

    if kind in ("gravesLSTM", "graveslstm", "gravesLstm"):
        _require(nIn=n_in, nOut=n_out)
        gate_act = _first(ld, "gateActivationFn", "gateActivationFunction")
        gate = "sigmoid"
        if gate_act is not None:
            gate = _activation_name({"activationFn": gate_act})
        conf = GravesLSTM(n_in=n_in, n_out=n_out, activation=act,
                          gate_activation=gate)
        nL = n_out
        n_wx = n_in * 4 * nL
        n_rw = nL * (4 * nL + 3)

        def load(seg, params, state):
            # DL4J gate columns [g, f, o, i] -> ours [i, f, o, g]
            def regate(W):
                g_, f_, o_, i_ = (W[:, :nL], W[:, nL:2 * nL],
                                  W[:, 2 * nL:3 * nL], W[:, 3 * nL:4 * nL])
                return np.concatenate([i_, f_, o_, g_], axis=1)
            Wx = seg[:n_wx].reshape(n_in, 4 * nL, order="F")
            RW = seg[n_wx:n_wx + n_rw].reshape(nL, 4 * nL + 3, order="F")
            b = seg[n_wx + n_rw:n_wx + n_rw + 4 * nL]
            params["Wx"] = regate(Wx)
            params["Wh"] = regate(RW[:, :4 * nL])
            # peephole columns [wFF, wOO, wGG] -> p = [input, forget, output]
            params["p"] = np.stack([RW[:, 4 * nL + 2], RW[:, 4 * nL],
                                    RW[:, 4 * nL + 1]])
            params["b"] = regate(b.reshape(1, 4 * nL))[0]
        return conf, load, n_wx + n_rw + 4 * nL

    if kind in ("activation", "activationLayer"):
        return ActivationLayer(activation=act), None, 0

    raise ValueError(
        f"DL4J-zip import: unsupported layer type '{kind}' (supported: "
        "dense, output, rnnoutput, convolution, subsampling, "
        "batchNormalization, gravesLSTM, activation)")


def restore_multi_layer_network_from_dl4j(path: str, input_type=None,
                                          dtype=None):
    """ModelSerializer.restoreMultiLayerNetwork parity: read a reference
    .zip checkpoint into a MultiLayerNetwork with identical parameters.
    ``dtype`` optionally sets the DtypePolicy of the restored net (the
    reference stores f32; default keeps our default policy)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        conf_json = json.loads(zf.read("configuration.json").decode("utf-8"))
        flat = read_nd4j_array(io.BytesIO(zf.read("coefficients.bin")))
        if "updaterState.bin" in zf.namelist():
            import warnings
            warnings.warn(
                "DL4J-zip import: updaterState.bin present but NOT "
                "restored — optimizer moments restart from zero (the "
                "reference's flat updater-state view layout is not mapped "
                "yet); expect a transient loss bump if training is "
                "continued", UserWarning)
    flat = np.asarray(flat, np.float64).reshape(-1)

    confs = conf_json.get("confs")
    if confs is None:
        raise ValueError(
            "configuration.json has no 'confs' — ComputationGraph zips are "
            "not supported yet (MultiLayerConfiguration only)")

    b0 = NeuralNetConfiguration.builder()
    if dtype is not None:
        b0 = b0.dtype(dtype)
    builder = b0.list()
    loaders = []
    offset = 0
    for c in confs:
        kind, ld = _unwrap_layer(c)
        conf, loader, n_params = translate_layer(kind, ld)
        builder = builder.layer(conf)
        loaders.append((loader, offset, n_params))
        offset += n_params
    if offset != flat.size:
        raise ValueError(
            f"coefficients.bin holds {flat.size} params but the "
            f"configuration implies {offset}")
    if input_type is not None:
        builder = builder.set_input_type(input_type)
    net = MultiLayerNetwork(builder.build()).init()

    new_params = dict(net.params)
    new_state = dict(net.state)
    for layer, (loader, off, n) in zip(net.layers, loaders):
        if loader is None:
            continue
        params, state = {}, {}
        loader(flat[off:off + n], params, state)
        pd = layer.param_dtype
        cur = dict(net.params.get(layer.name, {}))
        cur.update({k: jnp.asarray(v, pd) for k, v in params.items()})
        new_params[layer.name] = cur
        if state:
            cur_s = dict(net.state.get(layer.name, {}))
            cur_s.update({k: jnp.asarray(v, pd) for k, v in state.items()})
            new_state[layer.name] = cur_s
    net.params = new_params
    net.state = new_state
    return net


def write_dl4j_zip(net, path: str, *, dtype: str = "FLOAT"):
    """Export a MultiLayerNetwork to the reference's zip layout
    (ModelSerializer.writeModel :79-95) — the symmetric writer used to pin
    the format in tests and to hand checkpoints BACK to a reference
    stack."""
    confs = []
    segs = []
    for layer, lc in zip(net.layers, net._resolved_confs):
        kind, ld, seg = _export_layer(net, layer, lc)
        confs.append({"layer": {kind: ld}})
        if seg is not None:
            segs.append(seg)
    flat = (np.concatenate([s.reshape(-1) for s in segs])
            if segs else np.zeros((0,), np.float32))
    buf = io.BytesIO()
    write_nd4j_array(buf, flat.reshape(1, -1), dtype)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", json.dumps({"confs": confs}))
        zf.writestr("coefficients.bin", buf.getvalue())


def _export_layer(net, layer, lc):
    import numpy as np
    p = {k: np.asarray(v, np.float64)
         for k, v in net.params.get(layer.name, {}).items()}
    s = {k: np.asarray(v, np.float64)
         for k, v in net.state.get(layer.name, {}).items()}
    t = lc.layer_type
    # per-layer activation may be None (inherited from the global conf) —
    # the zip must carry the RESOLVED value or the restored net silently
    # runs identity activations
    act = layer.resolve("activation", "identity")

    if t in ("dense", "output", "rnn_output"):
        kind = {"dense": "dense", "output": "output",
                "rnn_output": "rnnoutput"}[t]
        ld = {"nin": int(lc.n_in), "nout": int(lc.n_out),
              "activation": act}
        if t != "dense":
            ld["lossFunction"] = (lc.loss or "mcxent").upper()
        seg = np.concatenate([p["W"].reshape(-1, order="F"),
                              p["b"].reshape(-1)])
        return kind, ld, seg

    if t == "conv2d":
        kh, kw = lc.kernel
        ld = {"nin": int(lc.n_in), "nout": int(lc.n_out),
              "kernelSize": [kh, kw], "stride": list(lc.stride),
              "padding": list(lc.padding),
              "convolutionMode": lc.mode.capitalize(),
              "activation": act}
        W = p["W"].transpose(3, 2, 0, 1)  # HWIO -> OIHW
        seg = np.concatenate([p["b"].reshape(-1), W.reshape(-1, order="C")])
        return "convolution", ld, seg

    if t == "subsampling":
        ld = {"kernelSize": list(lc.kernel), "stride": list(lc.stride),
              "poolingType": lc.pooling.upper()}
        return "subsampling", ld, None

    if t == "batch_norm":
        f = p["gamma"].shape[0]
        ld = {"nin": f, "nout": f, "eps": lc.eps, "decay": lc.decay,
              "activation": act}
        seg = np.concatenate([p["gamma"], p["beta"], s["mean"], s["var"]])
        return "batchNormalization", ld, seg

    if t == "graves_lstm":
        nL = int(lc.n_out)

        def degate(W):  # ours [i,f,o,g] -> DL4J [g,f,o,i]
            i_, f_, o_, g_ = (W[:, :nL], W[:, nL:2 * nL],
                              W[:, 2 * nL:3 * nL], W[:, 3 * nL:4 * nL])
            return np.concatenate([g_, f_, o_, i_], axis=1)
        Wx = degate(p["Wx"])
        RW4 = degate(p["Wh"])
        # p = [input, forget, output] -> columns [wFF, wOO, wGG]
        peep = np.stack([p["p"][1], p["p"][2], p["p"][0]], axis=1)
        RW = np.concatenate([RW4, peep], axis=1)
        b = degate(p["b"].reshape(1, -1))[0]
        ld = {"nin": int(lc.n_in), "nout": nL,
              "activation": layer.resolve("activation", "tanh"),
              "gateActivationFn": lc.gate_activation}
        seg = np.concatenate([Wx.reshape(-1, order="F"),
                              RW.reshape(-1, order="F"), b])
        return "gravesLSTM", ld, seg

    if t == "activation":
        return "activation", {"activation": act}, None

    raise ValueError(f"DL4J-zip export: unsupported layer type '{t}'")
