"""Mixed-precision step runtime: loss scaling + the shared fused-step
builder (PRECISION.md).

The dtype *policy* lives in nn/conf/core.py (DtypePolicy: param/compute
dtypes + per-path overrides); the layers honor it at their forward
boundaries (cast activations to compute dtype at entry, accumulate
reductions in param dtype). What remains is the training-step discipline
of Micikevicius et al.'s mixed-precision recipe, implemented here so
MultiLayerNetwork and ComputationGraph share one step body:

- **No scaling (f32/bf16 policies):** ``build_step_fn`` traces exactly
  the seed step — value_and_grad over the loss, normalize + update —
  so default paths stay bit-identical.
- **Loss scaling (f16, or an explicit ``loss_scale``):** the loss is
  multiplied by the current scale before autodiff (lifting small
  gradients above f16's underflow floor), gradients are unscaled in the
  master dtype, and a step whose gradients contain any inf/nan is
  SKIPPED — params and optimizer slots are selected back to their old
  values bit-identically — while the scale backs off by
  ``1/loss_scale_factor``. After ``loss_scale_growth_interval``
  consecutive finite steps the scale regrows by ``loss_scale_factor``.

The scale state rides INSIDE ``opt_state`` under :data:`LOSS_SCALE_KEY`
(a reserved top-level key next to the per-layer slots). That placement
is load-bearing: the state is then carried through ``jax.jit`` donation,
``lax.scan`` multi-step chunking (nn/multistep.py), mesh sharding, and
orbax checkpoints with zero extra plumbing — a resumed or rolled-back
run (resilience/supervisor.py) restores the scale alongside the slots
it protected. ``apply_layer_updates`` iterates layers by name, so the
extra key passes through it untouched.

The skip-step contract composes with the resilience NaN sentinel rather
than double-firing it: the reported score is the TRUE (unscaled) loss,
so a gradient overflow with a finite loss skips silently here and never
looks like divergence to the supervisor; only a genuinely non-finite
loss still triggers its rollback — by which point this step has already
refused to poison the parameters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.updater import apply_layer_updates

#: reserved top-level opt_state key holding {"scale", "good_steps"}
LOSS_SCALE_KEY = "_loss_scale"

#: dynamic-scale ceiling: unbounded growth would eventually overflow the
#: scale itself to inf, after which backoff (inf/2 == inf) can never
#: recover; 2^24 clears any realistic gradient magnitude by orders of
#: magnitude while staying far from f32's exponent limit
_SCALE_MAX = 2.0 ** 24


def init_loss_scale_state(policy):
    """The opt_state subtree for ``policy``, or None when the policy
    needs no scaling. Called inside each net's ``init_trees`` so
    ``jax.eval_shape`` structure-only inits (clone/checkpoint-restore)
    see the same tree."""
    mode = policy.loss_scale_mode()
    if mode is None:
        return None
    init = policy.loss_scale_init if mode == "dynamic" else float(mode)
    return {"scale": jnp.asarray(init, jnp.float32),
            "good_steps": jnp.zeros((), jnp.int32)}


def all_finite(tree):
    """Scalar bool: every leaf of ``tree`` is free of inf/nan (the
    skip-step predicate, evaluated on the unscaled gradients)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(l)) for l in leaves]))


def _next_scale_state(ls, finite, mode, policy):
    """Deterministic scale transition. Static mode only tracks
    good_steps (the scale is pinned); dynamic mode backs off on a
    skipped step and regrows after the growth interval."""
    good = jnp.where(finite, ls["good_steps"] + 1, 0)
    if mode != "dynamic":
        return {"scale": ls["scale"], "good_steps": good}
    factor = policy.loss_scale_factor
    grow = good >= policy.loss_scale_growth_interval
    scale = jnp.where(
        finite,
        jnp.where(grow,
                  jnp.minimum(ls["scale"] * factor, _SCALE_MAX),
                  ls["scale"]),
        jnp.maximum(ls["scale"] / factor, 1.0))
    good = jnp.where(grow, 0, good)
    return {"scale": scale, "good_steps": good}


def build_step_fn(loss_fn, layers, gc, lr_scale):
    """The shared raw (un-jitted) fused train step for both nets:
    forward + loss + backward + gradient normalization + update, with
    loss scaling woven in when the policy asks for it.

    ``loss_fn(params, state, *data_args) -> (loss, new_state)``; the
    returned step has signature
    ``(params, state, opt_state, it, *data_args) ->
    (new_params, new_state, new_opt_state, score)`` — identical to the
    seed step, so jit/scan/shard wrappers need no changes."""
    policy = gc.dtype
    mode = policy.loss_scale_mode()

    if mode is None:
        def step_fn(params, state, opt_state, it, *data_args):
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, *data_args)
            new_params, new_opt = apply_layer_updates(
                layers, gc, params, grads, opt_state, it, lr_scale)
            return new_params, new_state, new_opt, score

        return step_fn

    master = jnp.dtype(policy.param_dtype)

    def step_fn(params, state, opt_state, it, *data_args):
        ls = opt_state[LOSS_SCALE_KEY]
        scale = ls["scale"]

        def scaled_loss(p, s, *a):
            loss, new_state = loss_fn(p, s, *a)
            # aux carries the TRUE loss: the published score must not be
            # a scaled value, and the NaN sentinel keys off it
            return loss * scale.astype(loss.dtype), (loss, new_state)

        (_, (score, new_state)), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(params, state, *data_args)
        inv = (1.0 / scale).astype(master)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(master) * inv, grads)
        finite = all_finite(grads)
        new_params, new_opt = apply_layer_updates(
            layers, gc, params, grads, opt_state, it, lr_scale)
        # skip-step: a non-finite gradient selects every param and
        # optimizer slot back to its pre-step value BIT-IDENTICALLY
        # (jnp.where on a scalar predicate is an exact select)
        new_params = jax.tree_util.tree_map(
            lambda n, o: jnp.where(finite, n, o), new_params, params)
        new_opt = jax.tree_util.tree_map(
            lambda n, o: jnp.where(finite, n, o), new_opt, opt_state)
        new_opt[LOSS_SCALE_KEY] = _next_scale_state(ls, finite, mode,
                                                    policy)
        return new_params, new_state, new_opt, score

    return step_fn


def current_loss_scale(net):
    """The net's live loss scale as a float, or None when its policy
    runs unscaled (the observability hook PRECISION.md documents)."""
    opt = getattr(net, "opt_state", None)
    if not isinstance(opt, dict) or LOSS_SCALE_KEY not in opt:
        return None
    return float(opt[LOSS_SCALE_KEY]["scale"])
