"""MultiLayerNetwork — a sequential stack with fit()/output()/evaluate().

Parity: nn/multilayer/MultiLayerNetwork.java (2,590 LoC): init() :903,
fit(DataSetIterator) :947, output :1512, feedForward :675, evaluate :2413.

TPU-native design (SURVEY.md §7): instead of the reference's per-op JNI
dispatch through Solver -> StochasticGradientDescent -> per-layer
backpropGradient (call stack §3.1), ``fit`` compiles ONE jitted train step:
forward + loss + autodiff backward + gradient normalization + updater +
parameter update fused into a single XLA program. Parameters/optimizer state
are pytrees keyed by layer name. Optional distribution: pass a
``jax.sharding.Mesh`` and the same step is sharded over the 'data' axis
(gradients all-reduced by XLA over ICI) — see parallel/.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    DataSetIterator,
    DevicePrefetchIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf.core import MultiLayerConfiguration
from deeplearning4j_tpu.observability import goodput as _goodput
from deeplearning4j_tpu.observability import metrics as _obs_metrics
from deeplearning4j_tpu.observability.trace import get_tracer as _get_tracer
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers as layer_confs
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForward,
    FeedForwardToCnn,
    RnnToFeedForward,
)
from deeplearning4j_tpu.nn import precision
from deeplearning4j_tpu.nn.updater import apply_layer_updates


def _auto_preprocessor(input_type: InputType, conf):
    """Automatic shape-adapter insertion between mismatched layer families
    (parity: MultiLayerConfiguration setInputType preprocessor inference)."""
    kind = input_type.kind
    is_ff = isinstance(conf, layer_confs.FeedForwardLayerConfig)
    wants_cnn = getattr(conf, "expects_cnn_input", False)
    wants_rnn = getattr(conf, "expects_rnn_input", False)
    if kind == "convolutional" and is_ff and not wants_cnn and not wants_rnn:
        return CnnToFeedForward(input_type.height, input_type.width,
                                input_type.channels)
    if kind == "convolutional_flat" and wants_cnn:
        return FeedForwardToCnn(input_type.height, input_type.width,
                                input_type.channels)
    if kind == "recurrent" and is_ff and not wants_rnn and not wants_cnn:
        return RnnToFeedForward()
    return None


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = None          # runtime Layer objects
        self.preprocessors = None   # per-layer-index preprocessor or None
        self.params = None          # pytree {layer_name: {param: array}}
        self.state = None           # pytree {layer_name: {...}} (e.g. BN stats)
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self.score_value = None
        self._train_step = None
        self._tbptt_step = None
        self._multi_steps = {}
        self._apply_fns = {}
        self._mesh = None
        self._rng_key = None
        self._rnn_state = None
        # DL4J_TPU_REMAT resolved at train-step build time (None until
        # then); later env-var changes are no-ops for this model
        self.remat_prefixes = None
        self._remat_warned = False
        # runtime learning-rate multiplier (resilience NaN backoff); a
        # compile-time constant of the fused step — set via set_lr_scale
        self._lr_scale = 1.0

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None, *, structure_only: bool = False):
        """Build runtime layers and parameter/optimizer pytrees. With
        ``structure_only`` the trees are ShapeDtypeStructs (via jax.eval_shape)
        — used by clone()/restore, which overwrite every leaf anyway."""
        gc = self.conf.global_conf
        seed = gc.seed if seed is None else seed
        self._rng_key = jax.random.PRNGKey(seed)

        input_type = self.conf.input_type
        self.layers = []
        self.preprocessors = []
        resolved_confs = []
        for i, lc in enumerate(self.conf.layers):
            prep = self.conf.preprocessors.get(i)
            if prep is None and input_type is not None:
                prep = _auto_preprocessor(input_type, lc)
            if prep is not None and input_type is not None:
                input_type = prep.output_type(input_type)
            self.preprocessors.append(prep)
            if input_type is not None:
                lc = lc.with_n_in(input_type)
            if getattr(lc, "n_in", 1) is None:
                raise ValueError(
                    f"Layer {i} ({lc.layer_type}): n_in not set and no "
                    f"input_type provided for inference")
            if lc.name is None:
                lc = lc.replace(name=f"layer_{i}")
            resolved_confs.append(lc)
            layer = lc.make_layer(input_type, gc, gc.dtype)
            self.layers.append(layer)
            input_type = layer.output_type
        self._resolved_confs = resolved_confs

        # init params + state + per-layer optimizer state
        def init_trees(key):
            params, state = {}, {}
            for layer in self.layers:
                key_, sub = jax.random.split(key)
                key = key_
                p = layer.init_params(sub)
                if p:
                    params[layer.name] = p
                s = layer.init_state()
                if s:
                    state[layer.name] = s
            opt_state = {}
            for layer in self.layers:
                if layer.name in params:
                    upd = layer.resolve("updater")
                    opt_state[layer.name] = upd.init_state(params[layer.name])
            ls = precision.init_loss_scale_state(gc.dtype)
            if ls is not None:
                opt_state[precision.LOSS_SCALE_KEY] = ls
            return params, state, opt_state

        if structure_only:
            self.params, self.state, self.opt_state = jax.eval_shape(
                init_trees, self._rng_key)
        else:
            self.params, self.state, self.opt_state = init_trees(self._rng_key)
        self.iteration = 0
        self._train_step = None
        self._tbptt_step = None
        self._multi_steps = {}
        self._apply_fns = {}
        return self

    def materialize_state(self):
        """Concrete layer state (e.g. BN running stats) — used after a
        structure-only init when a checkpoint lacks the state tree."""
        state = {}
        for layer in self.layers:
            s = layer.init_state()
            if s:
                state[layer.name] = s
        self.state = state

    def materialize_opt_state(self):
        """Fresh optimizer state from (concrete) params — used after a
        structure-only init when the updater state isn't being restored."""
        opt_state = {}
        for layer in self.layers:
            if layer.name in self.params:
                upd = layer.resolve("updater")
                opt_state[layer.name] = upd.init_state(self.params[layer.name])
        ls = precision.init_loss_scale_state(self.conf.global_conf.dtype)
        if ls is not None:
            opt_state[precision.LOSS_SCALE_KEY] = ls
        self.opt_state = opt_state

    def set_lr_scale(self, scale: float):
        """Scale every layer's scheduled learning rate by ``scale`` from
        the next step on (resilience/supervisor.py backs off the rate
        after a NaN rollback). The scale is baked into the compiled step,
        so every cached step variant is invalidated — expect one
        recompile per change, which is why this is a recovery lever and
        not a schedule."""
        scale = float(scale)
        if scale <= 0.0:
            raise ValueError(f"lr scale must be > 0, got {scale}")
        if scale != self._lr_scale:
            self._lr_scale = scale
            self._train_step = None
            self._tbptt_step = None
            self._multi_steps = {}
        return self

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    def use_mesh(self, mesh, data_axis: str = "data",
                 model_axis: str | None = None, tp_rules=None):
        """Shard training over a jax Mesh: batches split on ``data_axis``;
        params replicated (pure dp) or, with ``model_axis`` set, sharded
        column-parallel over that axis (dp x tp — parallel/tensor.py).
        XLA inserts every collective (gradient all-reduce over data,
        activation all-gathers/reduce-scatters over model) in the one
        compiled step. (Replaces ParallelWrapper/Spark parameter
        averaging — SURVEY.md §2.8 — and adds the model-parallel axis the
        reference never had.)"""
        self._mark_meshed(mesh, data_axis, model_axis, tp_rules)
        if model_axis is not None:
            from deeplearning4j_tpu.parallel.tensor import (
                apply_tensor_parallel)
            apply_tensor_parallel(self, mesh, data_axis, model_axis,
                                  tp_rules)
        else:
            from deeplearning4j_tpu.parallel.data_parallel import apply_mesh
            apply_mesh(self, mesh, data_axis)
        return self

    def _mark_meshed(self, mesh, data_axis: str = "data",
                     model_axis=None, tp_rules=None):
        """Record mesh placement + drop compiled-step caches WITHOUT
        moving a single leaf. The elastic restore path
        (utils/checkpoint.py) places params/opt_state directly into
        their target NamedShardings and then calls this, instead of the
        replicate-then-``use_mesh`` double materialization."""
        self._mesh = (mesh, data_axis)
        self._mesh_detail = {"model_axis": model_axis, "tp_rules": tp_rules}
        self._train_step = None
        self._tbptt_step = None
        self._multi_steps = {}
        self._apply_fns = {}
        return self

    # -------------------------------------------------------------- forward
    def _remat_spans(self, n: int) -> dict:
        """start index -> end index for maximal contiguous runs of layers
        whose names match the DL4J_TPU_REMAT prefixes (the chain-network
        rendering of ComputationGraph's block-granular selective remat —
        e.g. ``DL4J_TPU_REMAT=layer_`` remats every hidden layer, the
        long-sequence memory lever for stacked LSTMs)."""
        from deeplearning4j_tpu.nn.graph import (_remat_match,
                                                  _remat_prefixes)
        prefixes = (self.remat_prefixes if self.remat_prefixes is not None
                    else _remat_prefixes())
        spans = {}
        if not prefixes:
            return spans
        start = None
        for i in range(n):
            ok = (_remat_match(self.layers[i].name, prefixes)
                  and not hasattr(self.layers[i], "loss"))
            if ok and start is None:
                start = i
            elif not ok and start is not None:
                if i - start >= 1:
                    spans[start] = i
                start = None
        if start is not None and n - start >= 1:
            spans[start] = n
        return spans

    def _run_remat_span(self, i, end, params, state, x, fmask, rng, train):
        """Execute layers [i, end) under one jax.checkpoint: only the
        span's inputs are saved; interiors (e.g. an LSTM's per-timestep
        gate activations) are recomputed in the backward."""
        rngs = []
        for _ in range(i, end):
            lr = None
            if rng is not None:
                rng, lr = jax.random.split(rng)
            rngs.append(lr)
        sub = self.layers[i:end]
        p_sub = {ly.name: params.get(ly.name, {}) for ly in sub}
        s_sub = {ly.name: state.get(ly.name, {}) for ly in sub}

        def run_span(p_sub, s_sub, x, fmask, rngs):
            ns = {}
            for k, j in enumerate(range(i, end)):
                ly = self.layers[j]
                if self.preprocessors[j] is not None:
                    x = self.preprocessors[j](x)
                x, s_new = ly.apply(p_sub.get(ly.name, {}),
                                    s_sub.get(ly.name, {}), x, train=train,
                                    rng=rngs[k], mask=fmask)
                fmask = ly.feed_forward_mask(fmask)
                if s_new:
                    ns[ly.name] = s_new
            return x, fmask, ns

        return jax.checkpoint(run_span)(p_sub, s_sub, x, fmask, tuple(rngs)
                                        ), rng

    def _forward(self, params, state, x, *, train, rng, fmask=None,
                 to_layer: Optional[int] = None, collect=False):
        """Walk the stack; returns (final activation or list, new_state)."""
        acts = []
        new_state = dict(state)
        n = len(self.layers) if to_layer is None else to_layer
        # selective remat spans apply on plain training walks only
        # (collect needs every activation; eval has no backward)
        spans = self._remat_spans(n) if train and not collect else {}
        i = 0
        while i < n:
            end = spans.get(i)
            if end is not None:
                (x, fmask, ns), rng = self._run_remat_span(
                    i, end, params, state, x, fmask, rng, train)
                new_state.update(ns)
                i = end
                continue
            layer = self.layers[i]
            if self.preprocessors[i] is not None:
                x = self.preprocessors[i](x)
            lrng = None
            if rng is not None:
                rng, lrng = jax.random.split(rng)
            p = params.get(layer.name, {})
            s = state.get(layer.name, {})
            x, s_new = layer.apply(p, s, x, train=train, rng=lrng, mask=fmask)
            fmask = layer.feed_forward_mask(fmask)
            if s_new:
                new_state[layer.name] = s_new
            if collect:
                acts.append(x)
            i += 1
        return (acts if collect else x), new_state

    def _loss(self, params, state, x, labels, fmask, lmask, rng, train=True):
        """Data loss + regularization: the scalar the jitted step autodiffs."""
        rng_fwd = lrng = None
        if rng is not None:
            rng_fwd, lrng = jax.random.split(rng)
        h, new_state = self._forward(params, state, x, train=train, rng=rng_fwd,
                                     fmask=fmask, to_layer=len(self.layers) - 1)
        out_layer = self.layers[-1]
        if self.preprocessors[-1] is not None:
            h = self.preprocessors[-1](h)
        p_out = params.get(out_layer.name, {})
        if getattr(out_layer, "loss_uses_state", False):
            s_out = state.get(out_layer.name, {})
            data_loss = out_layer.loss(p_out, h, labels, train=train,
                                       rng=lrng, mask=lmask, state=s_out)
            if train and hasattr(out_layer, "update_centers"):
                new_state[out_layer.name] = out_layer.update_centers(
                    s_out, jax.lax.stop_gradient(h), labels, mask=lmask)
        else:
            data_loss = out_layer.loss(p_out, h, labels, train=train,
                                       rng=lrng, mask=lmask)
        reg = jnp.zeros((), data_loss.dtype)
        for layer in self.layers:
            if layer.name in params:
                reg = reg + layer.regularization(params[layer.name])
        return data_loss + reg, new_state

    # ---------------------------------------------------------- train step
    def _resolve_remat(self) -> tuple:
        """Read DL4J_TPU_REMAT exactly ONCE — when the first train step
        is built — and record the resolved prefixes on the model
        (``self.remat_prefixes``). The jitted step is cached, so a later
        env-var change can never take effect; resolving eagerly (and
        warning on a detected change) keeps remat experiments from
        silently measuring a stale configuration."""
        from deeplearning4j_tpu.nn.graph import _remat_prefixes
        current = _remat_prefixes()
        if self.remat_prefixes is None:
            self.remat_prefixes = current
        elif current != self.remat_prefixes and not self._remat_warned:
            import warnings
            warnings.warn(
                f"DL4J_TPU_REMAT changed to {current!r} after the train "
                f"step was built with {self.remat_prefixes!r}; the cached "
                "step ignores the change (set the variable before the "
                "first training step, or rebuild the model)",
                RuntimeWarning, stacklevel=3)
            self._remat_warned = True
        return self.remat_prefixes

    def _step_fn(self):
        """The raw (un-jitted) fused train step: fwd+bwd+normalize+update,
        with loss scaling when the dtype policy calls for it (f16) —
        see nn/precision.py."""
        self._resolve_remat()
        gc = self.conf.global_conf

        def loss_fn(params, state, x, labels, fmask, lmask, rng):
            return self._loss(params, state, x, labels, fmask, lmask, rng)

        return precision.build_step_fn(loss_fn, self.layers, gc,
                                       self._lr_scale)

    def _build_train_step(self):
        step_fn = self._step_fn()
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.data_parallel import shard_step
            return shard_step(self, step_fn, *self._mesh)
        return jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def fit_batch_repeated(self, ds: DataSet, n_steps: int):
        """Run ``n_steps`` optimization steps on one minibatch inside a
        SINGLE XLA execution (``lax.scan`` over the fused train step).

        TPU-native tight loop: one dispatch instead of n — removes
        host-dispatch latency from the hot path (the reference pays a
        JNI crossing per op; a jitted-scan epoch pays one per n steps).
        Used by bench.py for device-true step timing and usable for
        training on a small device-resident dataset."""
        self._require_init()
        if n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        needs_tbptt = (self.conf.backprop_type == "tbptt"
                       and getattr(ds.features, "ndim", 0) == 3
                       and ds.features.shape[1] > self.conf.tbptt_fwd_length)
        if self._mesh is not None or needs_tbptt:
            # meshed execution needs shard_step's batch sharding/padding and
            # tbptt needs chunked backprop — both route through fit_batch
            # (n dispatches) to keep semantics identical
            for _ in range(n_steps):
                score = self.fit_batch(ds)
            return score
        from deeplearning4j_tpu.nn.multistep import get_multi_step
        jitted = get_multi_step(self, n_steps)
        self._rng_key, rng = jax.random.split(self._rng_key)
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        fmask = (None if ds.features_mask is None
                 else jnp.asarray(ds.features_mask))
        lmask = (None if ds.labels_mask is None
                 else jnp.asarray(ds.labels_mask))
        it = jnp.asarray(self.iteration, jnp.int32)
        self.params, self.state, self.opt_state, score = jitted(
            self.params, self.state, self.opt_state, it, x, y, fmask, lmask,
            rng)
        self.iteration += n_steps
        self.score_value = score
        self.last_batch_examples = ds.num_examples
        _goodput.observe_steps(n_steps)
        return score


    def step_cost_analysis(self, ds: DataSet) -> dict:
        """XLA cost-model numbers for ONE compiled train step on this
        batch shape: {"flops", "bytes_accessed"} (SURVEY.md §5.1 — feeds
        PerformanceListener(flops_per_step=...) for live MFU)."""
        self._require_init()
        if self._train_step is None:
            self._train_step = self._build_train_step()
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        it = jnp.asarray(self.iteration, jnp.int32)
        rng = jax.random.PRNGKey(0)
        from deeplearning4j_tpu.utils.perf import xla_step_cost
        return xla_step_cost(self._train_step, self.params, self.state,
                             self.opt_state, it, x, y, None, None, rng)

    def _require_init(self):
        if self.params is None:
            raise RuntimeError(
                "Network not initialized — call net.init() before "
                "fit()/output()/evaluate()")

    # ------------------------------------------------ recurrent state helpers
    def _set_streaming(self, flag: bool):
        from deeplearning4j_tpu.nn.layers.recurrent import set_streaming
        set_streaming(self.layers, flag)

    def _strip_carries(self, state):
        from deeplearning4j_tpu.nn.layers.recurrent import strip_carries
        return strip_carries(state)

    def rnn_clear_previous_state(self):
        """Reset streaming decode state (rnnClearPreviousState parity)."""
        self._rnn_state = None

    def rnn_time_step(self, x, mask=None):
        """Stateful streaming inference (MultiLayerNetwork.rnnTimeStep :2234):
        feed one step [b, f] or a chunk [b, t, f]; recurrent layers carry
        (h, c) across calls — attention layers carry their KV cache and
        per-row position. ``mask`` [b, t] marks real timesteps for
        right-padded one-shot prefill (the attention layers advance each
        row's position by its true length)."""
        self._require_init()
        x = jnp.asarray(x)
        single = x.ndim == 2
        if single:
            x = x[:, None, :]
        self._set_streaming(True)
        try:
            key = "stream" if mask is None else "stream_masked"
            if key not in self._apply_fns:
                def fn(params, state, xx, fmask=None):
                    return self._forward(params, state, xx, train=False,
                                         rng=None, fmask=fmask)
                self._apply_fns[key] = jax.jit(fn)
            state_in = getattr(self, "_rnn_state", None)
            if state_in is None:
                state_in = self.state
            if mask is None:
                out, new_state = self._apply_fns[key](self.params, state_in,
                                                      x)
            else:
                out, new_state = self._apply_fns[key](self.params, state_in,
                                                      x, jnp.asarray(mask))
            self._rnn_state = new_state
        finally:
            self._set_streaming(False)
        return out[:, 0, :] if single else out

    def _fit_tbptt(self, ds: DataSet):
        """Truncated BPTT (doTruncatedBPTT :1119): split the time axis into
        tbptt_fwd_length chunks; recurrent state carries across chunks inside
        the compiled step (via the state pytree) and resets per batch."""
        L = self.conf.tbptt_fwd_length
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        if y.ndim != 3 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "tBPTT requires per-timestep labels [batch, time, out] with "
                f"the same time length as the features; got labels shape "
                f"{tuple(y.shape)} vs features {tuple(x.shape)}. For "
                "sequence-classification labels use backprop_type='standard'")
        fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        self._set_streaming(True)
        try:
            if getattr(self, "_tbptt_step", None) is None:
                self._tbptt_step = self._build_train_step()
            t_total = x.shape[1]
            score_sum, weight = 0.0, 0
            _dev_span = _get_tracer().span("device_step", tbptt=True)
            _dev_span.__enter__()
            for start in range(0, t_total, L):
                sl = slice(start, min(start + L, t_total))
                self._rng_key, rng = jax.random.split(self._rng_key)
                it = jnp.asarray(self.iteration, jnp.int32)
                self.params, self.state, self.opt_state, chunk_score = \
                    self._tbptt_step(
                        self.params, self.state, self.opt_state, it,
                        x[:, sl], y[:, sl],
                        None if fmask is None else fmask[:, sl],
                        None if lmask is None else lmask[:, sl],
                        rng)
                w = sl.stop - sl.start
                # accumulate ON DEVICE: a float() here would sync the
                # pipeline once per chunk; consumers pull the final mean
                score_sum = score_sum + chunk_score * w
                weight += w
            _dev_span.__exit__(None, None, None)
            self.state = self._strip_carries(self.state)
            score = score_sum / max(weight, 1)
        finally:
            self._set_streaming(False)
        self.iteration += 1
        self.score_value = score
        self.last_batch_examples = ds.num_examples
        _goodput.observe_steps(1)
        with _get_tracer().span("score_sync"):
            for l in self.listeners:
                l.iteration_done(self, self.iteration, self.epoch)
        return score

    def _maybe_derive_flops(self, x, y, fmask, lmask):
        """Auto-derive per-step FLOPs from the XLA cost model on the
        *lowered* train step — tracing only, no second backend compile —
        the first time each (train-step, batch-shapes) pair is seen.
        Feeds live dl4j_mfu / dl4j_flops_per_second with zero user
        wiring; DL4J_TPU_AUTO_FLOPS=0 opts out."""
        if not _goodput.auto_flops_enabled():
            return
        key = (id(self._train_step), tuple(x.shape), tuple(y.shape),
               None if fmask is None else tuple(fmask.shape),
               None if lmask is None else tuple(lmask.shape))
        if getattr(self, "_flops_key", None) == key:
            return
        self._flops_key = key
        with _get_tracer().span("flops_derive"):
            try:
                if self._train_step is None:
                    self._train_step = self._build_train_step()
                from deeplearning4j_tpu.utils.perf import (
                    xla_step_cost_lowered,
                )
                it = jnp.asarray(self.iteration, jnp.int32)
                rng = jax.random.PRNGKey(0)
                cost = xla_step_cost_lowered(
                    self._train_step, self.params, self.state,
                    self.opt_state, it, x, y, fmask, lmask, rng)
                self.flops_per_step = cost["flops"] or None
            except Exception:
                # meshed/wrapped steps have no .lower
                self.flops_per_step = None
        _goodput.observe_flops(self.flops_per_step)

    def fit_batch(self, ds: DataSet):
        """One optimization step on one minibatch (Model.fit parity)."""
        self._require_init()
        if (self.conf.backprop_type == "tbptt"
                and getattr(ds.features, "ndim", 0) == 3
                and ds.features.shape[1] > self.conf.tbptt_fwd_length):
            return self._fit_tbptt(ds)
        if self._train_step is None:
            self._train_step = self._build_train_step()
        else:
            self._resolve_remat()  # warn if DL4J_TPU_REMAT changed since
        tracer = _get_tracer()
        with tracer.span("host_dispatch"):
            self._rng_key, rng = jax.random.split(self._rng_key)
            x = jnp.asarray(ds.features)
            y = jnp.asarray(ds.labels)
            fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
            lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
            it = jnp.asarray(self.iteration, jnp.int32)
        with tracer.span("device_step"):
            self.params, self.state, self.opt_state, score = self._train_step(
                self.params, self.state, self.opt_state, it, x, y, fmask, lmask, rng)
        self.iteration += 1
        self.score_value = score
        self.last_batch_examples = ds.num_examples
        _goodput.observe_steps(1)
        # after the dispatch: self.params holds fresh (undonated) outputs
        # and x/y were not donated, so lowering for cost analysis is safe
        self._maybe_derive_flops(x, y, fmask, lmask)
        if self.listeners:
            t0 = time.perf_counter()
            for l in self.listeners:
                l.iteration_done(self, self.iteration, self.epoch)
            t1 = time.perf_counter()
            tracer.record("score_sync", t0, t1)
            _obs_metrics.observe_dispatch_lag(t1 - t0)
        return score

    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            async_prefetch: bool = True, device_prefetch="auto",
            multi_step="auto"):
        """Train. Accepts a DataSetIterator, a DataSet, or (features, labels)
        arrays (MultiLayerNetwork.fit overloads parity; iterator is wrapped
        in an async prefetcher like MultiLayerNetwork.java:951).

        Async runtime (all bit-identity-preserving vs the per-batch loop):
        ``async_prefetch`` overlaps host batch prep (background thread),
        ``device_prefetch`` overlaps the host→device copy of batch N+1 with
        step N (DevicePrefetchIterator; "auto" = on for accelerator
        backends, off on CPU where there is no transfer to hide), and
        ``multi_step`` collapses k Python dispatches into one jitted scan
        chunk ("auto" = 8 on accelerators when no attached listener needs
        per-iteration values; an int pins k; 1 disables). Chunking is
        skipped under a device mesh and for tBPTT, where per-batch
        semantics differ."""
        if isinstance(data, DataSetIterator):
            it = data
        elif isinstance(data, DataSet):
            it = ListDataSetIterator([data])
        else:
            it = ArrayDataSetIterator(data, labels, batch_size=batch_size)
        chunk = self._resolve_multi_step(multi_step)
        device_prefetch = self._resolve_device_prefetch(device_prefetch)
        _obs_metrics.install_runtime_metrics()
        from deeplearning4j_tpu.compilecache import ensure_configured
        ensure_configured()  # DL4J_TPU_COMPILE_CACHE env var, if set
        tracer = _get_tracer()
        ledger = _goodput.start_run("fit", net=self)
        from deeplearning4j_tpu.observability import distributed as _obs_dist
        _obs_dist.stamp_run_marker("fit")
        status = "completed"
        try:
            for epoch in range(epochs):
                source = AsyncDataSetIterator(it) if async_prefetch else it
                if device_prefetch:
                    source = DevicePrefetchIterator(
                        source, sharding=self._prefetch_sharding())
                for l in self.listeners:
                    l.on_epoch_start(self)
                it0, t0 = self.iteration, time.perf_counter()
                if chunk > 1:
                    self._fit_epoch_chunked(source, chunk)
                else:
                    stream = iter(source)
                    while True:
                        with tracer.span("data_wait"):
                            ds = next(stream, None)
                        if ds is None:
                            break
                        self.fit_batch(ds)
                _obs_metrics.observe_rate(self.iteration - it0,
                                          time.perf_counter() - t0)
                for l in self.listeners:
                    l.on_epoch_end(self)
                self.epoch += 1
                if not getattr(it, "auto_epochs", False):
                    # datapipe Pipelines advance their own epoch state
                    # (seed + epoch shuffle orders); reset() would rewind
                    # them to epoch 0 every pass
                    it.reset()
        except BaseException:
            status = "failed"
            raise
        finally:
            self.last_run_report = _goodput.end_run(ledger, status=status)
        return self

    _FIT_CHUNK_DEFAULT = 8

    def _resolve_multi_step(self, multi_step) -> int:
        """How many fit steps one jitted dispatch may cover. 1 = per-batch
        (mesh / tbptt / a listener that needs real per-step boundaries).
        "auto" also resolves to 1 on the CPU backend: collapsing dispatch
        pays when per-step dispatch overhead rivals device compute
        (accelerators); XLA:CPU instead pays scan-carry copies + chunk
        slicing that dwarf the dispatch saved (measured in bench
        host_loop). An explicit int is always honored."""
        if multi_step in (None, False, 0, 1):
            return 1
        if self._mesh is not None or self.conf.backprop_type == "tbptt":
            return 1
        for l in self.listeners:
            if getattr(l, "needs_per_iteration", True):
                return 1
        if multi_step == "auto":
            if jax.default_backend() == "cpu":
                return 1
            return self._FIT_CHUNK_DEFAULT
        return max(1, int(multi_step))

    @staticmethod
    def _resolve_device_prefetch(device_prefetch) -> bool:
        """"auto" = on for accelerator backends (overlaps the host→device
        copy of batch N+1 with step N); off on CPU, where device_put is
        just an extra eager copy with no transfer to hide (measured in
        bench host_loop). Explicit booleans are always honored."""
        if device_prefetch == "auto":
            return jax.default_backend() != "cpu"
        return bool(device_prefetch)

    def _prefetch_sharding(self):
        """Target sharding for prefetched batches (None = default device).
        Multi-process meshes assemble global arrays from host shards in
        shard_step, so they keep host-side batches."""
        if self._mesh is None:
            return None
        if jax.process_count() > 1:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        mesh, axis = self._mesh
        return NamedSharding(mesh, PartitionSpec(axis))

    def _fit_epoch_chunked(self, source, chunk: int):
        """Group consecutive same-shape batches and dispatch each group as
        ONE jitted scan over distinct batches (bit-identical to the
        per-batch loop, including the rng chain — see multistep.py)."""
        self._require_init()
        tracer = _get_tracer()
        buf, sig = [], None
        stream = iter(source)
        while True:
            with tracer.span("data_wait"):
                ds = next(stream, None)
            if ds is None:
                break
            s = (tuple(ds.features.shape), tuple(ds.labels.shape),
                 None if ds.features_mask is None
                 else tuple(ds.features_mask.shape),
                 None if ds.labels_mask is None
                 else tuple(ds.labels_mask.shape))
            if buf and s != sig:
                self._dispatch_chunk(buf)
                buf = []
            sig = s
            buf.append(ds)
            if len(buf) == chunk:
                self._dispatch_chunk(buf)
                buf = []
        if buf:
            self._dispatch_chunk(buf)

    def _dispatch_chunk(self, batches):
        """Run len(batches) steps in one XLA execution (lax.scan over the
        fused step), then replay listeners with per-iteration scores."""
        if len(batches) == 1:
            self.fit_batch(batches[0])
            return
        from deeplearning4j_tpu.nn.multistep import get_multi_batch_step
        tracer = _get_tracer()
        with tracer.span("host_dispatch", steps=len(batches)):
            jitted = get_multi_batch_step(self)
            xs = jnp.stack([jnp.asarray(b.features) for b in batches])
            ys = jnp.stack([jnp.asarray(b.labels) for b in batches])
            fmask = (None if batches[0].features_mask is None else
                     jnp.stack([jnp.asarray(b.features_mask) for b in batches]))
            lmask = (None if batches[0].labels_mask is None else
                     jnp.stack([jnp.asarray(b.labels_mask) for b in batches]))
            it0 = jnp.asarray(self.iteration, jnp.int32)
            steps = jnp.arange(len(batches), dtype=jnp.int32)
        with tracer.span("device_step", steps=len(batches)):
            (self.params, self.state, self.opt_state, self._rng_key,
             scores) = jitted(self.params, self.state, self.opt_state, it0,
                              self._rng_key, steps, (xs, ys, fmask, lmask))
        start = self.iteration
        self.iteration += len(batches)
        self.score_value = scores[-1]
        self.last_batch_examples = batches[-1].num_examples
        _goodput.observe_steps(len(batches))  # one dispatch, k real steps
        # pre-stack arrays already have the per-step shape; slicing the
        # stacked device arrays here would dispatch (and first-call
        # compile) an XLA gather outside the flops_derive span
        b0 = batches[0]
        self._maybe_derive_flops(b0.features, b0.labels,
                                 b0.features_mask, b0.labels_mask)
        with tracer.span("score_sync", steps=len(batches)):
            self._replay_listeners(start, scores,
                                   [b.num_examples for b in batches])

    def _replay_listeners(self, start: int, scores, examples):
        """Post-chunk iteration_done replay: every listener here declared
        needs_per_iteration=False, so it sees the same (iteration, score)
        stream as per-batch dispatch — score_value stays a lazy device
        slice until a listener's own cadence floats it."""
        if not self.listeners:
            return
        for j in range(len(examples)):
            self.score_value = scores[j]
            self.last_batch_examples = examples[j]
            for l in self.listeners:
                l.iteration_done(self, start + j + 1, self.epoch)
        self.score_value = scores[-1]
        self.last_batch_examples = examples[-1]

    def resilient_fit(self, data, labels=None, *, checkpoint_dir: str,
                      epochs: int = 1, batch_size: int = 32, **supervisor_kw):
        """Supervised ``fit``: periodic checkpoints to fresh step
        directories, auto-resume from the newest valid one, transient-step
        retry, NaN rollback + LR backoff, SIGTERM preemption handling
        (resilience/supervisor.py). Returns the SupervisorResult."""
        from deeplearning4j_tpu.resilience import resilient_fit
        return resilient_fit(self, data, labels,
                             checkpoint_dir=checkpoint_dir, epochs=epochs,
                             batch_size=batch_size, **supervisor_kw)

    # ------------------------------------------------------------- pretrain
    def pretrain(self, data, *, epochs: int = 1, batch_size: int = 32):
        """Layer-wise unsupervised pretraining (MultiLayerNetwork.pretrain
        :963): each pretrainable layer (VAE/AutoEncoder/RBM) trains on the
        activations of the layers below it."""
        self._require_init()
        if isinstance(data, DataSetIterator):
            it = data
        elif isinstance(data, DataSet):
            it = ListDataSetIterator([data])
        else:
            it = ArrayDataSetIterator(data, None, batch_size=batch_size)
        for i, layer in enumerate(self.layers):
            if getattr(layer, "is_pretrainable", False):
                self.pretrain_layer(i, it, epochs=epochs)
        return self

    def pretrain_layer(self, idx: int, iterator, *, epochs: int = 1):
        """Pretrain one layer on its (preprocessed) input activations; the
        loss is the layer's own unsupervised objective
        (pretrain_loss: -ELBO for VAE, reconstruction for AE, CD free-energy
        difference for RBM), compiled into one jitted step."""
        layer = self.layers[idx]
        if not getattr(layer, "is_pretrainable", False):
            raise ValueError(f"Layer {idx} ({layer.conf.layer_type}) is not "
                             f"pretrainable")
        gc = self.conf.global_conf
        name = layer.name

        def step(params, opt_state, itc, x, rng):
            def loss_fn(p):
                return layer.pretrain_loss(p[name], x, rng)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_opt = apply_layer_updates(
                [layer], gc, params, grads, opt_state, itc)
            return new_params, new_opt, loss

        jitted = jax.jit(step, donate_argnums=(0, 1))
        # material copies: the jitted step donates these buffers, and the
        # net's own trees must never alias donated (deleted) arrays — an
        # exception mid-loop would otherwise corrupt the whole net
        params_sub = {name: jax.tree_util.tree_map(jnp.copy,
                                                   self.params[name])}
        opt_sub = {name: jax.tree_util.tree_map(jnp.copy,
                                                self.opt_state[name])}
        last = None
        iteration = self.iteration
        for _ in range(epochs):
            for ds in iterator:
                x = jnp.asarray(ds.features)
                # activations of the stack below + this layer's preprocessor
                if idx > 0:
                    x, _ = self._forward(self.params, self.state, x,
                                         train=False, rng=None, to_layer=idx)
                if self.preprocessors[idx] is not None:
                    x = self.preprocessors[idx](x)
                self._rng_key, rng = jax.random.split(self._rng_key)
                itc = jnp.asarray(iteration, jnp.int32)
                params_sub, opt_sub, last = jitted(params_sub, opt_sub, itc,
                                                   x, rng)
                iteration += 1
            iterator.reset()
        self.iteration = iteration
        self.params = {**self.params, name: params_sub[name]}
        self.opt_state = {**self.opt_state, name: opt_sub[name]}
        self.score_value = last
        return self

    # ------------------------------------------------------------ inference
    def _get_apply(self, collect=False, train=False):
        key = (collect, train)
        if key not in self._apply_fns:
            def apply_fn(params, state, x, rng, fmask):
                out, _ = self._forward(params, state, x, train=train, rng=rng,
                                       fmask=fmask, collect=collect)
                return out
            self._apply_fns[key] = jax.jit(apply_fn)
        return self._apply_fns[key]

    def _inference_rng(self, train):
        if not train:
            return None
        self._rng_key, rng = jax.random.split(self._rng_key)
        return rng

    def output(self, x, train: bool = False, mask=None):
        """Forward pass -> final layer activations
        (MultiLayerNetwork.output :1512). ``mask`` is the per-timestep
        features mask for variable-length sequences."""
        self._require_init()
        fn = self._get_apply(collect=False, train=train)
        return fn(self.params, self.state, jnp.asarray(x),
                  self._inference_rng(train),
                  None if mask is None else jnp.asarray(mask))

    def feed_forward(self, x, train: bool = False, mask=None) -> List[jnp.ndarray]:
        """All layer activations (feedForward :675)."""
        self._require_init()
        fn = self._get_apply(collect=True, train=train)
        return fn(self.params, self.state, jnp.asarray(x),
                  self._inference_rng(train),
                  None if mask is None else jnp.asarray(mask))

    def score(self, ds: DataSet, train: bool = False):
        """Loss on one dataset (MultiLayerNetwork.score parity)."""
        self._require_init()
        loss, _ = self._loss(
            self.params, self.state, jnp.asarray(ds.features),
            jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
            rng=None, train=train)
        return float(loss)

    def evaluate(self, iterator):
        """Classification evaluation over an iterator (evaluate :2413)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = Evaluation()
        if isinstance(iterator, DataSet):
            iterator = ListDataSetIterator([iterator])
        for ds in iterator:
            out = np.asarray(self.output(ds.features, mask=ds.features_mask))
            ev.eval(ds.labels, out, mask=ds.labels_mask)
        return ev

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        ev = RegressionEvaluation()
        if isinstance(iterator, DataSet):
            iterator = ListDataSetIterator([iterator])
        for ds in iterator:
            out = np.asarray(self.output(ds.features, mask=ds.features_mask))
            ev.eval(ds.labels, out, mask=ds.labels_mask)
        return ev

    # ---------------------------------------------------------------- misc
    def num_params(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    def summary(self) -> str:
        lines = ["=" * 70]
        lines.append(f"{'name':<18}{'type':<16}{'out type':<22}{'params':>10}")
        lines.append("-" * 70)
        for layer in self.layers:
            p = self.params.get(layer.name, {})
            n = sum(int(np.prod(a.shape)) for a in jax.tree_util.tree_leaves(p))
            lines.append(
                f"{layer.name:<18}{layer.conf.layer_type:<16}"
                f"{str(layer.output_type.kind):<22}{n:>10}")
        lines.append("-" * 70)
        lines.append(f"total params: {self.num_params()}")
        lines.append("=" * 70)
        return "\n".join(lines)

    def clone(self):
        """Deep copy (Model.clone parity) — used by transfer learning.
        Leaves are materially copied (jnp.copy): the jitted train step
        donates its input buffers, so an aliasing clone would be invalidated
        by the next fit_batch on either net."""
        net = MultiLayerNetwork(self.conf)
        net.init(structure_only=True)
        net.params = jax.tree_util.tree_map(jnp.copy, self.params)
        net.state = jax.tree_util.tree_map(jnp.copy, self.state)
        net.opt_state = jax.tree_util.tree_map(jnp.copy, self.opt_state)
        net.iteration = self.iteration
        net.epoch = self.epoch
        return net
