"""Updaters (optimizer state) + learning-rate schedules + gradient
normalization.

Parity with the reference's updater subsystem
(nn/updater/LayerUpdater.java: per-variable GradientUpdater construction at
:259-278 for SGD/ADAM/ADADELTA/NESTEROVS/ADAGRAD/RMSPROP; gradient
clipping/normalization `preApply` at :186 per GradientNormalization;
learning-rate schedules via LearningRatePolicy).

TPU-native design: an updater is a pure pytree transform —
``init_state(params) -> state`` and
``update(grads, state, lr) -> (deltas, new_state)`` with
``new_params = params - deltas``. The whole update runs inside the single
jitted train step; per-layer updaters simply apply to that layer's subtree.
Unlike the reference there is no flat state vector with views
(MultiLayerUpdater.java:161) — state is a pytree mirroring params, which XLA
lays out and fuses freely.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

_UPDATERS: dict[str, type] = {}


def _lr_dtype(lr):
    """The dtype lr arithmetic should run in: the schedule output's own
    dtype (the policy's master dtype once apply_layer_updates routed it
    through), falling back to f32 for plain-float callers. Keeps bias
    corrections and scheduled rates pinned to the master dtype instead
    of drifting with `jax_enable_x64` weak-type promotion."""
    return lr.dtype if hasattr(lr, "dtype") else jnp.float32


def register_updater(cls):
    _UPDATERS[cls.kind] = cls
    return cls


def updater_from_dict(d: dict) -> "Updater":
    d = dict(d)
    kind = d.pop("kind")
    return _UPDATERS[kind](**d)


@dataclass(frozen=True)
class Updater:
    """Base optimizer config. Stateless; per-variable state is a pytree."""

    kind = "base"
    learning_rate: float = 0.1

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["kind"] = self.kind
        return d

    def init_state(self, params):
        return {}

    def update(self, grads, state, lr):
        raise NotImplementedError

    def _zeros_like(self, params):
        return jax.tree_util.tree_map(jnp.zeros_like, params)


@register_updater
@dataclass(frozen=True)
class Sgd(Updater):
    kind = "sgd"

    def init_state(self, params):
        return {}

    def update(self, grads, state, lr):
        return jax.tree_util.tree_map(lambda g: lr * g, grads), state


@register_updater
@dataclass(frozen=True)
class Nesterovs(Updater):
    """Nesterov momentum, matching ND4J's NesterovsUpdater formulation:
    vPrev = v; v = mu*v - lr*g; update = -(mu*vPrev - (1+mu)*v)
    (equivalently: update applied = mu^2*vPrev - (1+mu)*mu*... — we keep the
    ND4J two-line form)."""

    kind = "nesterovs"
    learning_rate: float = 0.1
    momentum: float = 0.9

    def init_state(self, params):
        return {"v": self._zeros_like(params)}

    def update(self, grads, state, lr):
        mu = self.momentum

        def upd(g, v):
            v_new = mu * v - lr * g
            delta = mu * v - (1.0 + mu) * v_new  # subtracted from params
            return delta, v_new

        pairs = jax.tree_util.tree_map(upd, grads, state["v"])
        deltas = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                        is_leaf=lambda x: isinstance(x, tuple))
        v = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
        return deltas, {"v": v}


@register_updater
@dataclass(frozen=True)
class Adam(Updater):
    kind = "adam"
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {
            "m": self._zeros_like(params),
            "v": self._zeros_like(params),
            "t": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, state, lr):
        t = state["t"] + 1
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(
            lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)
        tf = t.astype(_lr_dtype(lr))
        alpha = lr * jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
        deltas = jax.tree_util.tree_map(
            lambda m_, v_: alpha * m_ / (jnp.sqrt(v_) + self.epsilon), m, v)
        return deltas, {"m": m, "v": v, "t": t}


@register_updater
@dataclass(frozen=True)
class AdaMax(Updater):
    kind = "adamax"
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {
            "m": self._zeros_like(params),
            "u": self._zeros_like(params),
            "t": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, state, lr):
        t = state["t"] + 1
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(
            lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        u = jax.tree_util.tree_map(
            lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g)), state["u"], grads)
        tf = t.astype(_lr_dtype(lr))
        alpha = lr / (1 - b1 ** tf)
        deltas = jax.tree_util.tree_map(
            lambda m_, u_: alpha * m_ / (u_ + self.epsilon), m, u)
        return deltas, {"m": m, "u": u, "t": t}


@register_updater
@dataclass(frozen=True)
class AdaGrad(Updater):
    kind = "adagrad"
    learning_rate: float = 1e-1
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"h": self._zeros_like(params)}

    def update(self, grads, state, lr):
        h = jax.tree_util.tree_map(lambda h_, g: h_ + g * g, state["h"], grads)
        deltas = jax.tree_util.tree_map(
            lambda g, h_: lr * g / (jnp.sqrt(h_) + self.epsilon), grads, h)
        return deltas, {"h": h}


@register_updater
@dataclass(frozen=True)
class AdaDelta(Updater):
    kind = "adadelta"
    rho: float = 0.95
    epsilon: float = 1e-6
    learning_rate: float = 1.0  # unused by the rule; kept for API uniformity

    def init_state(self, params):
        return {"eg": self._zeros_like(params), "ex": self._zeros_like(params)}

    def update(self, grads, state, lr):
        rho, eps = self.rho, self.epsilon

        def upd(g, eg, ex):
            eg_new = rho * eg + (1 - rho) * g * g
            delta = jnp.sqrt(ex + eps) / jnp.sqrt(eg_new + eps) * g
            ex_new = rho * ex + (1 - rho) * delta * delta
            return delta, eg_new, ex_new

        triples = jax.tree_util.tree_map(upd, grads, state["eg"], state["ex"])
        is_t = lambda x: isinstance(x, tuple)
        deltas = jax.tree_util.tree_map(lambda p: p[0], triples, is_leaf=is_t)
        eg = jax.tree_util.tree_map(lambda p: p[1], triples, is_leaf=is_t)
        ex = jax.tree_util.tree_map(lambda p: p[2], triples, is_leaf=is_t)
        return deltas, {"eg": eg, "ex": ex}


@register_updater
@dataclass(frozen=True)
class RmsProp(Updater):
    kind = "rmsprop"
    learning_rate: float = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"g2": self._zeros_like(params)}

    def update(self, grads, state, lr):
        d = self.rms_decay
        g2 = jax.tree_util.tree_map(
            lambda a, g: d * a + (1 - d) * g * g, state["g2"], grads)
        deltas = jax.tree_util.tree_map(
            lambda g, a: lr * g / (jnp.sqrt(a + self.epsilon)), grads, g2)
        return deltas, {"g2": g2}


@register_updater
@dataclass(frozen=True)
class NoOp(Updater):
    """For frozen layers (FrozenLayer.java parity): gradient is discarded."""

    kind = "noop"
    learning_rate: float = 0.0

    def update(self, grads, state, lr):
        return jax.tree_util.tree_map(jnp.zeros_like, grads), state


# ---------------------------------------------------------------------------
# Learning-rate schedules (LearningRatePolicy parity)
# ---------------------------------------------------------------------------

_SCHEDULES: dict[str, type] = {}


def register_schedule(cls):
    _SCHEDULES[cls.kind] = cls
    return cls


def schedule_from_dict(d):
    if d is None:
        return NoneSchedule()
    d = dict(d)
    kind = d.pop("kind")
    # JSON turns int dict keys into strings; restore for map schedules.
    if "schedule" in d and isinstance(d["schedule"], dict):
        d["schedule"] = {int(k): float(v) for k, v in d["schedule"].items()}
    return _SCHEDULES[kind](**d)


@dataclass(frozen=True)
class Schedule:
    """Schedule math runs entirely in ``dtype`` — the policy's master
    dtype when called from ``apply_layer_updates``, f32 for callers that
    don't pass one. This pins the scheduled rate regardless of the
    compute dtype and of `jax_enable_x64` (a bare Python float would
    weak-type-promote under x64)."""

    kind = "base"

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["kind"] = self.kind
        return d

    def __call__(self, base_lr, step, dtype=None):
        raise NotImplementedError


@register_schedule
@dataclass(frozen=True)
class NoneSchedule(Schedule):
    kind = "none"

    def __call__(self, base_lr, step, dtype=None):
        return jnp.asarray(base_lr, dtype or jnp.float32)


@register_schedule
@dataclass(frozen=True)
class Exponential(Schedule):
    kind = "exponential"
    decay_rate: float = 0.99

    def __call__(self, base_lr, step, dtype=None):
        dtype = dtype or jnp.float32
        return jnp.asarray(base_lr, dtype) * jnp.asarray(
            self.decay_rate, dtype) ** step.astype(dtype)


@register_schedule
@dataclass(frozen=True)
class Inverse(Schedule):
    kind = "inverse"
    gamma: float = 1e-3
    power: float = 1.0

    def __call__(self, base_lr, step, dtype=None):
        dtype = dtype or jnp.float32
        return jnp.asarray(base_lr, dtype) / (
            1.0 + self.gamma * step.astype(dtype)) ** self.power


@register_schedule
@dataclass(frozen=True)
class Poly(Schedule):
    kind = "poly"
    power: float = 1.0
    max_iter: int = 10000

    def __call__(self, base_lr, step, dtype=None):
        dtype = dtype or jnp.float32
        frac = jnp.clip(step.astype(dtype) / self.max_iter, 0.0, 1.0)
        return jnp.asarray(base_lr, dtype) * (1.0 - frac) ** self.power


@register_schedule
@dataclass(frozen=True)
class Sigmoid(Schedule):
    kind = "sigmoid"
    gamma: float = 1e-2
    steps: int = 1000

    def __call__(self, base_lr, step, dtype=None):
        dtype = dtype or jnp.float32
        return jnp.asarray(base_lr, dtype) / (
            1.0 + jnp.exp(self.gamma * (step.astype(dtype) - self.steps)))


@register_schedule
@dataclass(frozen=True)
class Step(Schedule):
    kind = "step"
    decay_rate: float = 0.1
    steps: int = 1000

    def __call__(self, base_lr, step, dtype=None):
        dtype = dtype or jnp.float32
        return jnp.asarray(base_lr, dtype) * jnp.asarray(
            self.decay_rate, dtype) ** jnp.floor(step.astype(dtype) / self.steps)


@register_schedule
@dataclass(frozen=True)
class MapSchedule(Schedule):
    """LearningRatePolicy.Schedule: explicit {iteration: lr} map; the lr at
    step t is the value for the largest key <= t (base_lr before the first)."""

    kind = "map"
    schedule: dict = field(default_factory=dict)

    def __call__(self, base_lr, step, dtype=None):
        dtype = dtype or jnp.float32
        lr = jnp.asarray(base_lr, dtype)
        for it in sorted(self.schedule):
            lr = jnp.where(step >= it, jnp.asarray(self.schedule[it], dtype), lr)
        return lr


# ---------------------------------------------------------------------------
# Gradient normalization (GradientNormalization.java parity; LayerUpdater
# preApply at nn/updater/LayerUpdater.java:186)
# ---------------------------------------------------------------------------

def normalize_gradients(grads, mode: str | None, threshold: float = 1.0):
    """Apply a GradientNormalization mode to one layer's gradient subtree.

    Modes (matching the reference enum): None, "renormalize_l2_per_layer",
    "renormalize_l2_per_param_type", "clip_element_wise_absolute_value",
    "clip_l2_per_layer", "clip_l2_per_param_type".
    """
    if mode in (None, "none"):
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return grads
    if mode == "renormalize_l2_per_layer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale = 1.0 / jnp.maximum(norm, 1e-12)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if mode == "renormalize_l2_per_param_type":
        return jax.tree_util.tree_map(
            lambda g: g / jnp.maximum(jnp.linalg.norm(g.reshape(-1)), 1e-12), grads)
    if mode == "clip_element_wise_absolute_value":
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    if mode == "clip_l2_per_layer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale = jnp.where(norm > threshold, threshold / (norm + 1e-12), 1.0)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if mode == "clip_l2_per_param_type":
        def clip_one(g):
            n = jnp.linalg.norm(g.reshape(-1))
            s = jnp.where(n > threshold, threshold / (n + 1e-12), 1.0)
            return g * s
        return jax.tree_util.tree_map(clip_one, grads)
    raise ValueError(f"Unknown gradient normalization mode: {mode}")


def apply_layer_updates(layers, gc, params, grads, opt_state, it,
                        lr_scale: float = 1.0):
    """Apply per-layer gradient normalization + updater to every
    parameterized layer (LayerUpdater.update :74 / preApply :186 semantics,
    shared by MultiLayerNetwork and ComputationGraph train steps).

    ``lr_scale`` multiplies every layer's scheduled rate — the runtime
    lever the resilience supervisor pulls after a NaN rollback (it is a
    compile-time constant of the step; nets invalidate their cached step
    when it changes).

    Mixed-precision contract (PRECISION.md): the update runs entirely in
    the policy's master dtype. Gradients arriving in a lower compute
    dtype are upcast to each parameter's own dtype before normalization
    and the updater rule, so optimizer slots (init'd as zeros_like the
    f32 masters) never see low-precision arithmetic; the scheduled lr is
    computed in the master dtype (never the compute dtype, never x64).

    Non-layer keys in ``opt_state`` (e.g. precision's ``_loss_scale``)
    pass through untouched.

    Returns (new_params, new_opt_state)."""
    master = jnp.dtype(gc.dtype.param_dtype)
    new_params = dict(params)
    new_opt = dict(opt_state)
    for layer in layers:
        name = layer.name
        if name not in params:
            continue
        g = jax.tree_util.tree_map(
            lambda gr, p: gr.astype(p.dtype), grads[name], params[name])
        mode = layer.resolve("gradient_normalization")
        thr = float(layer.resolve("gradient_normalization_threshold", 1.0)
                    or 1.0)
        g = normalize_gradients(g, mode, thr)
        upd = layer.resolve("updater")
        base_lr = layer.conf.learning_rate
        if base_lr is None:
            base_lr = gc.learning_rate
        if base_lr is None:
            base_lr = upd.learning_rate
        lr = gc.lr_schedule(base_lr, it, dtype=master) * lr_scale
        deltas, new_opt[name] = upd.update(g, opt_state[name], lr)
        new_params[name] = jax.tree_util.tree_map(
            lambda p, d: p - d, params[name], deltas)
    return new_params, new_opt
