"""ComputationGraph — DAG network with multi-input/multi-output training.

Parity: nn/graph/ComputationGraph.java (2,447 LoC): init() :273,
topologicalSortOrder() :888 (here on the config), feedForward :1089 (walk
topo order), calcBackpropGradients :1224 (here JAX autodiff through the DAG
— fan-in epsilon accumulation falls out of reverse-mode AD), fit :701.

Like MultiLayerNetwork, ``fit`` compiles ONE jitted train step (forward over
the whole DAG + loss sum over output layers + backward + updaters fused into
a single XLA program). Multi-output losses are summed (the reference
accumulates output-layer scores the same way).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.observability import goodput as _goodput
from deeplearning4j_tpu.observability import metrics as _obs_metrics
from deeplearning4j_tpu.observability.trace import get_tracer as _get_tracer
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.layers import BaseLayerConfig
from deeplearning4j_tpu.nn import precision
from deeplearning4j_tpu.nn.updater import apply_layer_updates

def _remat_match(name: str, prefixes) -> bool:
    """Prefix match; a trailing ``$`` anchors an EXACT name (needed for
    numeric layer names where 'layer_1' would also match 'layer_1x')."""
    for p in prefixes:
        if p.endswith("$"):
            if name == p[:-1]:
                return True
        elif name.startswith(p):
            return True
    return False


def _remat_prefixes() -> tuple:
    """Selective rematerialization scope: comma-separated vertex-name
    prefixes (e.g. ``DL4J_TPU_REMAT=s0b`` recomputes every stage-1 block
    interior in the backward instead of saving it; a trailing ``$``
    anchors an exact vertex/layer name — ``layer_1$`` does not match
    ``layer_10``). The TPU answer to
    activation-memory pressure at large batch: trade cheap stage FLOPs
    for HBM residency. Granularity is BLOCK-level: each maximal
    contiguous topo run of matching vertices executes under one
    jax.checkpoint, so only the span's INPUTS are saved and XLA keeps
    full scheduling freedom elsewhere. (The alternative — wrapping the
    whole loss in a jax.checkpoint name-policy — was measured NEGATIVE:
    forcing every untagged intermediate into the explicit residual set
    cost +18 GB/step and +3.8 GB peak on ResNet-50, PERF.md round 5.)
    Default off."""
    import os
    v = os.environ.get("DL4J_TPU_REMAT", "").strip()
    return tuple(p for p in (s.strip() for s in v.split(",")) if p)


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo = conf.topological_order()
        self.layers = None          # runtime layer objects (layer vertices)
        self.vertex_kind = None     # name -> "layer" | "vertex"
        self.params = None
        self.state = None
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self.score_value = None
        self._train_step = None
        self._tbptt_step = None
        self._multi_steps = {}
        self._apply_fns = {}
        self._mesh = None
        self._rng_key = None
        self._rnn_state = None
        # DL4J_TPU_REMAT resolved at train-step build time (None until
        # then); later env-var changes are no-ops for this model
        self.remat_prefixes = None
        self._remat_warned = False
        # runtime learning-rate multiplier (resilience NaN backoff); a
        # compile-time constant of the fused step — set via set_lr_scale
        self._lr_scale = 1.0

    def set_lr_scale(self, scale: float):
        """Scale every layer's scheduled learning rate by ``scale`` from
        the next step on (resilience/supervisor.py backs off the rate
        after a NaN rollback). Baked into the compiled step — every
        cached step variant is invalidated, so expect one recompile per
        change."""
        scale = float(scale)
        if scale <= 0.0:
            raise ValueError(f"lr scale must be > 0, got {scale}")
        if scale != self._lr_scale:
            self._lr_scale = scale
            self._train_step = None
            self._tbptt_step = None
            self._multi_steps = {}
        return self

    def resilient_fit(self, data, labels=None, *, checkpoint_dir: str,
                      epochs: int = 1, batch_size: int = 32, **supervisor_kw):
        """Supervised ``fit`` with checkpoint/resume, retry, NaN rollback
        and preemption handling — see resilience/supervisor.py."""
        from deeplearning4j_tpu.resilience import resilient_fit
        return resilient_fit(self, data, labels,
                             checkpoint_dir=checkpoint_dir, epochs=epochs,
                             batch_size=batch_size, **supervisor_kw)

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None, *, structure_only: bool = False):
        gc = self.conf.global_conf
        seed = gc.seed if seed is None else seed
        self._rng_key = jax.random.PRNGKey(seed)

        # resolve InputTypes through the DAG
        input_types: Dict[str, object] = {}
        if self.conf.input_types is not None:
            for name, it in zip(self.conf.network_inputs, self.conf.input_types):
                input_types[name] = it

        self.layers = []
        self._layer_by_name = {}
        self.vertex_kind = {}
        self._resolved_confs = {}
        for name in self.topo:
            conf = self.conf.vertices[name]
            in_names = self.conf.vertex_inputs[name]
            in_types = [input_types.get(i) for i in in_names]
            if isinstance(conf, BaseLayerConfig):
                self.vertex_kind[name] = "layer"
                if len(in_names) != 1:
                    raise ValueError(
                        f"Layer vertex '{name}' must have exactly 1 input, "
                        f"got {in_names} (merge first — MergeVertex)")
                it = in_types[0]
                if it is not None:
                    conf = conf.with_n_in(it)
                if getattr(conf, "n_in", 1) is None:
                    raise ValueError(
                        f"Layer vertex '{name}': n_in not set and no "
                        f"input type available for inference")
                layer = conf.make_layer(it, gc, gc.dtype)
                self.layers.append(layer)
                self._layer_by_name[name] = layer
                self._resolved_confs[name] = conf
                input_types[name] = layer.output_type
            else:
                self.vertex_kind[name] = "vertex"
                self._resolved_confs[name] = conf
                if all(t is not None for t in in_types):
                    input_types[name] = conf.output_type(*in_types)
                else:
                    input_types[name] = None

        # block-fusion pass: pattern-match bottleneck tails on the RESOLVED
        # configs (nn/fusion.py); applied in _walk for training walks only
        from deeplearning4j_tpu.nn import fusion as _fusion
        self._fusion_plans = _fusion.find_fusable_chains(
            self._resolved_confs, self.conf.vertex_inputs,
            self.conf.network_outputs,
            default_activation=gc.activation or "sigmoid")
        self._fusion_interior = _fusion.interior_vertices(self._fusion_plans)

        def init_trees(key):
            params, state = {}, {}
            for layer in self.layers:
                key_, sub = jax.random.split(key)
                key = key_
                p = layer.init_params(sub)
                if p:
                    params[layer.name] = p
                s = layer.init_state()
                if s:
                    state[layer.name] = s
            opt_state = {}
            for layer in self.layers:
                if layer.name in params:
                    upd = layer.resolve("updater")
                    opt_state[layer.name] = upd.init_state(params[layer.name])
            ls = precision.init_loss_scale_state(gc.dtype)
            if ls is not None:
                opt_state[precision.LOSS_SCALE_KEY] = ls
            return params, state, opt_state

        if structure_only:
            self.params, self.state, self.opt_state = jax.eval_shape(
                init_trees, self._rng_key)
        else:
            self.params, self.state, self.opt_state = init_trees(self._rng_key)
        self.iteration = 0
        self._train_step = None
        self._tbptt_step = None
        self._multi_steps = {}
        self._apply_fns = {}
        self._rnn_state = None
        return self

    def materialize_state(self):
        state = {}
        for layer in self.layers:
            s = layer.init_state()
            if s:
                state[layer.name] = s
        self.state = state

    def materialize_opt_state(self):
        opt_state = {}
        for layer in self.layers:
            if layer.name in self.params:
                upd = layer.resolve("updater")
                opt_state[layer.name] = upd.init_state(self.params[layer.name])
        ls = precision.init_loss_scale_state(self.conf.global_conf.dtype)
        if ls is not None:
            opt_state[precision.LOSS_SCALE_KEY] = ls
        self.opt_state = opt_state

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def use_mesh(self, mesh, data_axis: str = "data",
                 model_axis: str | None = None, tp_rules=None):
        """Sharded training over a Mesh: data-parallel by default;
        ``model_axis`` additionally shards weights column-parallel over
        that axis (dp x tp — see parallel/tensor.py)."""
        self._mark_meshed(mesh, data_axis, model_axis, tp_rules)
        if model_axis is not None:
            from deeplearning4j_tpu.parallel.tensor import (
                apply_tensor_parallel)
            apply_tensor_parallel(self, mesh, data_axis, model_axis,
                                  tp_rules)
        else:
            from deeplearning4j_tpu.parallel.data_parallel import apply_mesh
            apply_mesh(self, mesh, data_axis)
        return self

    def _mark_meshed(self, mesh, data_axis: str = "data",
                     model_axis=None, tp_rules=None):
        """Record mesh placement + drop compiled-step caches WITHOUT
        moving a single leaf (see MultiLayerNetwork._mark_meshed — the
        elastic restore path in utils/checkpoint.py places leaves
        directly into their target NamedShardings first)."""
        self._mesh = (mesh, data_axis)
        self._mesh_detail = {"model_axis": model_axis, "tp_rules": tp_rules}
        self._train_step = None
        self._tbptt_step = None
        self._multi_steps = {}
        self._apply_fns = {}
        self._rnn_state = None
        return self


    def step_cost_analysis(self, mds) -> dict:
        """XLA cost-model numbers for ONE compiled train step on this
        batch shape: {"flops", "bytes_accessed"} (feeds
        PerformanceListener(flops_per_step=...) for live MFU)."""
        self._require_init()
        mds = self._coerce(mds)
        if self._train_step is None:
            self._train_step = self._build_train_step()
        from deeplearning4j_tpu.utils.perf import xla_step_cost
        inputs, fmasks = self._prepare_inputs(mds.features,
                                              mds.features_masks)
        labels = [jnp.asarray(l) for l in mds.labels]
        it = jnp.asarray(self.iteration, jnp.int32)
        rng = jax.random.PRNGKey(0)
        return xla_step_cost(self._train_step, self.params, self.state,
                             self.opt_state, it, inputs, labels, fmasks,
                             None, rng)

    def _require_init(self):
        if self.params is None:
            raise RuntimeError("Call init() before fit()/output()/evaluate()")

    # -------------------------------------------------- selective remat
    def _remat_spans(self, prefixes, skip: set) -> Dict[str, list]:
        """Maximal contiguous topo runs of prefix-matching vertices,
        keyed by first vertex. Excludes loss-bearing layers, vertices the
        caller needs inputs of, and the named-input rnn vertices (their
        mask wiring is not replicated inside a span)."""
        from deeplearning4j_tpu.nn.conf.vertices import (
            DuplicateToTimeSeriesVertex, LastTimeStepVertex)
        spans: Dict[str, list] = {}
        run: list = []

        def close():
            if run:
                spans[run[0]] = list(run)
                run.clear()

        for name in self.topo:
            conf = self._resolved_confs[name]
            layer = self._layer_by_name.get(name)
            ok = (_remat_match(name, prefixes)
                  and name not in skip
                  and not (layer is not None and hasattr(layer, "loss"))
                  and not isinstance(conf, (LastTimeStepVertex,
                                            DuplicateToTimeSeriesVertex)))
            if ok:
                run.append(name)
            else:
                close()
        close()
        return spans

    def _span_ext_inputs(self, span: list) -> list:
        span_set = set(span)
        ext = []
        for v in span:
            for src in self.conf.vertex_inputs[v]:
                if src not in span_set and src not in ext:
                    ext.append(src)
        return ext

    def _run_remat_span(self, span, params, state, acts, masks, new_state,
                        rng):
        """Execute one contiguous vertex span under jax.checkpoint.
        Mutates acts/masks/new_state; returns (advanced rng, span len)."""
        span_set = set(span)
        ext = {src: acts[src] for src in self._span_ext_inputs(span)}
        # span outputs: vertices consumed outside the span (or network
        # outputs); these are the only activations that leave the
        # checkpoint boundary — everything interior is recomputed
        consumed_outside = set(self.conf.network_outputs)
        for v, ins in self.conf.vertex_inputs.items():
            if v not in span_set:
                consumed_outside.update(ins)
        outs = [v for v in span if v in consumed_outside] or [span[-1]]
        rngs = {}
        if rng is not None:
            for v in span:
                if self.vertex_kind[v] == "layer":
                    rng, lr = jax.random.split(rng)
                    rngs[v] = lr
        p_sub = {v: params[v] for v in span if v in params}
        s_sub = {v: state[v] for v in span if v in state}

        def run_span(p_sub, s_sub, ext, rngs):
            local = dict(ext)
            ns = {}
            for v in span:
                conf = self._resolved_confs[v]
                xs = [local[i] for i in self.conf.vertex_inputs[v]]
                if self.vertex_kind[v] == "layer":
                    layer = self._layer_by_name[v]
                    y, s_new = layer.apply(
                        p_sub.get(v, {}), s_sub.get(v, {}), xs[0],
                        train=True, rng=rngs.get(v), mask=None)
                    if s_new:
                        ns[v] = s_new
                    local[v] = y
                else:
                    local[v] = conf.forward(*xs, masks=[None] * len(xs))
            return {v: local[v] for v in outs}, ns

        out_acts, ns = jax.checkpoint(run_span)(p_sub, s_sub, ext, rngs)
        acts.update(out_acts)
        for v in span:
            masks[v] = None
        new_state.update(ns)
        return rng, len(span)

    # -------------------------------------------------------------- forward
    def _walk(self, params, state, inputs: Dict, *, train, rng,
              fmasks: Optional[Dict] = None, need_inputs_of=()):
        """Walk the DAG in topo order. Returns (activations dict, per-vertex
        input activations for ``need_inputs_of``, masks dict, new_state)."""
        acts = dict(inputs)
        masks = dict(fmasks or {})
        saved_inputs = {}
        new_state = dict(state)
        from deeplearning4j_tpu.nn.conf.vertices import (
            DuplicateToTimeSeriesVertex, LastTimeStepVertex)
        # training walks route matched bottleneck tails through the fused
        # op (nn/fusion.py); eval walks use the per-vertex path (running
        # statistics, no batch stats)
        plans = getattr(self, "_fusion_plans", None) or {}
        if not train:
            plans = {}
        interior = self._fusion_interior if plans else frozenset()
        # selective block remat: maximal contiguous topo runs of vertices
        # matching DL4J_TPU_REMAT prefixes execute under one
        # jax.checkpoint (span inputs saved, interiors recomputed in the
        # backward). Plain path only: fusion plans and masked inputs
        # fall back to inline execution.
        remat = ((self.remat_prefixes if self.remat_prefixes is not None
                  else _remat_prefixes()) if train else ())
        spans = (self._remat_spans(remat, set(need_inputs_of))
                 if remat and not plans else {})
        topo_i = 0
        topo = self.topo
        while topo_i < len(topo):
            name = topo[topo_i]
            span = spans.get(name)
            if span is not None and not any(
                    masks.get(e) is not None
                    for e in self._span_ext_inputs(span)):
                rng, step = self._run_remat_span(
                    span, params, state, acts, masks, new_state, rng)
                topo_i += step
                continue
            topo_i += 1
            if name in interior:
                continue
            if name in plans:
                from deeplearning4j_tpu.nn import fusion as _fusion
                fb = plans[name]
                y, bn_state_new = _fusion.execute_fused_tail(
                    fb, self, params, state, acts)
                acts[name] = y
                masks[name] = None
                new_state[fb.bn] = bn_state_new
                continue
            conf = self._resolved_confs[name]
            in_names = self.conf.vertex_inputs[name]
            xs = [acts[i] for i in in_names]
            in_masks = [masks.get(i) for i in in_names]
            # named-input wiring for the rnn vertices (reference API:
            # LastTimeStepVertex(maskArrayInput), DuplicateToTimeSeriesVertex
            # (inputName)) — the named vertex supplies the mask / time length
            if isinstance(conf, LastTimeStepVertex) and conf.mask_input:
                in_masks = [masks.get(conf.mask_input)]
            if (isinstance(conf, DuplicateToTimeSeriesVertex)
                    and conf.seq_input):
                xs = [xs[0], acts[conf.seq_input]]
                in_masks = [in_masks[0], masks.get(conf.seq_input)]
            if name in need_inputs_of:
                saved_inputs[name] = (xs, in_masks)
            if self.vertex_kind[name] == "layer":
                layer = self._layer_by_name[name]
                lrng = None
                if rng is not None:
                    rng, lrng = jax.random.split(rng)
                p = params.get(name, {})
                s = state.get(name, {})
                y, s_new = layer.apply(p, s, xs[0], train=train, rng=lrng,
                                       mask=in_masks[0])
                if s_new:
                    new_state[name] = s_new
                acts[name] = y
                masks[name] = layer.feed_forward_mask(in_masks[0])
            else:
                acts[name] = conf.forward(*xs, masks=in_masks)
                masks[name] = conf.feed_forward_mask(*in_masks)
        return acts, saved_inputs, masks, new_state

    def _prepare_inputs(self, features: List, fmasks: Optional[List]):
        inputs = {n: jnp.asarray(f)
                  for n, f in zip(self.conf.network_inputs, features)}
        md = {}
        if fmasks is not None:
            for n, m in zip(self.conf.network_inputs, fmasks):
                if m is not None:
                    md[n] = jnp.asarray(m)
        return inputs, md

    def _loss(self, params, state, inputs, labels, fmasks, lmasks, rng,
              train=True):
        """Sum of output-layer losses + regularization (the scalar the
        jitted step autodiffs)."""
        rng_fwd = lrng = None
        if rng is not None:
            rng_fwd, lrng = jax.random.split(rng)
        outs = self.conf.network_outputs
        acts, saved, masks, new_state = self._walk(
            params, state, inputs, train=train, rng=rng_fwd, fmasks=fmasks,
            need_inputs_of=set(outs))
        total = None
        for i, name in enumerate(outs):
            layer = self._layer_by_name.get(name)
            if layer is None or not hasattr(layer, "loss"):
                raise ValueError(
                    f"Network output '{name}' is not a loss-bearing layer "
                    f"(Output/RnnOutput/LossLayer)")
            xs, in_masks = saved[name]
            this_rng = None
            if lrng is not None:
                lrng, this_rng = jax.random.split(lrng)
            lm = None if lmasks is None else lmasks[i]
            if getattr(layer, "loss_uses_state", False):
                s_out = state.get(name, {})
                l = layer.loss(params.get(name, {}), xs[0], labels[i],
                               train=train, rng=this_rng, mask=lm, state=s_out)
                if train and hasattr(layer, "update_centers"):
                    new_state[name] = layer.update_centers(
                        s_out, jax.lax.stop_gradient(xs[0]), labels[i],
                        mask=lm)
            else:
                l = layer.loss(params.get(name, {}), xs[0], labels[i],
                               train=train, rng=this_rng, mask=lm)
            total = l if total is None else total + l
        for layer in self.layers:
            if layer.name in params:
                total = total + layer.regularization(params[layer.name])
        return total, new_state

    # ---------------------------------------------------------- train step
    def _resolve_remat(self) -> tuple:
        """Read DL4J_TPU_REMAT exactly ONCE — when the first train step
        is built — and record the resolved prefixes on the model
        (``self.remat_prefixes``). The jitted step is cached, so a later
        env-var change can never take effect; resolving eagerly (and
        warning on a detected change) keeps remat experiments from
        silently measuring a stale configuration."""
        current = _remat_prefixes()
        if self.remat_prefixes is None:
            self.remat_prefixes = current
        elif current != self.remat_prefixes and not self._remat_warned:
            import warnings
            warnings.warn(
                f"DL4J_TPU_REMAT changed to {current!r} after the train "
                f"step was built with {self.remat_prefixes!r}; the cached "
                "step ignores the change (set the variable before the "
                "first training step, or rebuild the model)",
                RuntimeWarning, stacklevel=3)
            self._remat_warned = True
        return self.remat_prefixes

    def _step_fn(self):
        """The raw (un-jitted) fused train step: fwd+bwd+normalize+update,
        with loss scaling when the dtype policy calls for it (f16) —
        see nn/precision.py."""
        self._resolve_remat()
        gc = self.conf.global_conf

        def loss_fn(params, state, inputs, labels, fmasks, lmasks, rng):
            return self._loss(params, state, inputs, labels, fmasks, lmasks,
                              rng)

        return precision.build_step_fn(loss_fn, self.layers, gc,
                                       self._lr_scale)

    def _build_train_step(self):
        step_fn = self._step_fn()
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.data_parallel import (
                shard_step_multi)
            return shard_step_multi(self, step_fn, *self._mesh)
        return jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def fit_batch_repeated(self, mds, n_steps: int):
        """Run ``n_steps`` optimization steps on one minibatch inside a
        SINGLE XLA execution (``lax.scan`` over the fused train step) —
        one host dispatch instead of n. See
        MultiLayerNetwork.fit_batch_repeated."""
        self._require_init()
        if n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        mds = self._coerce(mds)
        if self._mesh is not None or self.conf.backprop_type == "tbptt":
            # meshed execution needs shard_step_multi's batch handling;
            # tbptt needs chunked backprop — both route through fit_batch
            # (n dispatches) to keep semantics identical
            for _ in range(n_steps):
                score = self.fit_batch(mds)
            return score
        from deeplearning4j_tpu.nn.multistep import get_multi_step
        jitted = get_multi_step(self, n_steps)
        self._rng_key, rng = jax.random.split(self._rng_key)
        inputs, fmasks = self._prepare_inputs(mds.features, mds.features_masks)
        labels = [jnp.asarray(l) for l in mds.labels]
        lmasks = [None if m is None else jnp.asarray(m)
                  for m in mds.labels_masks]
        if all(m is None for m in lmasks):
            lmasks = None
        it = jnp.asarray(self.iteration, jnp.int32)
        self.params, self.state, self.opt_state, score = jitted(
            self.params, self.state, self.opt_state, it, inputs, labels,
            fmasks, lmasks, rng)
        self.iteration += n_steps
        self.score_value = score
        _goodput.observe_steps(n_steps)
        return score

    @staticmethod
    def _coerce(data) -> MultiDataSet:
        if isinstance(data, MultiDataSet):
            return data
        if isinstance(data, DataSet):
            return MultiDataSet.from_dataset(data)
        raise TypeError(f"Expected DataSet or MultiDataSet, got {type(data)}")

    # ------------------------------------------------ recurrent state helpers
    def _set_streaming(self, flag: bool):
        from deeplearning4j_tpu.nn.layers.recurrent import set_streaming
        set_streaming(self.layers, flag)

    def _strip_carries(self, state):
        from deeplearning4j_tpu.nn.layers.recurrent import strip_carries
        return strip_carries(state)

    def rnn_clear_previous_state(self):
        """Reset streaming decode state (rnnClearPreviousState parity)."""
        self._rnn_state = None

    def rnn_time_step(self, *features, masks=None):
        """Stateful streaming inference (ComputationGraph.rnnTimeStep
        parity): feed one step [b, f] or a chunk [b, t, f] per network
        input; recurrent layer vertices carry (h, c) across calls."""
        self._require_init()
        feats = [jnp.asarray(f) for f in features]
        # single-step mode: no input carries a time axis. Recurrent-typed
        # inputs are expanded to [b, 1, f]; static 2d inputs (e.g. the
        # non-sequence side of DuplicateToTimeSeries) are left alone.
        single = all(f.ndim == 2 for f in feats)
        if single:
            # untyped inputs default to time-series (matching the
            # MultiLayerNetwork behavior); only inputs explicitly typed
            # non-recurrent (e.g. the static side of
            # DuplicateToTimeSeries) stay 2d
            its = self.conf.input_types or [None] * len(feats)
            feats = [f[:, None, :]
                     if (it is None or it.kind == "recurrent")
                     else f
                     for f, it in zip(feats, its)]
        self._set_streaming(True)
        try:
            key = "stream"
            if key not in self._apply_fns:
                def fn(params, state, inputs, fmasks):
                    acts, _, _, new_state = self._walk(
                        params, state, inputs, train=False, rng=None,
                        fmasks=fmasks)
                    return (tuple(acts[o]
                                  for o in self.conf.network_outputs),
                            new_state)
                self._apply_fns[key] = jax.jit(fn)
            inputs, fmasks = self._prepare_inputs(feats, masks)
            state_in = getattr(self, "_rnn_state", None)
            if state_in is None:
                state_in = self.state
            outs, new_state = self._apply_fns[key](self.params, state_in,
                                                   inputs, fmasks)
            self._rnn_state = new_state
        finally:
            self._set_streaming(False)
        if single:
            outs = tuple(o[:, 0, :] if o.ndim == 3 else o for o in outs)
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------------- training
    def _fit_tbptt(self, mds):
        """Truncated BPTT on the DAG (ComputationGraphConfiguration tBPTT /
        ComputationGraph.doTruncatedBPTT parity): split the time axis of
        every time-series input/label into tbptt_fwd_length chunks;
        recurrent vertices carry (h, c) across chunks via the state pytree,
        reset per batch. Static (2d) inputs are fed whole to every chunk."""
        L = self.conf.tbptt_fwd_length
        feats = [jnp.asarray(f) for f in mds.features]
        labels = [jnp.asarray(l) for l in mds.labels]
        if any(l.ndim == 2 for l in labels):
            raise ValueError(
                "tBPTT requires per-timestep labels [batch, time, out]; got "
                "a 2d (sequence-classification) label — use "
                "backprop_type='standard' for sequence classification")
        t_lens = {f.shape[1] for f in feats if f.ndim == 3}
        t_lens |= {l.shape[1] for l in labels if l.ndim == 3}
        if len(t_lens) != 1:
            raise ValueError(
                "tBPTT requires all time-series inputs AND per-timestep "
                "labels to share one time length; got time lengths "
                f"{sorted(t_lens)} (sequence-classification labels need "
                "backprop_type='standard')")
        t_total = t_lens.pop()
        fmasks = [None if m is None else jnp.asarray(m)
                  for m in mds.features_masks]
        lmasks = [None if m is None else jnp.asarray(m)
                  for m in mds.labels_masks]

        def chunk(a, sl, time_like):
            if a is None:
                return None
            return a[:, sl] if time_like(a) else a

        self._set_streaming(True)
        try:
            if getattr(self, "_tbptt_step", None) is None:
                self._tbptt_step = self._build_train_step()
            score_sum, weight = 0.0, 0
            _dev_span = _get_tracer().span("device_step", tbptt=True)
            _dev_span.__enter__()
            for start in range(0, t_total, L):
                sl = slice(start, min(start + L, t_total))
                inputs = {n: chunk(f, sl, lambda a: a.ndim == 3)
                          for n, f in zip(self.conf.network_inputs, feats)}
                lab = [chunk(l, sl, lambda a: a.ndim == 3) for l in labels]
                fm = {n: chunk(m, sl, lambda a: a.ndim == 2)
                      for n, m in zip(self.conf.network_inputs, fmasks)
                      if m is not None}
                lm = [chunk(m, sl, lambda a: a.ndim == 2) for m in lmasks]
                if all(m is None for m in lm):
                    lm = None
                self._rng_key, rng = jax.random.split(self._rng_key)
                it = jnp.asarray(self.iteration, jnp.int32)
                (self.params, self.state, self.opt_state,
                 chunk_score) = self._tbptt_step(
                    self.params, self.state, self.opt_state, it, inputs,
                    lab, fm, lm, rng)
                w = sl.stop - sl.start
                # accumulate ON DEVICE: a float() here would sync the
                # pipeline once per chunk; consumers pull the final mean
                score_sum = score_sum + chunk_score * w
                weight += w
            _dev_span.__exit__(None, None, None)
            self.state = self._strip_carries(self.state)
            score = score_sum / max(weight, 1)
        finally:
            self._set_streaming(False)
        self.iteration += 1
        self.score_value = score
        self.last_batch_examples = mds.num_examples
        _goodput.observe_steps(1)
        with _get_tracer().span("score_sync"):
            for l in self.listeners:
                l.iteration_done(self, self.iteration, self.epoch)
        return score

    def fit_batch(self, mds):
        """One optimization step on one (Multi)DataSet minibatch
        (ComputationGraph.fit parity)."""
        self._require_init()
        mds = self._coerce(mds)
        if self.conf.backprop_type == "tbptt":
            t_dims = {f.shape[1] for f in mds.features
                      if getattr(f, "ndim", 0) == 3}
            if t_dims and max(t_dims) > self.conf.tbptt_fwd_length:
                return self._fit_tbptt(mds)
        if self._train_step is None:
            self._train_step = self._build_train_step()
        else:
            self._resolve_remat()  # warn if DL4J_TPU_REMAT changed since
        tracer = _get_tracer()
        with tracer.span("host_dispatch"):
            self._rng_key, rng = jax.random.split(self._rng_key)
            inputs, fmasks = self._prepare_inputs(mds.features, mds.features_masks)
            labels = [jnp.asarray(l) for l in mds.labels]
            lmasks = [None if m is None else jnp.asarray(m)
                      for m in mds.labels_masks]
            if all(m is None for m in lmasks):
                lmasks = None
            it = jnp.asarray(self.iteration, jnp.int32)
        with tracer.span("device_step"):
            self.params, self.state, self.opt_state, score = self._train_step(
                self.params, self.state, self.opt_state, it, inputs, labels,
                fmasks, lmasks, rng)
        self.iteration += 1
        self.score_value = score
        self.last_batch_examples = mds.num_examples
        _goodput.observe_steps(1)
        # post-dispatch: params hold fresh (undonated) outputs; inputs
        # and labels were not donated, so lowering for cost is safe
        self._maybe_derive_flops(inputs, labels, fmasks, lmasks)
        if self.listeners:
            t0 = time.perf_counter()
            for l in self.listeners:
                l.iteration_done(self, self.iteration, self.epoch)
            t1 = time.perf_counter()
            tracer.record("score_sync", t0, t1)
            _obs_metrics.observe_dispatch_lag(t1 - t0)
        return score

    def _maybe_derive_flops(self, inputs, labels, fmasks, lmasks):
        """Auto-derive per-step FLOPs from the XLA cost model on the
        *lowered* train step — tracing only, no backend compile — once
        per (train-step, batch-shapes) pair. See
        MultiLayerNetwork._maybe_derive_flops."""
        if not _goodput.auto_flops_enabled():
            return
        key = (id(self._train_step),
               tuple(sorted((n, tuple(v.shape)) for n, v in inputs.items())),
               tuple(tuple(l.shape) for l in labels),
               tuple(sorted((n, tuple(v.shape))
                            for n, v in (fmasks or {}).items())),
               None if lmasks is None else tuple(
                   None if m is None else tuple(m.shape) for m in lmasks))
        if getattr(self, "_flops_key", None) == key:
            return
        self._flops_key = key
        with _get_tracer().span("flops_derive"):
            try:
                if self._train_step is None:
                    self._train_step = self._build_train_step()
                from deeplearning4j_tpu.utils.perf import (
                    xla_step_cost_lowered,
                )
                it = jnp.asarray(self.iteration, jnp.int32)
                rng = jax.random.PRNGKey(0)
                cost = xla_step_cost_lowered(
                    self._train_step, self.params, self.state,
                    self.opt_state, it, inputs, labels, fmasks, lmasks, rng)
                self.flops_per_step = cost["flops"] or None
            except Exception:
                # meshed/wrapped steps have no .lower
                self.flops_per_step = None
        _goodput.observe_flops(self.flops_per_step)

    def fit(self, data, *, epochs: int = 1, async_prefetch: bool = True,
            device_prefetch="auto", multi_step="auto"):
        """Train on an iterator of DataSet/MultiDataSet, or a single one.
        Iterators are wrapped in a background prefetch thread
        (AsyncDataSetIterator auto-wrap parity, MultiLayerNetwork.java:951 /
        ComputationGraph.java:701).

        Async runtime (bit-identity-preserving, see
        MultiLayerNetwork.fit): ``device_prefetch`` overlaps the
        host→device copy of batch N+1 with step N ("auto" = accelerator
        backends only); ``multi_step`` drives chunks of k steps through
        one jitted scan when no attached listener needs per-iteration
        values ("auto" = 8 on accelerators)."""
        if isinstance(data, (DataSet, MultiDataSet)):
            _obs_metrics.install_runtime_metrics()
            from deeplearning4j_tpu.compilecache import ensure_configured
            ensure_configured()  # DL4J_TPU_COMPILE_CACHE env var, if set
            ledger = _goodput.start_run("fit", net=self)
            from deeplearning4j_tpu.observability import (
                distributed as _obs_dist)
            _obs_dist.stamp_run_marker("fit")
            status = "completed"
            try:
                items = [data]
                for _ in range(epochs):
                    for d in items:
                        self.fit_batch(d)
                    self.epoch += 1
            except BaseException:
                status = "failed"
                raise
            finally:
                self.last_run_report = _goodput.end_run(ledger, status=status)
            return self
        from deeplearning4j_tpu.datasets.iterator import (
            AsyncDataSetIterator, DevicePrefetchIterator)
        chunk = self._resolve_multi_step(multi_step)
        device_prefetch = self._resolve_device_prefetch(device_prefetch)
        _obs_metrics.install_runtime_metrics()
        from deeplearning4j_tpu.compilecache import ensure_configured
        ensure_configured()  # DL4J_TPU_COMPILE_CACHE env var, if set
        tracer = _get_tracer()
        ledger = _goodput.start_run("fit", net=self)
        from deeplearning4j_tpu.observability import distributed as _obs_dist
        _obs_dist.stamp_run_marker("fit")
        status = "completed"
        try:
            for _ in range(epochs):
                source = data
                if async_prefetch and hasattr(data, "reset"):
                    source = AsyncDataSetIterator(data)
                if device_prefetch:
                    source = DevicePrefetchIterator(
                        source, sharding=self._prefetch_sharding())
                it0, t0 = self.iteration, time.perf_counter()
                if chunk > 1:
                    self._fit_epoch_chunked(source, chunk)
                else:
                    stream = iter(source)
                    while True:
                        with tracer.span("data_wait"):
                            d = next(stream, None)
                        if d is None:
                            break
                        self.fit_batch(d)
                _obs_metrics.observe_rate(self.iteration - it0,
                                          time.perf_counter() - t0)
                if hasattr(data, "reset") and not getattr(data, "auto_epochs",
                                                          False):
                    # datapipe Pipelines advance their own epoch state
                    # (seed + epoch shuffle orders); reset() would rewind
                    # them to epoch 0 every pass
                    data.reset()
                for l in self.listeners:
                    l.on_epoch_end(self)
                self.epoch += 1
        except BaseException:
            status = "failed"
            raise
        finally:
            self.last_run_report = _goodput.end_run(ledger, status=status)
        return self

    _FIT_CHUNK_DEFAULT = 8

    def _resolve_multi_step(self, multi_step) -> int:
        """How many fit steps one jitted dispatch may cover. 1 = per-batch
        (mesh / tbptt / a listener that needs real per-step boundaries).
        "auto" also resolves to 1 on the CPU backend — see
        MultiLayerNetwork._resolve_multi_step; an explicit int is always
        honored."""
        if multi_step in (None, False, 0, 1):
            return 1
        if self._mesh is not None or self.conf.backprop_type == "tbptt":
            return 1
        for l in self.listeners:
            if getattr(l, "needs_per_iteration", True):
                return 1
        if multi_step == "auto":
            if jax.default_backend() == "cpu":
                return 1
            return self._FIT_CHUNK_DEFAULT
        return max(1, int(multi_step))

    @staticmethod
    def _resolve_device_prefetch(device_prefetch) -> bool:
        """"auto" = accelerator backends only — see
        MultiLayerNetwork._resolve_device_prefetch."""
        if device_prefetch == "auto":
            return jax.default_backend() != "cpu"
        return bool(device_prefetch)

    def _prefetch_sharding(self):
        """Target sharding for prefetched batches (None = default device);
        multi-process meshes keep host batches for shard_step_multi."""
        if self._mesh is None:
            return None
        if jax.process_count() > 1:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        mesh, axis = self._mesh
        return NamedSharding(mesh, PartitionSpec(axis))

    def _fit_epoch_chunked(self, source, chunk: int):
        """Group consecutive same-shape minibatches and dispatch each group
        as ONE jitted scan over distinct batches (bit-identical to the
        per-batch loop, including the rng chain — see multistep.py)."""
        self._require_init()

        def signature(m):
            return (tuple(tuple(f.shape) for f in m.features),
                    tuple(tuple(l.shape) for l in m.labels),
                    tuple(None if x is None else tuple(x.shape)
                          for x in m.features_masks),
                    tuple(None if x is None else tuple(x.shape)
                          for x in m.labels_masks))

        tracer = _get_tracer()
        buf, sig = [], None
        stream = iter(source)
        while True:
            with tracer.span("data_wait"):
                d = next(stream, None)
            if d is None:
                break
            m = self._coerce(d)
            s = signature(m)
            if buf and s != sig:
                self._dispatch_chunk(buf)
                buf = []
            sig = s
            buf.append(m)
            if len(buf) == chunk:
                self._dispatch_chunk(buf)
                buf = []
        if buf:
            self._dispatch_chunk(buf)

    def _dispatch_chunk(self, batches):
        """Run len(batches) steps in one XLA execution (lax.scan over the
        fused step), then replay listeners with per-iteration scores."""
        if len(batches) == 1:
            self.fit_batch(batches[0])
            return
        from deeplearning4j_tpu.nn.multistep import get_multi_batch_step
        tracer = _get_tracer()
        with tracer.span("host_dispatch", steps=len(batches)):
            jitted = get_multi_batch_step(self)
            prepared = [self._prepare_inputs(m.features, m.features_masks)
                        for m in batches]
            inputs = {n: jnp.stack([p[0][n] for p in prepared])
                      for n in prepared[0][0]}
            fmasks = {n: jnp.stack([p[1][n] for p in prepared])
                      for n in prepared[0][1]}
            labels = [jnp.stack([jnp.asarray(m.labels[i]) for m in batches])
                      for i in range(len(batches[0].labels))]
            lmasks = [None if batches[0].labels_masks[i] is None else
                      jnp.stack([jnp.asarray(m.labels_masks[i])
                                 for m in batches])
                      for i in range(len(batches[0].labels_masks))]
            if all(m is None for m in lmasks):
                lmasks = None
            it0 = jnp.asarray(self.iteration, jnp.int32)
            steps = jnp.arange(len(batches), dtype=jnp.int32)
        with tracer.span("device_step", steps=len(batches)):
            (self.params, self.state, self.opt_state, self._rng_key,
             scores) = jitted(self.params, self.state, self.opt_state, it0,
                              self._rng_key, steps,
                              (inputs, labels, fmasks, lmasks))
        start = self.iteration
        self.iteration += len(batches)
        self.score_value = scores[-1]
        self.last_batch_examples = batches[-1].num_examples
        _goodput.observe_steps(len(batches))  # one dispatch, k real steps
        # pre-stack arrays already have the per-step shape; slicing the
        # stacked device arrays here would dispatch (and first-call
        # compile) an XLA gather outside the flops_derive span
        self._maybe_derive_flops(
            prepared[0][0], list(batches[0].labels), prepared[0][1],
            None if lmasks is None else list(batches[0].labels_masks))
        with tracer.span("score_sync", steps=len(batches)):
            self._replay_listeners(start, scores,
                                   [m.num_examples for m in batches])

    def _replay_listeners(self, start: int, scores, examples):
        """Post-chunk iteration_done replay with per-iteration lazy score
        slices (every listener here declared needs_per_iteration=False)."""
        if not self.listeners:
            return
        for j in range(len(examples)):
            self.score_value = scores[j]
            self.last_batch_examples = examples[j]
            for l in self.listeners:
                l.iteration_done(self, start + j + 1, self.epoch)
        self.score_value = scores[-1]
        self.last_batch_examples = examples[-1]

    # ------------------------------------------------------------- pretrain
    def pretrain(self, data, *, epochs: int = 1):
        """Layer-wise unsupervised pretraining over the DAG
        (ComputationGraph.pretrain parity): each pretrainable layer vertex
        (VAE/AutoEncoder/RBM) trains on the activations its input vertices
        produce under the current parameters, in topological order."""
        self._require_init()
        for name in self.topo:
            layer = self._layer_by_name.get(name) if self.vertex_kind[
                name] == "layer" else None
            if layer is not None and getattr(layer, "is_pretrainable", False):
                self.pretrain_layer(name, data, epochs=epochs)
        return self

    def pretrain_layer(self, name: str, data, *, epochs: int = 1):
        """Pretrain one layer vertex on its featurized input (the
        pretrainLayer(String, DataSetIterator) overload)."""
        self._require_init()
        layer = self._layer_by_name.get(name)
        if layer is None or not getattr(layer, "is_pretrainable", False):
            raise ValueError(f"Vertex '{name}' is not a pretrainable layer")
        gc = self.conf.global_conf

        def step(params, opt_state, itc, x, rng):
            def loss_fn(p):
                return layer.pretrain_loss(p[name], x, rng)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_opt = apply_layer_updates(
                [layer], gc, params, grads, opt_state, itc)
            return new_params, new_opt, loss

        jitted = jax.jit(step, donate_argnums=(0, 1))

        def featurize(params, state, inputs, fmasks):
            _, saved, _, _ = self._walk(params, state, inputs, train=False,
                                        rng=None, fmasks=fmasks,
                                        need_inputs_of=(name,))
            return saved[name][0][0]

        feat_fn = jax.jit(featurize)
        # material copies: the jitted step donates these buffers, and the
        # net's own trees must never alias donated (deleted) arrays — an
        # exception mid-loop would otherwise corrupt the whole net
        params_sub = {name: jax.tree_util.tree_map(jnp.copy,
                                                   self.params[name])}
        opt_sub = {name: jax.tree_util.tree_map(jnp.copy,
                                                self.opt_state[name])}
        last = None
        iteration = self.iteration
        items = ([data] if isinstance(data, (DataSet, MultiDataSet))
                 else data)
        for _ in range(epochs):
            for d in items:
                mds = self._coerce(d)
                inputs, fmasks = self._prepare_inputs(mds.features,
                                                      mds.features_masks)
                x = feat_fn(self.params, self.state, inputs, fmasks)
                self._rng_key, rng = jax.random.split(self._rng_key)
                itc = jnp.asarray(iteration, jnp.int32)
                params_sub, opt_sub, last = jitted(params_sub, opt_sub, itc,
                                                   x, rng)
                iteration += 1
            if hasattr(items, "reset"):
                items.reset()
        self.iteration = iteration
        self.params = {**self.params, name: params_sub[name]}
        self.opt_state = {**self.opt_state, name: opt_sub[name]}
        self.score_value = last
        return self

    # ------------------------------------------------------------ inference
    def output(self, *features, masks=None, train: bool = False):
        """Forward pass -> tuple of network-output activations (single array
        if the graph has one output)."""
        self._require_init()
        feats = [jnp.asarray(f) for f in features]
        key = ("out", train, masks is not None)
        if key not in self._apply_fns:
            def fn(params, state, inputs, fmasks):
                acts, _, _, _ = self._walk(params, state, inputs, train=train,
                                           rng=None, fmasks=fmasks)
                return tuple(acts[o] for o in self.conf.network_outputs)
            self._apply_fns[key] = jax.jit(fn)
        inputs, fmasks = self._prepare_inputs(
            feats, masks if masks is not None else None)
        outs = self._apply_fns[key](self.params, self.state, inputs, fmasks)
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, *features, masks=None, train: bool = False):
        """All vertex activations as a dict (feedForward :1089 parity)."""
        self._require_init()
        feats = [jnp.asarray(f) for f in features]
        inputs, fmasks = self._prepare_inputs(feats, masks)
        acts, _, _, _ = self._walk(self.params, self.state, inputs,
                                   train=train, rng=None, fmasks=fmasks)
        return acts

    def score(self, mds, train: bool = False):
        self._require_init()
        mds = self._coerce(mds)
        inputs, fmasks = self._prepare_inputs(mds.features, mds.features_masks)
        labels = [jnp.asarray(l) for l in mds.labels]
        lmasks = [None if m is None else jnp.asarray(m)
                  for m in mds.labels_masks]
        if all(m is None for m in lmasks):
            lmasks = None
        loss, _ = self._loss(self.params, self.state, inputs, labels, fmasks,
                             lmasks, rng=None, train=train)
        return float(loss)

    def _evaluate_with(self, ev, iterator, what: str):
        """Shared single-output eval loop for evaluate/evaluate_regression."""
        if len(self.conf.network_outputs) != 1:
            raise ValueError(f"{what}() requires a single-output graph")
        if isinstance(iterator, (DataSet, MultiDataSet)):
            iterator = [iterator]
        for d in iterator:
            mds = self._coerce(d)
            out = self.output(*mds.features, masks=(
                mds.features_masks
                if any(m is not None for m in mds.features_masks) else None))
            ev.eval(mds.labels[0], np.asarray(out), mask=mds.labels_masks[0])
        return ev

    def evaluate(self, iterator):
        """Classification eval for single-output graphs (evaluate parity)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        return self._evaluate_with(Evaluation(), iterator, "evaluate")

    def evaluate_regression(self, iterator):
        """Regression eval for single-output graphs (evaluateRegression
        parity)."""
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        return self._evaluate_with(RegressionEvaluation(), iterator,
                                   "evaluate_regression")

    # ---------------------------------------------------------------- misc
    def num_params(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    def summary(self) -> str:
        lines = ["=" * 78]
        lines.append(f"{'name':<20}{'kind':<16}{'inputs':<28}{'params':>10}")
        lines.append("-" * 78)
        for name in self.topo:
            kind = self.vertex_kind[name]
            t = (self._resolved_confs[name].layer_type if kind == "layer"
                 else self._resolved_confs[name].vertex_type)
            p = self.params.get(name, {})
            n = sum(int(np.prod(a.shape)) for a in jax.tree_util.tree_leaves(p))
            ins = ",".join(self.conf.vertex_inputs[name])
            lines.append(f"{name:<20}{t:<16}{ins:<28}{n:>10}")
        lines.append("-" * 78)
        lines.append(f"total params: {self.num_params()}")
        lines.append("=" * 78)
        return "\n".join(lines)

    def clone(self):
        net = ComputationGraph(self.conf)
        net.init(structure_only=True)
        net.params = jax.tree_util.tree_map(jnp.copy, self.params)
        net.state = jax.tree_util.tree_map(jnp.copy, self.state)
        net.opt_state = jax.tree_util.tree_map(jnp.copy, self.opt_state)
        net.iteration = self.iteration
        net.epoch = self.epoch
        return net
