"""Base runtime layer.

Reference seam: nn/api/Layer.java (activate :165-202, backpropGradient :119)
and nn/layers/BaseLayer.java. Backprop is derived by JAX autodiff of
``apply``, so only the forward pass is written by hand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import activations as activations_mod


class Layer:
    """Functional runtime layer.

    Lifecycle: constructed from (config, input_type, global_conf, policy);
    ``init_params(key)`` returns this layer's param subtree; ``apply(params,
    state, x, train=..., rng=...)`` returns ``(output, new_state)``.
    """

    def __init__(self, conf, input_type, global_conf, policy):
        self.conf = conf
        self.input_type = input_type
        self.global_conf = global_conf
        self.policy = policy
        self.output_type = conf.get_output_type(input_type)

    # ---- config resolution (layer overrides global) -----------------------
    def resolve(self, name, default=None):
        v = getattr(self.conf, name, None)
        if v is None:
            v = getattr(self.global_conf, name, None)
        return default if v is None else v

    @property
    def param_dtype(self):
        return jnp.dtype(self.policy.param_dtype)

    @property
    def compute_dtype(self):
        # Resolved per layer path so policy overrides like
        # (("batchnorm", "float32"),) pin named layers (PRECISION.md).
        return jnp.dtype(self.policy.compute_dtype_for(self.name))

    @property
    def activation_fn(self):
        return activations_mod.get(self.resolve("activation", "identity"))

    @property
    def name(self):
        return self.conf.name

    # ---- params/state -----------------------------------------------------
    def init_params(self, key) -> dict:
        return {}

    def init_state(self) -> dict:
        return {}

    def has_params(self) -> bool:
        return self.conf.has_params()

    # ---- forward ----------------------------------------------------------
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        raise NotImplementedError

    def feed_forward_mask(self, mask):
        """Transform the per-timestep feature mask for downstream layers
        (Layer.feedForwardMaskArray parity). Time-shrinking layers override;
        layers that collapse the time axis return None."""
        return mask

    def _input_dropout(self, x, train, rng):
        """Per-layer input dropout (reference: conf.dropOut applied to layer
        input). ``dropout`` here is the DROP probability; inverted-dropout
        scaling keeps expectations unchanged at inference."""
        p = float(self.resolve("dropout", 0.0) or 0.0)
        if not train or p <= 0.0:
            return x
        if rng is None:
            raise ValueError(
                f"Layer {self.name}: dropout requires an rng during training")
        keep = 1.0 - p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    # ---- regularization ---------------------------------------------------
    def regularization(self, params) -> jnp.ndarray:
        """L1/L2 penalty for this layer's params, matching the reference's
        score contribution (BaseLayer.calcL2 = 0.5*l2*||W||^2, calcL1 =
        l1*sum|W|; biases use l1_bias/l2_bias). Included in the loss so
        autodiff reproduces LayerUpdater.postApply's gradient terms."""
        if not params:
            return jnp.zeros((), self.param_dtype)
        l1 = float(self.resolve("l1", 0.0) or 0.0)
        l2 = float(self.resolve("l2", 0.0) or 0.0)
        l1b = float(self.resolve("l1_bias", 0.0) or 0.0)
        l2b = float(self.resolve("l2_bias", 0.0) or 0.0)
        total = jnp.zeros((), self.param_dtype)
        for pname, w in params.items():
            is_bias = pname in ("b", "bias", "beta")
            a1, a2 = (l1b, l2b) if is_bias else (l1, l2)
            if a1:
                total = total + a1 * jnp.sum(jnp.abs(w))
            if a2:
                total = total + 0.5 * a2 * jnp.sum(w * w)
        return total
