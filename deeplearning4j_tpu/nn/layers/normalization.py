"""Normalization runtime layers: batch norm + LRN.

Parity: nn/layers/normalization/BatchNormalization.java (batch statistics
during training, global moving mean/var for inference, helper seam at
:53-60) and LocalResponseNormalization.java. The cuDNN helper path maps to
the op registry; moving statistics live in the layer *state* pytree (updated
functionally inside the jitted train step, not mutated in place).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import normalization as _bn  # registers the op
from deeplearning4j_tpu.ops import registry as ops

del _bn


class BatchNormLayer(Layer):
    def _num_features(self):
        it = self.input_type
        if it is None:
            raise ValueError("BatchNorm requires an input_type for init")
        if it.kind == "convolutional":
            return it.channels
        return it.flat_size()

    def init_params(self, key):
        if self.conf.lock_gamma_beta:
            return {}
        f = self._num_features()
        return {
            "gamma": jnp.full((f,), float(self.conf.gamma), self.param_dtype),
            "beta": jnp.full((f,), float(self.conf.beta), self.param_dtype),
        }

    def init_state(self):
        f = self._num_features()
        return {
            "mean": jnp.zeros((f,), self.param_dtype),
            "var": jnp.ones((f,), self.param_dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        f = x.shape[-1]
        if params:
            gamma, beta = params["gamma"], params["beta"]
        else:
            gamma = jnp.full((f,), float(c.gamma), self.param_dtype)
            beta = jnp.full((f,), float(c.beta), self.param_dtype)
        if train:
            # custom-VJP op: single-pass f32 statistics, bf16-clean backward
            # (see ops/normalization.py; CudnnBatchNormalizationHelper.java
            # is the reference's fused-kernel analogue). The RUNNING mean
            # is the variance-stabilization shift: data-independent (keeps
            # the stats fused into the producing conv) and tracking the
            # batch mean after warm-up
            xhat, mean, var = ops.get("batch_norm_train")(
                x, gamma, beta, shift=state["mean"], eps=c.eps)
            d = c.decay
            sd = self.param_dtype
            new_state = {
                "mean": d * state["mean"] + (1 - d) * mean.astype(sd),
                "var": d * state["var"] + (1 - d) * var.astype(sd),
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = {}
            inv = jax.lax.rsqrt(var + c.eps)
            scale, shift = gamma * inv, beta - mean * gamma * inv
            xhat = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return self.activation_fn(xhat), new_state


class LRNLayer(Layer):
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        return ops.get("lrn")(x, k=c.k, n=c.n, alpha=c.alpha, beta=c.beta), state
