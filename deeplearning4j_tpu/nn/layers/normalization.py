"""Normalization runtime layers: batch norm + LRN.

Parity: nn/layers/normalization/BatchNormalization.java (batch statistics
during training, global moving mean/var for inference, helper seam at
:53-60) and LocalResponseNormalization.java. The cuDNN helper path maps to
the op registry; moving statistics live in the layer *state* pytree (updated
functionally inside the jitted train step, not mutated in place).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import registry as ops


class BatchNormLayer(Layer):
    def _num_features(self):
        it = self.input_type
        if it is None:
            raise ValueError("BatchNorm requires an input_type for init")
        if it.kind == "convolutional":
            return it.channels
        return it.flat_size()

    def init_params(self, key):
        if self.conf.lock_gamma_beta:
            return {}
        f = self._num_features()
        return {
            "gamma": jnp.full((f,), float(self.conf.gamma), self.param_dtype),
            "beta": jnp.full((f,), float(self.conf.beta), self.param_dtype),
        }

    def init_state(self):
        f = self._num_features()
        return {
            "mean": jnp.zeros((f,), self.param_dtype),
            "var": jnp.ones((f,), self.param_dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        axes = tuple(range(x.ndim - 1))  # all but the feature/channel axis
        sd = self.param_dtype  # statistics accumulate at full precision
        if train:
            mean = jnp.mean(x.astype(sd), axis=axes)
            var = jnp.var(x.astype(sd), axis=axes)
            d = c.decay
            new_state = {
                "mean": d * state["mean"] + (1 - d) * mean,
                "var": d * state["var"] + (1 - d) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = {}
        # normalize in the activation dtype (bf16 under the mixed policy) —
        # the per-channel scale/shift fuse into neighbouring ops
        inv = jax.lax.rsqrt(var + c.eps)
        if params:
            scale, shift = params["gamma"] * inv, params["beta"] - mean * params["gamma"] * inv
        else:
            scale, shift = c.gamma * inv, c.beta - mean * c.gamma * inv
        xhat = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return self.activation_fn(xhat), new_state


class LRNLayer(Layer):
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        return ops.get("lrn")(x, k=c.k, n=c.n, alpha=c.alpha, beta=c.beta), state
