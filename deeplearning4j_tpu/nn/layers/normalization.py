"""Normalization runtime layers: batch norm + LRN.

Parity: nn/layers/normalization/BatchNormalization.java (batch statistics
during training, global moving mean/var for inference, helper seam at
:53-60) and LocalResponseNormalization.java. The cuDNN helper path maps to
the op registry; moving statistics live in the layer *state* pytree (updated
functionally inside the jitted train step, not mutated in place).
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import registry as ops


class BatchNormLayer(Layer):
    def _num_features(self):
        it = self.input_type
        if it is None:
            raise ValueError("BatchNorm requires an input_type for init")
        if it.kind == "convolutional":
            return it.channels
        return it.flat_size()

    def init_params(self, key):
        if self.conf.lock_gamma_beta:
            return {}
        f = self._num_features()
        return {
            "gamma": jnp.full((f,), float(self.conf.gamma), self.param_dtype),
            "beta": jnp.full((f,), float(self.conf.beta), self.param_dtype),
        }

    def init_state(self):
        f = self._num_features()
        return {
            "mean": jnp.zeros((f,), self.param_dtype),
            "var": jnp.ones((f,), self.param_dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        axes = tuple(range(x.ndim - 1))  # all but the feature/channel axis
        if train:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            d = c.decay
            new_state = {
                "mean": d * state["mean"] + (1 - d) * mean,
                "var": d * state["var"] + (1 - d) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = {}
        xhat = (x - mean) / jnp.sqrt(var + c.eps)
        if params:
            xhat = xhat * params["gamma"] + params["beta"]
        else:
            xhat = xhat * c.gamma + c.beta
        return self.activation_fn(xhat), new_state


class LRNLayer(Layer):
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        return ops.get("lrn")(x, k=c.k, n=c.n, alpha=c.alpha, beta=c.beta), state
