"""Dense / output / activation / dropout / embedding runtime layers.

Reference parity: nn/layers/feedforward/dense/DenseLayer.java (preOutput =
input.mmul(W).addiRowVector(b)), nn/layers/OutputLayer.java (dense + loss;
loss grad here comes from autodiff, not ILossFunction.computeGradient),
nn/layers/feedforward/embedding/EmbeddingLayer.java (index lookup).

TPU notes: the matmul is the MXU op; compute dtype may be bf16 while params
stay f32 (DtypePolicy). Activations fuse into the matmul under XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import initializers as init_mod
from deeplearning4j_tpu.ops import losses as losses_mod


class DenseLayer(Layer):
    def _fans(self):
        return self.conf.n_in, self.conf.n_out

    def init_params(self, key):
        fan_in, fan_out = self._fans()
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        k_w, _ = jax.random.split(key)
        W = w_fn(k_w, (fan_in, fan_out), fan_in, fan_out, self.param_dtype)
        params = {"W": W}
        if getattr(self.conf, "has_bias", True):
            params["b"] = jnp.full(
                (fan_out,), float(self.resolve("bias_init", 0.0)),
                self.param_dtype)
        return params

    def preout(self, params, x):
        # Activations stay in compute dtype between layers (bf16 under the
        # mixed policy) — HBM traffic and residuals are half-width; loss
        # heads cast back up to param dtype (see OutputLayer.loss).
        cd = self.compute_dtype
        z = jnp.matmul(x.astype(cd), params["W"].astype(cd))
        if "b" in params:
            z = z + params["b"].astype(cd)
        return z

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # 2d [batch, n_in]; time series are flattened by an rnn_to_ff
        # preprocessor before dense layers (reference layout semantics).
        x = self._input_dropout(x, train, rng)
        z = self.preout(params, x)
        return self.activation_fn(z), state


class OutputLayer(DenseLayer):
    """Dense layer + loss head (OutputLayer.java parity)."""

    @property
    def loss_fn(self) -> losses_mod.Loss:
        return losses_mod.get(self.conf.loss)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Inference mirrors the loss path's precision: the head's
        # activation (softmax et al.) runs in param dtype even when the
        # matmul ran in bf16, so serving outputs are full-precision
        # probabilities under any policy.
        x = self._input_dropout(x, train, rng)
        z = self.preout(params, x).astype(self.param_dtype)
        return self.activation_fn(z), state

    def loss(self, params, x, labels, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        # loss math (softmax/log) in param dtype (f32) for stability
        z = self.preout(params, x).astype(self.param_dtype)
        return self.loss_fn.score(labels, z, self.activation_fn, mask)


class LossOnlyLayer(Layer):
    """Parameter-free loss head (LossLayer.java parity)."""

    @property
    def loss_fn(self) -> losses_mod.Loss:
        return losses_mod.get(self.conf.loss)

    def preout(self, params, x):
        return x

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn(x), state

    def loss(self, params, x, labels, *, train=False, rng=None, mask=None):
        return self.loss_fn.score(labels, x.astype(self.param_dtype),
                                  self.activation_fn, mask)


class ActivationOnlyLayer(Layer):
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn(x), state


class DropoutOnlyLayer(Layer):
    """Standalone dropout (DropoutLayer.java parity). Uses the layer's
    ``dropout`` field (or the global default) as the drop probability."""

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._input_dropout(x, train, rng), state


class EmbeddingLayerImpl(Layer):
    """Integer-index embedding (EmbeddingLayer.java parity). The reference
    computes a one-hot mmul; on TPU a gather (jnp.take) is the idiomatic
    lowering and XLA emits a fused dynamic-gather."""

    def init_params(self, key):
        n_in, n_out = self.conf.n_in, self.conf.n_out
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        W = w_fn(key, (n_in, n_out), n_in, n_out, self.param_dtype)
        params = {"W": W}
        if getattr(self.conf, "has_bias", True):
            params["b"] = jnp.full(
                (n_out,), float(self.resolve("bias_init", 0.0)), self.param_dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # x: integer indices [batch] or [batch, 1] (reference accepts a
        # column of indices) or one-hot [batch, n_in].
        if x.ndim == 2 and x.shape[-1] == self.conf.n_in and not jnp.issubdtype(
                x.dtype, jnp.integer):
            idx = jnp.argmax(x, axis=-1)
        else:
            idx = x.reshape(x.shape[0]).astype(jnp.int32)
        emb = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            emb = emb + params["b"]
        return self.activation_fn(emb), state
