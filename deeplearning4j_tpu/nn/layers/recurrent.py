"""Recurrent runtime layers: Graves LSTM (+bidirectional), RNN output head,
last-time-step extraction.

Parity: nn/layers/recurrent/{GravesLSTM, GravesBidirectionalLSTM,
LSTMHelpers, RnnOutputLayer, BaseRecurrentLayer}.java. The reference's
hand-written per-timestep Java loop (LSTMHelpers.activateHelper :57 looping
:76; backprop :271) becomes a ``lax.scan`` whose backward pass is derived by
autodiff; the whole sequence compiles into the train step.

Gate math (LSTMHelpers parity, Graves formulation with peepholes):
    i = gate_act(x Wx_i + h Wh_i + p_i * c_prev + b_i)
    f = gate_act(x Wx_f + h Wh_f + p_f * c_prev + b_f)
    g = act(x Wx_g + h Wh_g + b_g)
    c = f * c_prev + i * g
    o = gate_act(x Wx_o + h Wh_o + p_o * c + b_o)
    h = o * act(c)

Masking: masked timesteps carry (h, c) through unchanged and emit zero
output (per-timestep masking semantics, GradientCheckTestsMasking parity).

Streaming (`rnnTimeStep` :2234 / BaseRecurrentLayer stateMap parity): when
``layer.streaming`` is set by the network, the final (h, c) carry is read
from / written to the layer's state subtree under "h"/"c" — used by
``MultiLayerNetwork.rnn_time_step`` and truncated BPTT.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import initializers as init_mod
from deeplearning4j_tpu.ops import losses as losses_mod
from deeplearning4j_tpu.ops import lstm as _lstm  # registers lstm_sequence
from deeplearning4j_tpu.ops import registry as ops

del _lstm

# recurrent (h, c) carries plus the attention tier's KV-cache carries
# (k/v caches + per-row absolute position — nn/layers/attention.py)
CARRY_KEYS = ("h", "c", "h_bwd", "c_bwd", "k", "v", "pos")


def _lstm_scan(params, x, h0, c0, mask, gate_act, cell_act):
    """Run an LSTM over [b, t, f]; returns (y [b,t,n], hT, cT).

    Runs entirely in x.dtype (the compute dtype — bf16 under the mixed
    policy, so the recurrent matmul hits the MXU at full rate). The input
    projection for the whole sequence is one MXU matmul; the time loop is
    the ``lstm_sequence`` registry op (Pallas fused kernel on TPU, lax.scan
    under autodiff elsewhere — the LSTMHelpers.java:57,271 seam)."""
    cd = x.dtype
    params = {k: v.astype(cd) for k, v in params.items()}
    xz = jnp.einsum("btf,fg->btg", x, params["Wx"]) + params["b"]
    xz_t = jnp.moveaxis(xz, 1, 0)  # [t, b, 4n]
    mask_t = None if mask is None else jnp.moveaxis(mask, 1, 0)  # [t, b]
    ys, hT, cT = ops.get("lstm_sequence")(
        xz_t, h0, c0, params["Wh"], params["p"], mask_t,
        gate_act=gate_act, cell_act=cell_act)
    return jnp.moveaxis(ys, 0, 1), hT, cT


class GravesLSTMLayer(Layer):
    is_recurrent_stateful = True
    streaming = False

    def _init_direction(self, key):
        n_in, n = self.conf.n_in, self.conf.n_out
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        k1, k2 = jax.random.split(key)
        Wx = w_fn(k1, (n_in, 4 * n), n_in, n, self.param_dtype)
        Wh = w_fn(k2, (n, 4 * n), n, n, self.param_dtype)
        b = jnp.zeros((4 * n,), self.param_dtype)
        # forget-gate bias init (gate order i, f, o, g)
        b = b.at[n:2 * n].set(float(self.conf.forget_gate_bias_init))
        p = jnp.zeros((3, n), self.param_dtype)
        return {"Wx": Wx, "Wh": Wh, "b": b, "p": p}

    def init_params(self, key):
        return self._init_direction(key)

    def _run(self, params, x, mask, carry, reverse=False):
        n = self.conf.n_out
        b = x.shape[0]
        if carry is None:
            h0 = jnp.zeros((b, n), x.dtype)
            c0 = jnp.zeros((b, n), x.dtype)
        else:
            h0, c0 = (carry[0].astype(x.dtype), carry[1].astype(x.dtype))
        if reverse:
            x = jnp.flip(x, axis=1)
            mask = None if mask is None else jnp.flip(mask, axis=1)
        y, hT, cT = _lstm_scan(params, x, h0, c0, mask,
                               self.conf.gate_activation,
                               self.resolve("activation", "tanh"))
        if reverse:
            y = jnp.flip(y, axis=1)
        return y, hT, cT

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng).astype(self.compute_dtype)
        m = None
        if mask is not None:
            m = mask.reshape(mask.shape[0], -1).astype(x.dtype)
        carry = None
        if self.streaming and "h" in state:
            carry = (state["h"], state["c"])
        y, hT, cT = self._run(params, x, m, carry)
        new_state = dict(state)
        if self.streaming:
            new_state["h"] = hT
            new_state["c"] = cT
        return y, new_state


class GravesBidirectionalLSTMLayer(GravesLSTMLayer):
    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"fwd": self._init_direction(k1),
                "bwd": self._init_direction(k2)}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if self.streaming:
            raise ValueError(
                "rnnTimeStep/tBPTT streaming is undefined for bidirectional "
                "LSTM (the backward pass needs the full sequence) — matching "
                "the reference's restriction")
        x = self._input_dropout(x, train, rng).astype(self.compute_dtype)
        m = None
        if mask is not None:
            m = mask.reshape(mask.shape[0], -1).astype(x.dtype)
        y_f, _, _ = self._run(params["fwd"], x, m, None)
        y_b, _, _ = self._run(params["bwd"], x, m, None, reverse=True)
        # reference sums directions (GravesBidirectionalLSTM.java:206)
        return y_f + y_b, state


class RnnOutputLayerImpl(Layer):
    """Per-timestep dense + loss (RnnOutputLayer.java parity)."""

    def init_params(self, key):
        n_in, n_out = self.conf.n_in, self.conf.n_out
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        params = {"W": w_fn(key, (n_in, n_out), n_in, n_out, self.param_dtype)}
        if self.conf.has_bias:
            params["b"] = jnp.full(
                (n_out,), float(self.resolve("bias_init", 0.0)),
                self.param_dtype)
        return params

    @property
    def loss_fn(self) -> losses_mod.Loss:
        return losses_mod.get(self.conf.loss)

    def preout(self, params, x):
        cd = self.compute_dtype
        z = jnp.einsum("btf,fg->btg", x.astype(cd), params["W"].astype(cd))
        if "b" in params:
            z = z + params["b"].astype(cd)
        return z

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Head activation in param dtype, mirroring the loss path, so
        # per-timestep serving outputs are full precision under any
        # policy (see OutputLayer.apply).
        x = self._input_dropout(x, train, rng)
        z = self.preout(params, x).astype(self.param_dtype)
        return self.activation_fn(z), state

    def loss(self, params, x, labels, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        # loss math in param dtype (f32) for stability
        z = self.preout(params, x).astype(self.param_dtype)
        n_out = z.shape[-1]
        z2 = z.reshape(-1, n_out)
        labels2 = labels.reshape(-1, n_out)
        m2 = None if mask is None else mask.reshape(-1)
        return self.loss_fn.score(labels2, z2, self.activation_fn, m2)


class TimeDistributedDenseLayer(RnnOutputLayerImpl):
    """Per-timestep dense, no loss head (Keras TimeDistributed(Dense) /
    the reference's KerasLayer.java:206-212 mapping)."""

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Mid-network layer: unlike the RnnOutput head, its activation
        # stays in compute dtype between layers.
        x = self._input_dropout(x, train, rng)
        return self.activation_fn(self.preout(params, x)), state

    def loss(self, *args, **kwargs):
        raise ValueError(
            "TimeDistributedDense has no loss head — use RnnOutput as the "
            "terminal layer")


class LastTimeStepLayer(Layer):
    """[b, t, f] -> [b, f]: last step, or last *unmasked* step per example
    (LastTimeStepVertex.java parity)."""

    def feed_forward_mask(self, mask):
        return None

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.ops.sequence import last_unmasked_step
        return last_unmasked_step(x, mask), state


def set_streaming(layers, flag: bool):
    """Toggle stateful (h, c) carry on every recurrent layer — shared by
    MultiLayerNetwork and ComputationGraph streaming/tBPTT paths."""
    for layer in layers:
        if getattr(layer, "is_recurrent_stateful", False):
            layer.streaming = flag


def strip_carries(state):
    """Drop recurrent (h, c) carries from a state pytree (batch-boundary
    reset after tBPTT / streaming)."""
    out = {}
    for name, sub in state.items():
        kept = {k: v for k, v in sub.items() if k not in CARRY_KEYS}
        if kept:
            out[name] = kept
    return out
