"""Convolution / pooling / padding runtime layers.

Parity: nn/layers/convolution/ConvolutionLayer.java (the reference's forward
is im2col+GEMM at :281-300 or cuDNN via the helper seam at :69-76; here the
op registry resolves to lax.conv_general_dilated, which XLA lowers directly
onto the MXU — no im2col materialization), SubsamplingLayer.java,
ZeroPaddingLayer.java. Backprop is JAX autodiff.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import initializers as init_mod
from deeplearning4j_tpu.ops import registry as ops
from deeplearning4j_tpu.ops.convolution import (conv2d_space_to_depth,
                                                conv2d_strided_1x1_as_slice)
from deeplearning4j_tpu.ops.convolution import pair as _pair
from deeplearning4j_tpu.ops.convolution import spatial_padding


def _s2d_stem_enabled() -> bool:
    """Space-to-depth lowering for few-channel odd-kernel s2 convs (the
    ResNet stem). Exact rewrite; MEASURED NEUTRAL end-to-end on ResNet-50
    (min-of-runs 99.5 vs 99.8 ms — inside the chip's ~3.5% run-to-run
    weather; PERF.md round 5), so default off: no graph change without a
    measured win. The standard TPU transform is kept as tested machinery
    for stem-dominated models (the win MLPerf sees on the 7x7 stem is
    already captured by XLA's own lane packing on this stack)."""
    return os.environ.get("DL4J_TPU_S2D_STEM", "0") == "1"


def _slice_1x1_enabled() -> bool:
    """Strided-1x1-as-slice lowering for unpadded projection convs.
    Exact rewrite; MEASURED NEGATIVE end-to-end on ResNet-50 (+4-7
    ms/step, PERF.md round 5 — the materialized quarter tensor loses to
    XLA's strided window walk), so default off; kept as tested machinery
    for architectures where the projection share is larger."""
    return os.environ.get("DL4J_TPU_SLICE_1X1", "0") == "1"


class ConvolutionLayer(Layer):
    def init_params(self, key):
        kh, kw = _pair(self.conf.kernel)
        c_in, c_out = self.conf.n_in, self.conf.n_out
        fan_in = c_in * kh * kw
        fan_out = c_out * kh * kw
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        W = w_fn(key, (kh, kw, c_in, c_out), fan_in, fan_out, self.param_dtype)
        params = {"W": W}
        if self.conf.has_bias:
            params["b"] = jnp.full(
                (c_out,), float(self.resolve("bias_init", 0.0)), self.param_dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        kh, kw = _pair(self.conf.kernel)
        sh, sw = _pair(self.conf.stride)
        dh, dw = _pair(self.conf.dilation)
        pads = spatial_padding(
            (x.shape[1], x.shape[2]), (kh, kw), (sh, sw),
            _pair(self.conf.padding), self.conf.mode, (dh, dw))
        cd = self.compute_dtype
        xc, wc = x.astype(cd), params["W"].astype(cd)
        if (kh == kw == 1 and (sh > 1 or sw > 1) and (dh, dw) == (1, 1)
                and all(p == (0, 0) for p in pads) and _slice_1x1_enabled()):
            z = conv2d_strided_1x1_as_slice(xc, wc, strides=(sh, sw))
        elif ((sh, sw) == (2, 2) and (dh, dw) == (1, 1) and kh % 2 == 1
                and kw % 2 == 1 and kh >= 5 and x.shape[-1] <= 8
                and _s2d_stem_enabled()):
            z = conv2d_space_to_depth(xc, wc, padding=pads)
        else:
            z = ops.get("conv2d")(
                xc, wc, strides=(sh, sw), padding=pads, dilation=(dh, dw))
        if "b" in params:
            z = z + params["b"].astype(cd)
        # stay in compute dtype (bf16 activations end-to-end under the
        # mixed policy — halves HBM traffic and residual memory)
        return self.activation_fn(z), state


class Convolution1DLayerImpl(Layer):
    def feed_forward_mask(self, mask):
        c = self.conf
        eff_k = (c.kernel - 1) * c.dilation + 1
        return _downsample_time_mask(mask, eff_k, c.stride, c.padding, c.mode)

    def init_params(self, key):
        k = int(self.conf.kernel)
        c_in, c_out = self.conf.n_in, self.conf.n_out
        fan_in, fan_out = c_in * k, c_out * k
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        W = w_fn(key, (k, c_in, c_out), fan_in, fan_out, self.param_dtype)
        params = {"W": W}
        if self.conf.has_bias:
            params["b"] = jnp.full(
                (c_out,), float(self.resolve("bias_init", 0.0)), self.param_dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        c = self.conf
        pads = spatial_padding(
            (x.shape[1],), (c.kernel,), (c.stride,), (c.padding,), c.mode,
            (c.dilation,))
        cd = self.compute_dtype
        z = ops.get("conv1d")(
            x.astype(cd), params["W"].astype(cd),
            stride=c.stride, padding=pads, dilation=c.dilation)
        if "b" in params:
            z = z + params["b"].astype(cd)
        return self.activation_fn(z), state


def _pool2d(x, *, kernel, strides, padding, pooling, pnorm):
    """Dispatch to the registered pooling op (shared by the 2D and 1D
    subsampling layers)."""
    if pooling == "max":
        return ops.get("max_pool2d")(x, kernel=kernel, strides=strides,
                                     padding=padding)
    if pooling == "avg":
        return ops.get("avg_pool2d")(x, kernel=kernel, strides=strides,
                                     padding=padding)
    if pooling == "pnorm":
        return ops.get("pnorm_pool2d")(x, kernel=kernel, strides=strides,
                                       padding=padding, p=pnorm)
    raise ValueError(f"Unknown pooling type: {pooling}")


class SubsamplingLayerImpl(Layer):
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        kernel, strides = _pair(c.kernel), _pair(c.stride)
        pads = spatial_padding(
            (x.shape[1], x.shape[2]), kernel, strides, _pair(c.padding), c.mode)
        y = _pool2d(x, kernel=kernel, strides=strides, padding=pads,
                    pooling=c.pooling, pnorm=c.pnorm)
        return y, state


def _downsample_time_mask(mask, kernel, stride, padding, mode):
    """Downsample a [b, t] mask with a conv/pool's geometry: an output step
    is valid if ANY contributing input step is valid
    (Layer.feedForwardMaskArray parity for time-shrinking layers)."""
    if mask is None:
        return None
    m = mask.reshape(mask.shape[0], -1)[:, :, None, None].astype(jnp.float32)
    pads = spatial_padding((m.shape[1],), (kernel,), (stride,), (padding,),
                           mode) + [(0, 0)]
    out = ops.get("max_pool2d")(m, kernel=(kernel, 1), strides=(stride, 1),
                                padding=pads)
    return out[:, :, 0, 0]


class Subsampling1DLayerImpl(Layer):
    """1D pooling on [b, t, f]: runs the 2D kernels with a unit W dim."""

    def feed_forward_mask(self, mask):
        c = self.conf
        return _downsample_time_mask(mask, c.kernel, c.stride, c.padding,
                                     c.mode)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        pads = spatial_padding((x.shape[1],), (c.kernel,), (c.stride,),
                               (c.padding,), c.mode) + [(0, 0)]
        y = _pool2d(x[:, :, None, :], kernel=(c.kernel, 1),
                    strides=(c.stride, 1), padding=pads, pooling=c.pooling,
                    pnorm=c.pnorm)
        return y[:, :, 0, :], state


class ZeroPaddingLayerImpl(Layer):
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self.conf.pad
        return jnp.pad(x, [(0, 0), (t, b), (l, r), (0, 0)]), state
