"""Variational autoencoder runtime layer.

Parity: nn/layers/variational/VariationalAutoencoder.java (1,095 LoC) —
encoder/decoder MLPs inside ONE layer, reparameterization trick, ELBO with a
pluggable reconstruction distribution, own computeGradientAndScore (here:
``pretrain_loss`` autodiffed inside the jitted pretrain step). Supervised
``apply`` emits the posterior mean (the reference's activate()).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers_pretrain import (
    BernoulliReconstruction,
    CompositeReconstruction,
    ExponentialReconstruction,
    GaussianReconstruction,
    LossWrapperReconstruction,
)
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.ops import activations as act_mod
from deeplearning4j_tpu.ops import initializers as init_mod
from deeplearning4j_tpu.ops import losses as losses_mod


def _neg_log_prob(dist, x, raw):
    """-log p(x|z) summed over features, mean over batch. ``raw`` is the
    reconstruction head's raw output (distribution parameters)."""
    if isinstance(dist, BernoulliReconstruction):
        p = jax.nn.sigmoid(raw)
        eps = 1e-7
        ll = x * jnp.log(p + eps) + (1 - x) * jnp.log(1 - p + eps)
        return -jnp.mean(jnp.sum(ll, axis=-1))
    if isinstance(dist, GaussianReconstruction):
        n = x.shape[-1]
        act = act_mod.get(dist.activation)
        mean = act(raw[..., :n])
        logvar = raw[..., n:]
        ll = -0.5 * (math.log(2 * math.pi) + logvar
                     + (x - mean) ** 2 / jnp.exp(logvar))
        return -jnp.mean(jnp.sum(ll, axis=-1))
    if isinstance(dist, ExponentialReconstruction):
        gamma = raw  # log(lambda)
        ll = gamma - jnp.exp(gamma) * x
        return -jnp.mean(jnp.sum(ll, axis=-1))
    if isinstance(dist, LossWrapperReconstruction):
        loss = losses_mod.get(dist.loss)
        return loss.score(x, raw, act_mod.get(dist.activation), None)
    if isinstance(dist, CompositeReconstruction):
        total = 0.0
        x_off = p_off = 0
        for n, inner in dist.distributions:
            psize = inner.param_size(n)
            total = total + _neg_log_prob(
                inner, x[..., x_off:x_off + n], raw[..., p_off:p_off + psize])
            x_off += n
            p_off += psize
        return total
    raise TypeError(f"Unknown reconstruction distribution {type(dist)}")


class VAELayer(Layer):
    is_pretrainable = True

    def _sizes(self):
        c = self.conf
        enc = [c.n_in, *c.encoder_layer_sizes]
        dec = [c.n_out, *c.decoder_layer_sizes]
        return enc, dec

    def init_params(self, key):
        c = self.conf
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        dt = self.param_dtype
        enc, dec = self._sizes()
        params = {}

        def dense(key, n_in, n_out):
            kW, _ = jax.random.split(key)
            return {"W": w_fn(kW, (n_in, n_out), n_in, n_out, dt),
                    "b": jnp.zeros((n_out,), dt)}

        keys = jax.random.split(key, len(enc) + len(dec) + 3)
        ki = 0
        for i in range(len(enc) - 1):
            params[f"enc{i}"] = dense(keys[ki], enc[i], enc[i + 1]); ki += 1
        params["mean"] = dense(keys[ki], enc[-1], c.n_out); ki += 1
        params["logvar"] = dense(keys[ki], enc[-1], c.n_out); ki += 1
        for i in range(len(dec) - 1):
            params[f"dec{i}"] = dense(keys[ki], dec[i], dec[i + 1]); ki += 1
        psize = c.reconstruction.param_size(c.n_in)
        params["recon"] = dense(keys[ki], dec[-1], psize)
        return params

    def _mlp(self, params, prefix, n_layers, x):
        act = self.activation_fn
        for i in range(n_layers):
            p = params[f"{prefix}{i}"]
            x = act(x @ p["W"] + p["b"])
        return x

    def encode(self, params, x):
        c = self.conf
        h = self._mlp(params, "enc", len(c.encoder_layer_sizes), x)
        mean = h @ params["mean"]["W"] + params["mean"]["b"]
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mean, logvar

    def decode(self, params, z):
        c = self.conf
        d = self._mlp(params, "dec", len(c.decoder_layer_sizes), z)
        return d @ params["recon"]["W"] + params["recon"]["b"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        mean, _ = self.encode(params, x)
        return mean, state  # activate() == pzxMean in the reference

    def pretrain_loss(self, params, x, rng):
        """-ELBO = reconstruction NLL + KL(q(z|x) || N(0, I)), averaged over
        the batch (VariationalAutoencoder.computeGradientAndScore parity)."""
        c = self.conf
        x = x.astype(self.param_dtype)
        mean, logvar = self.encode(params, x)
        kl = -0.5 * jnp.sum(1 + logvar - mean ** 2 - jnp.exp(logvar), axis=-1)
        recon = 0.0
        for s in range(c.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape,
                                    mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            recon = recon + _neg_log_prob(c.reconstruction, x,
                                          self.decode(params, z))
        return recon / c.num_samples + jnp.mean(kl)

    def reconstruction_error(self, params, x, rng=None):
        """Deterministic reconstruction NLL at the posterior mean
        (reconstructionError parity — usable as an anomaly score)."""
        mean, _ = self.encode(params, x)
        return _neg_log_prob(self.conf.reconstruction, x,
                             self.decode(params, mean))

    def generate_at_mean_given_z(self, params, z):
        """Decode latent codes (generateAtMeanGivenZ parity)."""
        raw = self.decode(params, z)
        dist = self.conf.reconstruction
        if isinstance(dist, BernoulliReconstruction):
            return jax.nn.sigmoid(raw)
        if isinstance(dist, GaussianReconstruction):
            n = raw.shape[-1] // 2
            return act_mod.get(dist.activation)(raw[..., :n])
        return raw
