"""Global pooling runtime layer.

Parity: nn/layers/pooling/GlobalPoolingLayer.java — mask-aware global
max/avg/sum/pnorm over the time dimension ([b, t, f]) or spatial dimensions
([b, h, w, c]); masking semantics follow util/MaskedReductionUtil.java.
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer


class GlobalPoolingLayerImpl(Layer):
    def feed_forward_mask(self, mask):
        return None

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        if x.ndim == 3:      # [b, t, f] — pool over time, mask-aware
            axes = (1,)
            m = None
            if mask is not None:
                m = mask.reshape(mask.shape[0], -1)[:, :, None].astype(x.dtype)
        elif x.ndim == 4:    # [b, h, w, c] — pool over space
            axes = (1, 2)
            m = None
        else:
            raise ValueError(
                f"GlobalPooling expects 3d or 4d input, got shape {x.shape}")

        if m is None:
            if c.pooling == "max":
                y = jnp.max(x, axis=axes)
            elif c.pooling == "avg":
                y = jnp.mean(x, axis=axes)
            elif c.pooling == "sum":
                y = jnp.sum(x, axis=axes)
            elif c.pooling == "pnorm":
                y = jnp.sum(jnp.abs(x) ** c.pnorm, axis=axes) ** (1.0 / c.pnorm)
            else:
                raise ValueError(f"Unknown pooling type: {c.pooling}")
            return y, state

        # masked time-series reductions (MaskedReductionUtil parity)
        if c.pooling == "max":
            neg = jnp.finfo(x.dtype).min
            y = jnp.max(jnp.where(m > 0, x, neg), axis=1)
        elif c.pooling == "avg":
            denom = jnp.maximum(jnp.sum(m, axis=1), 1e-8)
            y = jnp.sum(x * m, axis=1) / denom
        elif c.pooling == "sum":
            y = jnp.sum(x * m, axis=1)
        elif c.pooling == "pnorm":
            s = jnp.sum(jnp.abs(x * m) ** c.pnorm, axis=1)
            y = s ** (1.0 / c.pnorm)
        else:
            raise ValueError(f"Unknown pooling type: {c.pooling}")
        return y, state
