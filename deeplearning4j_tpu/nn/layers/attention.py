"""Transformer runtime layers: GPT embedding, causal self-attention, the
pre-LN transformer block, and the streaming-exact output head.

Streaming (`rnnTimeStep` parity, extended): where GravesLSTM carries
(h, c), attention carries the KV cache ("k"/"v", [b, C, heads, dh] f32)
and each row's absolute position ("pos", [b] int32). The cache is
allocated ONCE at ``max_cache_len`` on the first streaming call and every
subsequent call — prefill chunk or single decode token — attends against
that full fixed extent, because the decode bit-identity contract
(ops/attention.py docstring) only holds at a constant kv length.

Two arithmetic paths, one tolerance seam:

- **training / net.output()**: compute-dtype einsum projections (MXU
  GEMMs) and the registry-resolved ``causal_mha`` (Pallas flash on TPU).
- **streaming (prefill + decode)**: f32 multiply+reduce projections
  (``_dense_exact``), f32 LayerNorm, and ``causal_mha_exact`` — every op
  whose reduction order a GEMM would retile by shape is lowered as a
  fused reduce instead, so a token's output is bit-identical whether it
  was computed in a full-prompt prefill, a chunked prefill, or a
  one-token decode step. Measured on this XLA: the einsum
  ``btf,fg->btg`` itself moves by 1 ulp between t=1 and t=128 at
  (1, 128, 256, 1024) f32, so exactness has to cover the projections and
  the head, not just the attention op.

The two paths agree to dtype tolerance (f32 ~1e-6 relative, bf16 ~1e-2)
— the same two-tier contract PRECISION.md documents for serving, pinned
in tests/test_transformer.py. Masked (right-padded) streaming calls are
OUTPUT-exact from any cache frontier, not just pos 0: per-row true
lengths come from the features mask, junk key slots beyond a row's
length land at positions >= the row's new frontier — above everything
the real tokens attend — and are overwritten by later steps before they
ever become visible. That is what lets serving's chunked prefill extend
a mid-sequence cache with mask-padded chunk buckets (the extend op in
serving/decode.py) and stay bit-identical, pinned by
tests/test_transformer.py::TestChunkedPrefillSharing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayerImpl
from deeplearning4j_tpu.ops import attention as att
from deeplearning4j_tpu.ops import initializers as init_mod

_DEFAULT_CACHE_LEN = 256


def _layer_norm(x, g, b, eps):
    """LayerNorm in f32 (returns f32). mean/variance lower as fused
    reduces over the feature axis — shape-stable, measured."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    d = xf - mu
    var = jnp.mean(d * d, axis=-1, keepdims=True)
    y = d * jax.lax.rsqrt(var + float(eps))
    return y * g.astype(jnp.float32) + b.astype(jnp.float32)


def _dense_exact(x, W, b):
    """[b, t, f] @ [f, g] as an explicit multiply+reduce in f32 — the
    decode-stable lowering (module docstring). XLA fuses the broadcast
    product into the reduce; nothing [b, t, f, g]-shaped reaches memory."""
    out = jnp.sum(
        x.astype(jnp.float32)[:, :, :, None]
        * W.astype(jnp.float32)[None, None, :, :], axis=2)
    if b is not None:
        out = out + b.astype(jnp.float32)[None, None, :]
    return out


def _dense_gemm(x, W, b, cd):
    """The throughput lowering: one compute-dtype GEMM."""
    z = jnp.einsum("btf,fg->btg", x.astype(cd), W.astype(cd))
    if b is not None:
        z = z + b.astype(cd)
    return z


def _mask_lengths(mask, t):
    """Per-row true length [b] int32 from a features mask (or None)."""
    if mask is None:
        return None
    m = mask.reshape(mask.shape[0], -1)
    return jnp.sum(m.astype(jnp.int32), axis=1)


class GptEmbeddingLayer(Layer):
    """One-hot [b, t, vocab] -> [b, t, d]: token gather + learned
    positional table. Gathers are per-element exact, so this layer is
    bit-stable in both paths by construction; streaming carries "pos" to
    offset the positional lookup."""

    is_recurrent_stateful = True
    streaming = False

    def init_params(self, key):
        n_in, n_out = self.conf.n_in, self.conf.n_out
        max_len = int(self.conf.max_len)
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        k1, k2 = jax.random.split(key)
        return {
            "Wtok": w_fn(k1, (n_in, n_out), n_in, n_out, self.param_dtype),
            "Wpos": w_fn(k2, (max_len, n_out), max_len, n_out,
                         self.param_dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        b, t = x.shape[0], x.shape[1]
        idx = jnp.argmax(x, axis=-1)                          # [b, t]
        tok = jnp.take(params["Wtok"], idx, axis=0)           # param dtype
        if self.streaming and "pos" in state:
            p0 = state["pos"]
        else:
            p0 = jnp.zeros((b,), jnp.int32)
        positions = p0[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        positions = jnp.clip(positions, 0, int(self.conf.max_len) - 1)
        pos_emb = jnp.take(params["Wpos"], positions, axis=0)  # [b, t, d]
        y = tok.astype(jnp.float32) + pos_emb.astype(jnp.float32)
        new_state = dict(state)
        if self.streaming:
            lengths = _mask_lengths(mask, t)
            new_state["pos"] = p0 + (t if lengths is None else lengths)
            return y, new_state                               # f32, exact
        return y.astype(self.compute_dtype), new_state


class _AttentionCore(Layer):
    """Shared QKV/output-projection + KV-cache machinery."""

    is_recurrent_stateful = True
    streaming = False

    def __init__(self, conf, input_type, global_conf, policy):
        super().__init__(conf, input_type, global_conf, policy)
        d = int(conf.n_out)
        heads = int(conf.n_heads)
        if d % heads != 0:
            raise ValueError(
                f"{type(conf).__name__} '{conf.name}': n_out={d} not "
                f"divisible by n_heads={heads}")
        self.n_heads = heads
        self.head_dim = d // heads

    @property
    def cache_len(self) -> int:
        return int(self.resolve("max_cache_len", None) or _DEFAULT_CACHE_LEN)

    def _init_attn_params(self, key):
        d_in, d = int(self.conf.n_in), int(self.conf.n_out)
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        ks = jax.random.split(key, 4)
        bias0 = float(self.resolve("bias_init", 0.0))
        pd = self.param_dtype
        return {
            # column-parallel QKV (last axis shards on the model mesh
            # axis), row-parallel output projection (first axis shards)
            "Wq": w_fn(ks[0], (d_in, d), d_in, d, pd),
            "Wk": w_fn(ks[1], (d_in, d), d_in, d, pd),
            "Wv": w_fn(ks[2], (d_in, d), d_in, d, pd),
            "Wo": w_fn(ks[3], (d, d), d, d, pd),
            "bq": jnp.full((d,), bias0, pd),
            "bk": jnp.full((d,), bias0, pd),
            "bv": jnp.full((d,), bias0, pd),
            "bo": jnp.full((d,), bias0, pd),
        }

    def _attn(self, params, state, h, mask):
        """Apply MHA to ``h`` [b, t, d_in]; returns (proj [b, t, d],
        carries-or-None). Streaming attends against the fixed-extent
        cache; training runs the registry seam over the live sequence."""
        b, t = h.shape[0], h.shape[1]
        heads, dh, d = self.n_heads, self.head_dim, int(self.conf.n_out)
        if self.streaming:
            q = _dense_exact(h, params["Wq"], params["bq"])
            k = _dense_exact(h, params["Wk"], params["bk"])
            v = _dense_exact(h, params["Wv"], params["bv"])
            q = q.reshape(b, t, heads, dh)
            k = k.reshape(b, t, heads, dh)
            v = v.reshape(b, t, heads, dh)
            if "k" in state:
                kc, vc, pos0 = state["k"], state["v"], state["pos"]
            else:
                C = self.cache_len
                kc = jnp.zeros((b, C, heads, dh), jnp.float32)
                vc = jnp.zeros((b, C, heads, dh), jnp.float32)
                pos0 = jnp.zeros((b,), jnp.int32)
            kc, vc = att.extend_cache(kc, vc, k, v, pos0)
            out = att.causal_mha_exact(q, kc, vc, q_start=pos0)
            lengths = _mask_lengths(mask, t)
            new_pos = pos0 + (t if lengths is None else lengths)
            proj = _dense_exact(out.reshape(b, t, d), params["Wo"],
                                params["bo"])
            return proj, {"k": kc, "v": vc, "pos": new_pos}
        cd = h.dtype
        q = _dense_gemm(h, params["Wq"], params["bq"], cd)
        k = _dense_gemm(h, params["Wk"], params["bk"], cd)
        v = _dense_gemm(h, params["Wv"], params["bv"], cd)
        out = att.causal_mha(q.reshape(b, t, heads, dh),
                             k.reshape(b, t, heads, dh),
                             v.reshape(b, t, heads, dh))
        proj = _dense_gemm(out.reshape(b, t, d), params["Wo"], params["bo"],
                           cd)
        return proj, None


class SelfAttentionLayer(_AttentionCore):
    """Bare causal MHA (projections + attention + output projection) —
    no residual or norm; ``activation`` (default identity) applies to the
    projected output."""

    def init_params(self, key):
        return self._init_attn_params(key)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        if self.streaming:
            h = x.astype(jnp.float32)
        else:
            h = x.astype(self.compute_dtype)
        proj, carries = self._attn(params, state, h, mask)
        y = self.activation_fn(proj)
        new_state = dict(state)
        if carries:
            new_state.update(carries)
        return y, new_state

    @property
    def activation_fn(self):
        from deeplearning4j_tpu.ops import activations as activations_mod
        return activations_mod.get(self.resolve("activation", "identity"))


class TransformerBlockLayer(_AttentionCore):
    """Pre-LN block: ``a = x + attn(ln1(x))``, ``y = a + mlp(ln2(a))``.
    Residual width is fixed (n_in == n_out enforced); LayerNorm always
    runs in f32; the MLP nonlinearity is ``activation`` (gelu unless
    overridden)."""

    def __init__(self, conf, input_type, global_conf, policy):
        super().__init__(conf, input_type, global_conf, policy)
        if int(conf.n_in) != int(conf.n_out):
            raise ValueError(
                f"TransformerBlock '{conf.name}': residual stream needs "
                f"n_in == n_out, got {conf.n_in} != {conf.n_out}")

    @property
    def activation_fn(self):
        from deeplearning4j_tpu.ops import activations as activations_mod
        return activations_mod.get(self.resolve("activation", "gelu"))

    def init_params(self, key):
        d = int(self.conf.n_out)
        hidden = int(self.conf.ffn_mult) * d
        w_fn = init_mod.resolve(self.resolve("weight_init", "xavier"))
        k_attn, k1, k2 = jax.random.split(key, 3)
        pd = self.param_dtype
        bias0 = float(self.resolve("bias_init", 0.0))
        params = self._init_attn_params(k_attn)
        params.update({
            "ln1_g": jnp.ones((d,), pd),
            "ln1_b": jnp.zeros((d,), pd),
            "ln2_g": jnp.ones((d,), pd),
            "ln2_b": jnp.zeros((d,), pd),
            # column-parallel up-projection, row-parallel down-projection
            "W1": w_fn(k1, (d, hidden), d, hidden, pd),
            "b1": jnp.full((hidden,), bias0, pd),
            "W2": w_fn(k2, (hidden, d), hidden, d, pd),
            "b2": jnp.full((d,), bias0, pd),
        })
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        eps = float(self.conf.ln_eps)
        x = self._input_dropout(x, train, rng)
        if self.streaming:
            xf = x.astype(jnp.float32)
            h1 = _layer_norm(xf, params["ln1_g"], params["ln1_b"], eps)
            proj, carries = self._attn(params, state, h1, mask)
            a = xf + proj
            h2 = _layer_norm(a, params["ln2_g"], params["ln2_b"], eps)
            m = self.activation_fn(_dense_exact(h2, params["W1"],
                                                params["b1"]))
            y = a + _dense_exact(m, params["W2"], params["b2"])
            new_state = dict(state)
            new_state.update(carries)
            return y, new_state
        cd = self.compute_dtype
        xc = x.astype(cd)
        h1 = _layer_norm(xc, params["ln1_g"], params["ln1_b"], eps)
        proj, _ = self._attn(params, state, h1.astype(cd), mask)
        a = xc + proj
        h2 = _layer_norm(a, params["ln2_g"], params["ln2_b"], eps)
        m = self.activation_fn(
            _dense_gemm(h2.astype(cd), params["W1"], params["b1"], cd))
        y = a + _dense_gemm(m, params["W2"], params["b2"], cd)
        return y, state


class GptOutputLayer(RnnOutputLayerImpl):
    """RnnOutput head whose STREAMING preout is the exact multiply+reduce
    lowering — the final logits must be decode-stable too (the head einsum
    alone moves by 1 ulp between t=1 and t=T, module docstring), and the
    stock RnnOutput head keeps its einsum because the existing LSTM
    rnn_time_step pin is calibrated against it."""

    is_recurrent_stateful = True
    streaming = False

    def preout(self, params, x):
        if self.streaming:
            return _dense_exact(x.astype(jnp.float32), params["W"],
                                params.get("b"))
        return super().preout(params, x)
