"""AutoEncoder, RBM, CenterLossOutput, Frozen runtime layers.

Parity: nn/layers/feedforward/autoencoder/AutoEncoder.java (denoising AE),
nn/layers/feedforward/rbm/RBM.java (contrastive divergence),
nn/layers/training/CenterLossOutputLayer.java, nn/layers/FrozenLayer.java.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops import initializers as init_mod
from deeplearning4j_tpu.ops import losses as losses_mod


class AutoEncoderLayer(DenseLayer):
    """Denoising autoencoder: encoder = the dense forward; pretrain loss
    reconstructs the uncorrupted input through tied decoder params
    (AutoEncoder.java: W' = W^T plus separate visible bias)."""

    is_pretrainable = True

    def init_params(self, key):
        params = super().init_params(key)
        params["vb"] = jnp.zeros((self.conf.n_in,), self.param_dtype)
        return params

    def pretrain_loss(self, params, x, rng):
        c = self.conf
        x = x.astype(self.param_dtype)
        corrupted = x
        if c.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1.0 - c.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        h = self.activation_fn(self.preout(params, corrupted))
        recon = h @ params["W"].T + params["vb"]
        loss = losses_mod.get(c.loss)
        return loss.score(x, recon, self.activation_fn, None)


class RBMLayer(DenseLayer):
    """Bernoulli-Bernoulli RBM (RBM.java parity, legacy). Pretraining uses
    CD-k with the reparameterization-free gradient estimator: the positive
    and negative phase statistics enter the loss via stop_gradient samples,
    so autodiff reproduces the classic CD update."""

    is_pretrainable = True

    def init_params(self, key):
        params = super().init_params(key)
        params["vb"] = jnp.zeros((self.conf.n_in,), self.param_dtype)
        return params

    def _propup(self, params, v):
        return jax.nn.sigmoid(v @ params["W"] + params.get(
            "b", jnp.zeros((self.conf.n_out,), self.param_dtype)))

    def _propdown(self, params, h):
        return jax.nn.sigmoid(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._input_dropout(x, train, rng)
        return self._propup(params, x.astype(self.param_dtype)), state

    def _free_energy(self, params, v):
        b = params.get("b", jnp.zeros((self.conf.n_out,), self.param_dtype))
        wx_b = v @ params["W"] + b
        return (-v @ params["vb"]
                - jnp.sum(jax.nn.softplus(wx_b), axis=-1))

    def pretrain_loss(self, params, x, rng):
        """CD-k via the free-energy difference F(v_data) - F(v_model) with a
        stop-gradient Gibbs chain — its gradient is the standard CD update."""
        c = self.conf
        v0 = x.astype(self.param_dtype)
        v = v0
        for step in range(c.k):
            kh, kv = jax.random.split(jax.random.fold_in(rng, step))
            h = jax.random.bernoulli(kh, self._propup(params, v)).astype(
                v.dtype)
            v = self._propdown(params, h)
        v_model = jax.lax.stop_gradient(v)
        return jnp.mean(self._free_energy(params, v0)
                        - self._free_energy(params, v_model))


class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (CenterLossOutputLayer.java):
    total = dataLoss + lambda/2 * ||f - c_y||^2. Class centers live in layer
    STATE and track class-mean features with an ``alpha`` moving average
    (the reference folds the center update into the gradient step; the
    moving-average form is the same fixed point, functional-style)."""

    loss_uses_state = True

    def init_state(self):
        return {"centers": jnp.zeros(
            (self.conf.n_out, self.conf.n_in), self.param_dtype)}

    def loss(self, params, x, labels, *, train=False, rng=None, mask=None,
             state=None):
        base = super().loss(params, x, labels, train=train, rng=rng, mask=mask)
        centers = state["centers"] if state is not None else None
        if centers is None:
            return base
        c_y = labels @ centers  # one-hot selects each example's class center
        sq = jnp.sum((x - c_y) ** 2, axis=-1)
        if mask is not None:
            m = mask.reshape(-1).astype(sq.dtype)
            center_term = 0.5 * self.conf.lmbda * (
                jnp.sum(sq * m) / jnp.maximum(jnp.sum(m), 1.0))
        else:
            center_term = 0.5 * self.conf.lmbda * jnp.mean(sq)
        return base + center_term

    def update_centers(self, state, x, labels, mask=None):
        """alpha moving-average center update (applied in the train step,
        outside the differentiated loss); masked examples are excluded."""
        centers = state["centers"]
        if mask is not None:
            labels = labels * mask.reshape(-1, 1).astype(labels.dtype)
        counts = jnp.maximum(labels.sum(axis=0), 1.0)[:, None]
        sums = labels.T @ x
        batch_means = sums / counts
        present = (labels.sum(axis=0) > 0)[:, None]
        a = self.conf.alpha
        new = jnp.where(present, (1 - a) * centers + a * batch_means, centers)
        return {"centers": new}


class FrozenLayerWrapper(Layer):
    """Delegates forward to the wrapped layer; update-time freezing comes
    from resolve('updater') -> NoOp and zero regularization."""

    # pinned (not delegated): pretraining a frozen layer is a guaranteed
    # no-op, so skip it entirely
    is_pretrainable = False

    def __init__(self, conf, input_type, global_conf, policy):
        super().__init__(conf, input_type, global_conf, policy)
        self.inner = conf.inner.make_layer(input_type, global_conf, policy)

    def resolve(self, name, default=None):
        if name == "updater":
            from deeplearning4j_tpu.nn.updater import NoOp
            return NoOp()
        return self.inner.resolve(name, default)

    def init_params(self, key):
        return self.inner.init_params(key)

    def init_state(self):
        return self.inner.init_state()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.inner.apply(params, state, x, train=train, rng=rng,
                                mask=mask)

    def feed_forward_mask(self, mask):
        return self.inner.feed_forward_mask(mask)

    def regularization(self, params):
        return jnp.zeros((), self.param_dtype)

    def loss(self, params, x, labels, *, train=False, rng=None, mask=None,
             **kwargs):
        return self.inner.loss(params, x, labels, train=train, rng=rng,
                               mask=mask, **kwargs)

    def update_centers(self, state, x, labels, mask=None):
        """Frozen: the center-loss term still contributes to the loss (via
        the delegated ``loss``/``loss_uses_state``), but centers do not
        move."""
        return state

    def __getattr__(self, name):
        # Delegate capability flags/hooks (e.g. ``loss_uses_state``) so
        # wrapping an output layer does not silently drop loss terms.
        inner = self.__dict__.get("inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)
