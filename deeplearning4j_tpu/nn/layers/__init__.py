"""Runtime layer implementations (parity: deeplearning4j-nn/.../nn/layers/).

Layers here are *functional*: they hold config + shapes only; parameters and
mutable state (e.g. batch-norm running stats) live in pytrees owned by the
network and are passed through ``apply``. This replaces the reference's
stateful layer objects holding views into one flat param vector
(MultiLayerNetwork.java:903-906) — XLA's fusion makes the contiguous-buffer
trick obsolete (SURVEY.md §7).
"""

from deeplearning4j_tpu.nn.layers.base import Layer
