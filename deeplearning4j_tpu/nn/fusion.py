"""Block-fusion pass: rewrite bottleneck-tail chains onto the fused op.

The ComputationGraph executes vertices one by one (graph._walk), which
leaves the conv -> batch-norm -> residual-add -> relu tail of every
ResNet-style block to XLA's generic fusion: the conv output is
materialized and re-read, and it is pinned as an autodiff residual. This
pass pattern-matches those chains in the DAG *configuration* and routes
them through ops/fused_block's ``conv1x1_bn_add_relu`` op (the
two-pass-recompute schedule) at execution time — the framework-level
analogue of the reference wiring whole-layer work into one cuDNN call
(CudnnConvolutionHelper.java:49) instead of composing primitive ops.

Pattern (all interior vertices single-consumer, none a network output):

    conv: Convolution2D, 1x1 kernel, stride 1, no bias, identity
          activation, no dropout, padding 0
    bn:   BatchNorm, identity activation (params present)
    add:  ElementWiseVertex(op="add") with exactly 2 inputs — the bn and
          an arbitrary shortcut vertex
    act:  ActivationLayer("relu")

Profitability gate (measured on the v5e, PERF.md round 4): the recompute
schedule reads x twice per pass, so it must satisfy 2*n_out > n_in AND
n_in % 128 == 0 — C_in = 64 tensors are lane-padded to 128 on TPU, which
doubles every x read and flips the trade (stage-1 bottlenecks stay on the
composed path).

The pass only changes the TRAINING step's lowering; eval-mode forward
(running statistics, no batch stats) walks the graph unfused. OFF by
default (see ``enabled``); opt in with DL4J_TPU_FUSE_BLOCKS=1.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict

from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
from deeplearning4j_tpu.nn.conf.layers_conv import BatchNorm, Convolution2D
from deeplearning4j_tpu.nn.conf.vertices import ElementWiseVertex


def enabled() -> bool:
    """Default OFF: measured end-to-end on the v5e (PERF.md round 4), the
    recompute schedule's cost-model savings on isolated chains did not
    survive composition into the full ResNet-50 step (106.4 vs 103.7
    ms/step, +2.7 GB) — XLA's own residual sharing beats the recompute
    once the whole backward is in one program. The pass stays available
    (DL4J_TPU_FUSE_BLOCKS=1) as the integration point for a future
    schedule that does pay."""
    return os.environ.get("DL4J_TPU_FUSE_BLOCKS", "0") == "1"


@dataclass(frozen=True)
class FusedBlockTail:
    conv: str           # conv vertex name
    bn: str             # batch-norm vertex name
    add: str            # element-wise add vertex name
    out: str            # relu activation vertex name (the chain's output)
    conv_input: str     # vertex feeding the conv
    shortcut: str       # the add's other input


def _pair_of(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_matches(conf: Convolution2D, default_activation: str) -> bool:
    if not isinstance(conf, Convolution2D):
        return False
    if _pair_of(conf.kernel) != (1, 1) or _pair_of(conf.stride) != (1, 1):
        return False
    if _pair_of(conf.dilation or 1) != (1, 1):
        return False
    if _pair_of(conf.padding or 0) != (0, 0):
        return False
    if conf.has_bias:
        return False
    # a None activation INHERITS the global default (sigmoid per the
    # reference's NeuralNetConfiguration defaults) — resolve before
    # matching, never assume identity
    if (conf.activation or default_activation) != "identity":
        return False
    if getattr(conf, "dropout", None):
        return False
    n_in, n_out = conf.n_in, conf.n_out
    if not n_in or not n_out:
        return False
    # profitability: expand conv, unpadded input lanes (see module doc)
    return 2 * n_out > n_in and n_in % 128 == 0


def find_fusable_chains(vertices, vertex_inputs, network_outputs,
                        default_activation: str = "sigmoid"
                        ) -> Dict[str, FusedBlockTail]:
    """Scan a graph's RESOLVED vertex configs (n_in inference done) for
    fusable block tails. Returns {relu-vertex-name: FusedBlockTail}."""
    if not enabled():
        return {}
    consumers: Dict[str, list] = {}
    for name, ins in vertex_inputs.items():
        for i in ins:
            consumers.setdefault(i, []).append(name)
    outputs = set(network_outputs)

    def sole_consumer(name):
        c = consumers.get(name, [])
        return c[0] if len(c) == 1 and name not in outputs else None

    plans: Dict[str, FusedBlockTail] = {}
    for conv_name, conv_conf in vertices.items():
        if not _conv_matches(conv_conf, default_activation):
            continue
        bn_name = sole_consumer(conv_name)
        if bn_name is None:
            continue
        bn_conf = vertices[bn_name]
        if not isinstance(bn_conf, BatchNorm):
            continue
        if (bn_conf.activation or default_activation) != "identity":
            continue
        if getattr(bn_conf, "lock_gamma_beta", False):
            continue
        if getattr(bn_conf, "dropout", None):
            continue  # fused tail has no dropout application point
        add_name = sole_consumer(bn_name)
        if add_name is None:
            continue
        add_conf = vertices[add_name]
        if not (isinstance(add_conf, ElementWiseVertex)
                and add_conf.op == "add"):
            continue
        add_inputs = vertex_inputs[add_name]
        if len(add_inputs) != 2 or bn_name not in add_inputs:
            continue
        shortcut = [i for i in add_inputs if i != bn_name]
        if len(shortcut) != 1:   # bn feeding both slots: not this pattern
            continue
        act_name = sole_consumer(add_name)
        if act_name is None:
            continue
        act_conf = vertices[act_name]
        if not (isinstance(act_conf, ActivationLayer)
                and (act_conf.activation
                     or default_activation) == "relu"):
            continue
        if getattr(act_conf, "dropout", None):
            continue
        plans[act_name] = FusedBlockTail(
            conv=conv_name, bn=bn_name, add=add_name, out=act_name,
            conv_input=vertex_inputs[conv_name][0],
            shortcut=shortcut[0])
    return plans


def interior_vertices(plans: Dict[str, FusedBlockTail]) -> set:
    """Vertices whose per-vertex execution is subsumed by a fused tail."""
    out = set()
    for fb in plans.values():
        out.update((fb.conv, fb.bn, fb.add))
    return out


def execute_fused_tail(fb: FusedBlockTail, graph, params, state, acts):
    """Run one fused tail (training mode): returns (y, bn_state_update).
    Mirrors BatchNormLayer.apply's running-statistics update exactly."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import fused_block as _fb  # registers op
    from deeplearning4j_tpu.ops import registry as ops

    del _fb
    conv_layer = graph._layer_by_name[fb.conv]
    bn_layer = graph._layer_by_name[fb.bn]
    bn_conf = graph._resolved_confs[fb.bn]
    cd = conv_layer.compute_dtype

    x = acts[fb.conv_input]
    sc = acts[fb.shortcut]
    W = params[fb.conv]["W"].astype(cd)          # [1, 1, K, N]
    bn_params = params.get(fb.bn, {})
    f = W.shape[-1]
    if bn_params:
        gamma, beta = bn_params["gamma"], bn_params["beta"]
    else:
        gamma = jnp.full((f,), float(bn_conf.gamma), bn_layer.param_dtype)
        beta = jnp.full((f,), float(bn_conf.beta), bn_layer.param_dtype)
    bn_state = state[fb.bn]

    y, mean, var = ops.get("conv1x1_bn_add_relu", backend="xla_recompute")(
        x.astype(cd), W, gamma, beta, sc, shift=bn_state["mean"],
        eps=bn_conf.eps)

    d = bn_conf.decay
    sd = bn_layer.param_dtype
    new_bn_state = {
        "mean": d * bn_state["mean"] + (1 - d) * mean.astype(sd),
        "var": d * bn_state["var"] + (1 - d) * var.astype(sd),
    }
    return y, new_bn_state
