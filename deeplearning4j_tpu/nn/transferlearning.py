"""Transfer learning: clone-and-edit trained networks.

Parity: nn/transferlearning/{TransferLearning, FineTuneConfiguration,
TransferLearningHelper}.java (SURVEY.md §2.3) — fine-tune overrides, freeze
prefixes (wrapping layers in Frozen), output replacement, n_out surgery with
re-initialization, and featurization through the frozen boundary.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.core import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.layers_pretrain import Frozen
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclass(frozen=True)
class FineTuneConfiguration:
    """Subset of NeuralNetConfiguration fields to override on the new net
    (FineTuneConfiguration.java parity); None = keep the original value."""

    seed: Optional[int] = None
    activation: Optional[str] = None
    weight_init: Optional[Any] = None
    learning_rate: Optional[float] = None
    updater: Optional[Any] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    def apply_to(self, gc: NeuralNetConfiguration) -> NeuralNetConfiguration:
        overrides = {f.name: getattr(self, f.name)
                     for f in dataclasses.fields(self)
                     if getattr(self, f.name) is not None}
        return gc.replace(**overrides)


class TransferLearningBuilder:
    """TransferLearning.Builder parity: freeze a prefix, drop/replace the
    tail, change n_out, fine-tune hyperparameters — weights of kept layers
    are copied, edited/new layers re-initialize."""

    def __init__(self, net: MultiLayerNetwork):
        self._net = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        # (conf, carry_weights) per retained layer, resolved shapes
        self._layers = [(c, True) for c in net._resolved_confs]
        self._input_type = net.conf.input_type

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer: int | str):
        """Freeze layers [0..layer] inclusive (setFeatureExtractor parity)."""
        self._freeze_until = self._index_of(layer)
        return self

    def _index_of(self, layer: int | str) -> int:
        if isinstance(layer, int):
            return layer
        for i, (c, _) in enumerate(self._layers):
            if c.name == layer:
                return i
        raise ValueError(f"No layer named '{layer}'")

    def remove_output_layer(self):
        self._layers = self._layers[:-1]
        return self

    def remove_layers_from(self, layer: int | str):
        self._layers = self._layers[:self._index_of(layer)]
        return self

    def add_layer(self, conf):
        self._layers.append((conf, False))
        return self

    def n_out_replace(self, layer: int | str, n_out: int,
                      weight_init: Any = None):
        """Change a layer's n_out; that layer and the next re-initialize
        (nOutReplace parity)."""
        i = self._index_of(layer)
        conf, _ = self._layers[i]
        kw = {"n_out": n_out}
        if weight_init is not None:
            kw["weight_init"] = weight_init
        self._layers[i] = (conf.replace(**kw), False)
        if i + 1 < len(self._layers):
            nxt, _ = self._layers[i + 1]
            self._layers[i + 1] = (nxt.replace(n_in=None), False)
        return self

    def build(self) -> MultiLayerNetwork:
        gc = self._net.conf.global_conf
        if self._fine_tune is not None:
            gc = self._fine_tune.apply_to(gc)
        confs = []
        for i, (conf, keep) in enumerate(self._layers):
            if self._freeze_until is not None and i <= self._freeze_until:
                conf = Frozen(inner=conf, name=conf.name)
            confs.append(conf)
        new_conf = MultiLayerConfiguration(
            global_conf=gc,
            layers=tuple(confs),
            input_type=self._input_type,
            backprop_type=self._net.conf.backprop_type,
            tbptt_fwd_length=self._net.conf.tbptt_fwd_length,
            tbptt_bwd_length=self._net.conf.tbptt_bwd_length,
            preprocessors=dict(self._net.conf.preprocessors),
        )
        new_net = MultiLayerNetwork(new_conf).init()
        # copy weights for retained layers (by name)
        for i, (conf, keep) in enumerate(self._layers):
            if not keep:
                continue
            name = conf.name
            if name in self._net.params and name in new_net.params:
                new_net.params[name] = jax.tree_util.tree_map(
                    jnp.copy, self._net.params[name])
            if name in (self._net.state or {}) and name in (new_net.state or {}):
                new_net.state[name] = jax.tree_util.tree_map(
                    jnp.copy, self._net.state[name])
        return new_net


class TransferLearning:
    Builder = TransferLearningBuilder


class TransferLearningHelper:
    """Featurize inputs through the frozen prefix so the unfrozen tail can
    be trained on cached features (TransferLearningHelper.java parity)."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: int | str):
        self.net = net
        if isinstance(frozen_until, str):
            names = [l.name for l in net.layers]
            frozen_until = names.index(frozen_until)
        self.frozen_until = frozen_until

    def featurize(self, ds: DataSet) -> DataSet:
        x = jnp.asarray(ds.features)
        fmask = (None if ds.features_mask is None
                 else jnp.asarray(ds.features_mask))
        h, _ = self.net._forward(self.net.params, self.net.state, x,
                                 train=False, rng=None, fmask=fmask,
                                 to_layer=self.frozen_until + 1)
        return DataSet(h, ds.labels, ds.features_mask, ds.labels_mask)

    def unfrozen_net(self) -> MultiLayerNetwork:
        """A tail network (layers after the frozen boundary) sharing this
        net's configs, with weights copied in. The boundary layer's
        preprocessor (explicit or auto-inserted) moves into the tail so
        featurized activations feed it exactly as in the full net."""
        start = self.frozen_until + 1
        confs = self.net._resolved_confs[start:]
        preprocessors = {
            i - start: p
            for i, p in enumerate(self.net.preprocessors)
            if i >= start and p is not None
        }
        tail_conf = MultiLayerConfiguration(
            global_conf=self.net.conf.global_conf,
            layers=tuple(confs),
            preprocessors=preprocessors,
        )
        tail = MultiLayerNetwork(tail_conf).init()
        for c in confs:
            if c.name in self.net.params:
                tail.params[c.name] = jax.tree_util.tree_map(
                    jnp.copy, self.net.params[c.name])
        return tail

    def copy_back(self, tail: MultiLayerNetwork):
        """Write a trained tail's weights back into the full net."""
        for name, p in tail.params.items():
            self.net.params[name] = jax.tree_util.tree_map(jnp.copy, p)
        return self.net
