"""Neural-network core (the TPU-native equivalent of deeplearning4j-nn)."""
