"""Recurrent-family layer configs.

Parity: nn/conf/layers/{GravesLSTM, GravesBidirectionalLSTM,
BaseRecurrentLayer, RnnOutputLayer}.java (SURVEY.md §2.1/2.2). Layout is
[batch, time, features] (the reference is [batch, features, time]); the
per-timestep Java hot loop (LSTMHelpers.activateHelper :57, :76) becomes a
``lax.scan`` compiled into the single XLA train step.
"""

from __future__ import annotations

from dataclasses import dataclass

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    FeedForwardLayerConfig,
    register_layer,
)


@dataclass(frozen=True)
class BaseRecurrentConfig(FeedForwardLayerConfig):
    layer_type = "base_recurrent"
    expects_rnn_input = True

    def with_n_in(self, input_type: InputType):
        if self.n_in is None:
            if input_type.kind != "recurrent":
                raise ValueError(
                    f"{type(self).__name__} needs recurrent input, got "
                    f"{input_type.kind}")
            return self.replace(n_in=input_type.size)
        return self

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(
            self.n_out, None if input_type is None else input_type.timesteps)


@register_layer
@dataclass(frozen=True)
class GravesLSTM(BaseRecurrentConfig):
    """Graves-style LSTM with peephole connections
    (GravesLSTM.java + LSTMHelpers.java parity). ``gate_activation`` is the
    reference's sigmoid gates; ``activation`` applies to the cell candidate
    and cell output (default tanh)."""

    layer_type = "graves_lstm"
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTMLayer
        return GravesLSTMLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class GravesBidirectionalLSTM(BaseRecurrentConfig):
    """Bidirectional Graves LSTM; forward and backward passes are SUMMED
    (GravesBidirectionalLSTM.java:206 ``totalOutput = fwdOutput.addi(
    backOutput)``), so the output size is n_out."""

    layer_type = "graves_bi_lstm"
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.recurrent import (
            GravesBidirectionalLSTMLayer)
        return GravesBidirectionalLSTMLayer(self, input_type, global_conf,
                                            policy)


@register_layer
@dataclass(frozen=True)
class RnnOutput(BaseRecurrentConfig):
    """Per-timestep dense + loss head (RnnOutputLayer.java parity): input
    [b, t, n_in] -> scores [b, t, n_out]; the loss averages over unmasked
    timesteps via the label mask."""

    layer_type = "rnn_output"
    loss: str = "mcxent"
    has_bias: bool = True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayerImpl
        return RnnOutputLayerImpl(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class LastTimeStep(BaseRecurrentConfig):
    """Wrapper-free equivalent of the reference's LastTimeStepVertex for
    sequential nets: [b, t, f] -> [b, f], mask-aware (takes the last
    unmasked step per example)."""

    layer_type = "last_time_step"

    def with_n_in(self, input_type: InputType):
        if self.n_in is None and input_type.kind == "recurrent":
            return self.replace(n_in=input_type.size, n_out=input_type.size)
        return self

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.recurrent import LastTimeStepLayer
        return LastTimeStepLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class TimeDistributedDense(BaseRecurrentConfig):
    """Per-timestep dense WITHOUT a loss head: [b, t, n_in] ->
    [b, t, n_out]. The reference maps Keras' TimeDistributed(Dense) /
    TimeDistributedDense onto DenseLayer behind shape preprocessors
    (KerasLayer.java:206-212); here it is a first-class layer so the time
    axis never round-trips through a flatten."""

    layer_type = "time_distributed_dense"
    has_bias: bool = True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.recurrent import (
            TimeDistributedDenseLayer)
        return TimeDistributedDenseLayer(self, input_type, global_conf,
                                         policy)
