"""Layer configuration classes.

Parity target: the reference's 28 config classes in
deeplearning4j-nn/.../nn/conf/layers/ (SURVEY.md §2.1). Each config is a
frozen dataclass registered by ``layer_type`` (for JSON round-trip) and knows
how to (a) infer its n_in from an InputType, (b) compute its output
InputType, and (c) instantiate its runtime layer.

TPU-native notes: conv/pool layers run NHWC (TPU-preferred layout; the
reference is NCHW — handled at the API boundary, not in the kernels);
recurrent layers run [batch, time, features] and lower to lax.scan.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.updater import Updater, updater_from_dict

LAYER_REGISTRY: dict[str, type] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.layer_type] = cls
    return cls


def _encode(v):
    if isinstance(v, BaseLayerConfig):
        return layer_to_dict(v)
    if hasattr(v, "to_dict"):  # Updater, ReconstructionDistribution, ...
        return v.to_dict()
    if isinstance(v, tuple):
        return [_encode(x) for x in v]
    return v


def layer_to_dict(layer: "BaseLayerConfig") -> dict:
    d = {}
    for f in dataclasses.fields(layer):
        v = getattr(layer, f.name)
        if v is None:
            continue
        d[f.name] = _encode(v)
    d["layer_type"] = layer.layer_type
    return d


def layer_from_dict(d: dict) -> "BaseLayerConfig":
    d = dict(d)
    ltype = d.pop("layer_type")
    cls = LAYER_REGISTRY[ltype]
    if "updater" in d and isinstance(d["updater"], dict):
        d["updater"] = updater_from_dict(d["updater"])
    if hasattr(cls, "_decode_fields"):  # nested configs (VAE, Frozen, ...)
        d = cls._decode_fields(d)
    fields = {f.name for f in dataclasses.fields(cls)}
    # tuple-valued fields arrive as lists from JSON
    for k, v in list(d.items()):
        if isinstance(v, list) and k in fields:
            d[k] = tuple(v)
    return cls(**{k: v for k, v in d.items() if k in fields})


@dataclass(frozen=True)
class BaseLayerConfig:
    """Common per-layer hyperparameters. ``None`` means "inherit from the
    global NeuralNetConfiguration" (mirroring the reference's
    Layer/NeuralNetConfiguration override semantics)."""

    layer_type = "base"

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[Any] = None     # str name or distribution dict
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[float] = None       # drop probability (0 disables).
    updater: Optional[Updater] = None     # per-layer optimizer override
    learning_rate: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    # -- shape inference ---------------------------------------------------
    def with_n_in(self, input_type: InputType) -> "BaseLayerConfig":
        """Return a copy with n_in (etc.) inferred from the input type."""
        return self

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    # -- runtime -----------------------------------------------------------
    def make_layer(self, input_type: InputType, global_conf, policy):
        raise NotImplementedError

    def has_params(self) -> bool:
        return False

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class FeedForwardLayerConfig(BaseLayerConfig):
    """Base for layers with (n_in, n_out) dense-style params
    (FeedForwardLayer.java parity)."""

    layer_type = "feed_forward"
    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def with_n_in(self, input_type: InputType) -> "FeedForwardLayerConfig":
        if self.n_in is None:
            return self.replace(n_in=input_type.flat_size())
        return self

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def has_params(self) -> bool:
        return True


@register_layer
@dataclass(frozen=True)
class Dense(FeedForwardLayerConfig):
    """Fully connected layer (DenseLayer.java parity)."""

    layer_type = "dense"
    has_bias: bool = True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
        return DenseLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class Output(FeedForwardLayerConfig):
    """Dense + loss head (OutputLayer.java parity). ``loss`` names an
    ops.losses entry; the loss gradient flows via autodiff rather than the
    reference's ILossFunction.computeGradient."""

    layer_type = "output"
    loss: str = "mcxent"
    has_bias: bool = True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
        return OutputLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class LossLayer(BaseLayerConfig):
    """Loss-only head without params (LossLayer.java parity)."""

    layer_type = "loss"
    loss: str = "mcxent"

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.feedforward import LossOnlyLayer
        return LossOnlyLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class ActivationLayer(BaseLayerConfig):
    """Standalone activation (ActivationLayer.java parity)."""

    layer_type = "activation"

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.feedforward import ActivationOnlyLayer
        return ActivationOnlyLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class Dropout(BaseLayerConfig):
    """Standalone dropout layer (DropoutLayer.java parity). The per-layer
    ``dropout`` field on other layers applies dropout to their *input*,
    mirroring the reference's conf.dropOut semantics."""

    layer_type = "dropout"

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.feedforward import DropoutOnlyLayer
        return DropoutOnlyLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class Embedding(FeedForwardLayerConfig):
    """Integer-index embedding lookup (EmbeddingLayer.java parity — the
    reference implements it as a one-hot mmul shortcut; on TPU it is a
    jnp.take gather, which XLA lowers to an efficient dynamic-gather)."""

    layer_type = "embedding"
    has_bias: bool = True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.feedforward import EmbeddingLayerImpl
        return EmbeddingLayerImpl(self, input_type, global_conf, policy)
