"""Pretrain-family + special-output layer configs: VAE, AutoEncoder, RBM,
CenterLossOutput, Frozen.

Parity: nn/conf/layers/{variational/VariationalAutoencoder, AutoEncoder,
RBM, CenterLossOutputLayer}.java and nn/layers/FrozenLayer.java
(SURVEY.md §2.1/2.2). Reconstruction distributions mirror
nn/conf/layers/variational/{BernoulliReconstructionDistribution,
GaussianReconstructionDistribution, ExponentialReconstructionDistribution,
CompositeReconstructionDistribution, LossFunctionWrapper}.java.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayerConfig,
    FeedForwardLayerConfig,
    LAYER_REGISTRY,
    layer_from_dict,
    layer_to_dict,
    register_layer,
)


# ---------------------------------------------------------------------------
# Reconstruction distributions (pure specs; math lives in layers/variational)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ReconstructionDistribution:
    kind = "base"

    def to_dict(self):
        import dataclasses as dc
        d = {}
        for f in dc.fields(self):
            v = getattr(self, f.name)
            if f.name == "distributions":
                v = [[n, inner.to_dict()] for n, inner in v]
            d[f.name] = v
        d["kind"] = self.kind
        return d

    def param_size(self, data_size: int) -> int:
        raise NotImplementedError


_DISTRIBUTIONS: dict[str, type] = {}


def register_distribution(cls):
    _DISTRIBUTIONS[cls.kind] = cls
    return cls


def distribution_from_dict(d: dict) -> ReconstructionDistribution:
    d = dict(d)
    kind = d.pop("kind")
    if kind == "composite":
        d["distributions"] = tuple(
            (n, distribution_from_dict(inner))
            for n, inner in d.get("distributions", ()))
    cls = _DISTRIBUTIONS[kind]
    import dataclasses as dc
    names = {f.name for f in dc.fields(cls)}
    for k, v in list(d.items()):
        if isinstance(v, list) and k in names and k != "distributions":
            d[k] = tuple(v)
    return cls(**{k: v for k, v in d.items() if k in names})


@register_distribution
@dataclass(frozen=True)
class BernoulliReconstruction(ReconstructionDistribution):
    """p(x|z) Bernoulli with sigmoid'd logits
    (BernoulliReconstructionDistribution.java)."""

    kind = "bernoulli"

    def param_size(self, data_size: int) -> int:
        return data_size


@register_distribution
@dataclass(frozen=True)
class GaussianReconstruction(ReconstructionDistribution):
    """p(x|z) diagonal Gaussian: head emits [mean, log var]
    (GaussianReconstructionDistribution.java)."""

    kind = "gaussian"
    activation: str = "identity"

    def param_size(self, data_size: int) -> int:
        return 2 * data_size


@register_distribution
@dataclass(frozen=True)
class ExponentialReconstruction(ReconstructionDistribution):
    """p(x|z) exponential, head emits gamma = log(lambda)
    (ExponentialReconstructionDistribution.java)."""

    kind = "exponential"

    def param_size(self, data_size: int) -> int:
        return data_size


@register_distribution
@dataclass(frozen=True)
class LossWrapperReconstruction(ReconstructionDistribution):
    """-log p(x|z) := a standard loss (LossFunctionWrapper.java)."""

    kind = "loss_wrapper"
    loss: str = "mse"
    activation: str = "identity"

    def param_size(self, data_size: int) -> int:
        return data_size


@register_distribution
@dataclass(frozen=True)
class CompositeReconstruction(ReconstructionDistribution):
    """Different distributions over feature ranges
    (CompositeReconstructionDistribution.java): tuple of
    (num_features, distribution)."""

    kind = "composite"
    distributions: Tuple = ()

    def param_size(self, data_size: int) -> int:
        assert sum(n for n, _ in self.distributions) == data_size, (
            "Composite distribution sizes must sum to the data size")
        return sum(d.param_size(n) for n, d in self.distributions)


# ---------------------------------------------------------------------------
# Layer configs
# ---------------------------------------------------------------------------

@register_layer
@dataclass(frozen=True)
class VariationalAutoencoder(FeedForwardLayerConfig):
    """VAE as ONE layer: encoder/decoder MLPs + reparameterization + ELBO
    (nn/layers/variational/VariationalAutoencoder.java, 1,095 LoC parity).
    n_out = latent size. Supervised ``activate`` emits the posterior mean
    (matching the reference). Pretrains on unlabeled features via
    MultiLayerNetwork.pretrain()."""

    layer_type = "vae"
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    reconstruction: ReconstructionDistribution = field(
        default_factory=BernoulliReconstruction)
    num_samples: int = 1

    @classmethod
    def _decode_fields(cls, d):
        if isinstance(d.get("reconstruction"), dict):
            d["reconstruction"] = distribution_from_dict(d["reconstruction"])
        return d

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.variational import VAELayer
        return VAELayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class AutoEncoder(FeedForwardLayerConfig):
    """Denoising autoencoder (nn/layers/feedforward/autoencoder/
    AutoEncoder.java parity): corruption_level masks inputs during pretrain;
    supervised activate = encoder forward."""

    layer_type = "autoencoder"
    corruption_level: float = 0.3
    loss: str = "mse"

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.pretrain import AutoEncoderLayer
        return AutoEncoderLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class RBM(FeedForwardLayerConfig):
    """Restricted Boltzmann machine (nn/layers/feedforward/rbm/RBM.java
    parity, legacy): CD-k pretraining, sigmoid propup as activate."""

    layer_type = "rbm"
    k: int = 1  # contrastive divergence steps

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.pretrain import RBMLayer
        return RBMLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class CenterLossOutput(FeedForwardLayerConfig):
    """Softmax classification + center loss
    (nn/layers/training/CenterLossOutputLayer.java parity):
    loss = dataLoss + lambda/2 * ||f - c_y||^2; class centers live in layer
    state and track features with an ``alpha`` moving average."""

    layer_type = "center_loss_output"
    loss: str = "mcxent"
    alpha: float = 0.05
    lmbda: float = 2e-4
    has_bias: bool = True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.pretrain import CenterLossOutputLayer
        return CenterLossOutputLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class Frozen(BaseLayerConfig):
    """Freeze a wrapped layer (FrozenLayer.java parity): forward passes
    through; parameters get zero updates and no regularization."""

    layer_type = "frozen"
    inner: Optional[BaseLayerConfig] = None

    def with_n_in(self, input_type):
        return self.replace(inner=self.inner.with_n_in(input_type))

    def get_output_type(self, input_type):
        return self.inner.get_output_type(input_type)

    def has_params(self) -> bool:
        return self.inner.has_params()

    def replace(self, **kw):
        # keep the wrapper's name in sync with the inner layer's
        import dataclasses
        if "name" in kw and self.inner is not None:
            inner = dataclasses.replace(self.inner, name=kw["name"])
            kw = dict(kw, inner=inner)
        return dataclasses.replace(self, **kw)

    @classmethod
    def _decode_fields(cls, d):
        if isinstance(d.get("inner"), dict):
            d["inner"] = layer_from_dict(d["inner"])
        return d

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.pretrain import FrozenLayerWrapper
        return FrozenLayerWrapper(self, input_type, global_conf, policy)
