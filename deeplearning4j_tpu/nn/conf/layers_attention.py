"""Transformer-family layer configs (ROADMAP item 1 — the workload class
the reference never had: no attention exists anywhere in its 28 config
classes, PAPER.md §0).

Layout follows the recurrent family: [batch, time, features], streaming
state carried per layer under the same ``rnn_time_step`` contract that
GravesLSTM uses for (h, c) — here the carries are the KV cache
("k"/"v") and each row's absolute position ("pos"). ``max_cache_len``
fixes the cache extent at prefill: the decode bit-identity contract
(ops/attention.py docstring) requires prefill and every decode step to
attend at the SAME kv length, so the cache is allocated once and never
grows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import register_layer
from deeplearning4j_tpu.nn.conf.layers_recurrent import (
    BaseRecurrentConfig,
    RnnOutput,
)


@register_layer
@dataclass(frozen=True)
class GptEmbedding(BaseRecurrentConfig):
    """Token + learned positional embedding: one-hot [b, t, vocab] ->
    [b, t, n_out]. The token lookup is a gather (argmax over the one-hot,
    EmbeddingLayer.java's mmul-shortcut rendered TPU-native); the
    positional table is learned, ``max_len`` rows. Streaming carries
    "pos" so decode steps index the positional table at each row's true
    absolute offset."""

    layer_type = "gpt_embedding"
    max_len: int = 512

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.attention import GptEmbeddingLayer
        return GptEmbeddingLayer(self, input_type, global_conf, policy)


@dataclass(frozen=True)
class BaseAttentionConfig(BaseRecurrentConfig):
    """Shared shape inference for width-preserving attention layers:
    n_out defaults to n_in (residual streams keep the model width)."""

    layer_type = "base_attention"
    n_heads: int = 4
    max_cache_len: Optional[int] = None

    def with_n_in(self, input_type: InputType):
        c = super().with_n_in(input_type)
        if c.n_out is None:
            c = c.replace(n_out=c.n_in)
        return c


@register_layer
@dataclass(frozen=True)
class SelfAttention(BaseAttentionConfig):
    """Causal multi-head self-attention: QKV projections (column-parallel
    under tp_rules), the ``causal_mha`` registry op, and the output
    projection (row-parallel). No residual/norm — compose those
    explicitly, or use ``TransformerBlock`` for the standard pre-LN
    block."""

    layer_type = "self_attention"

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
        return SelfAttentionLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class TransformerBlock(BaseAttentionConfig):
    """Pre-LN transformer block (the GPT-2 arrangement):
    ``x + attn(ln1(x))`` then ``a + mlp(ln2(a))`` with an
    ``ffn_mult * width`` hidden MLP. ``activation`` (default gelu) is the
    MLP nonlinearity; LayerNorm runs in f32 under any compute policy."""

    layer_type = "transformer_block"
    ffn_mult: int = 4
    ln_eps: float = 1e-5

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.attention import (
            TransformerBlockLayer)
        return TransformerBlockLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class GptOutput(RnnOutput):
    """RnnOutput whose streaming preout uses the decode-stable exact
    lowering (see nn/layers/attention.py docstring) — the head GPT models
    must terminate in for the decode bit-identity contract to reach the
    logits."""

    layer_type = "gpt_output"

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.attention import GptOutputLayer
        return GptOutputLayer(self, input_type, global_conf, policy)
