"""ComputationGraph configuration — a DAG of layer and op vertices.

Parity: nn/conf/ComputationGraphConfiguration.java (730 LoC; GraphBuilder)
in the reference. Pure data with JSON round-trip; topological validation at
build time (the reference sorts at ComputationGraph.init :888 — here the
sort lives on the config so both the runtime and importers can use it).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu.nn.conf.core import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayerConfig,
    layer_from_dict,
    layer_to_dict,
)
from deeplearning4j_tpu.nn.conf.vertices import (
    GraphVertexConfig,
    vertex_from_dict,
    vertex_to_dict,
)


@dataclass(frozen=True)
class ComputationGraphConfiguration:
    global_conf: NeuralNetConfiguration
    vertices: Dict[str, object]            # name -> layer conf | vertex conf
    vertex_inputs: Dict[str, Tuple[str, ...]]
    network_inputs: Tuple[str, ...]
    network_outputs: Tuple[str, ...]
    input_types: Optional[Tuple[InputType, ...]] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20

    def __post_init__(self):
        self.topological_order()  # validates the DAG (raises on cycle)
        for name, inputs in self.vertex_inputs.items():
            for i in inputs:
                if i not in self.vertices and i not in self.network_inputs:
                    raise ValueError(
                        f"Vertex '{name}' references unknown input '{i}'")
        for o in self.network_outputs:
            if o not in self.vertices:
                raise ValueError(f"Unknown network output '{o}'")

    def topological_order(self) -> list:
        """Kahn's algorithm over vertex names
        (ComputationGraph.topologicalSortOrder :888 parity)."""
        indeg = {}
        dependents: Dict[str, list] = {}
        for name, inputs in self.vertex_inputs.items():
            real = [i for i in inputs if i in self.vertices]
            indeg[name] = len(real)
            for i in real:
                dependents.setdefault(i, []).append(name)
        queue = sorted([n for n, d in indeg.items() if d == 0])
        order = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for dep in dependents.get(n, []):
                indeg[dep] -= 1
                if indeg[dep] == 0:
                    queue.append(dep)
        if len(order) != len(self.vertices):
            cyclic = sorted(set(self.vertices) - set(order))
            raise ValueError(f"Graph has a cycle involving: {cyclic}")
        return order

    # ------------------------------------------------------------------ json
    def to_json(self) -> str:
        verts = {}
        for name, conf in self.vertices.items():
            if isinstance(conf, BaseLayerConfig):
                verts[name] = {"kind": "layer", "conf": layer_to_dict(conf)}
            else:
                verts[name] = {"kind": "vertex", "conf": vertex_to_dict(conf)}
        return json.dumps({
            "format_version": 1,
            "model_kind": "computation_graph",
            "global_conf": self.global_conf.to_dict(),
            "vertices": verts,
            "vertex_inputs": {k: list(v) for k, v in self.vertex_inputs.items()},
            "network_inputs": list(self.network_inputs),
            "network_outputs": list(self.network_outputs),
            "input_types": (None if self.input_types is None else
                            [it.to_dict() for it in self.input_types]),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_bwd_length": self.tbptt_bwd_length,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        vertices = {}
        for name, spec in d["vertices"].items():
            if spec["kind"] == "layer":
                vertices[name] = layer_from_dict(spec["conf"])
            else:
                vertices[name] = vertex_from_dict(spec["conf"])
        return ComputationGraphConfiguration(
            global_conf=NeuralNetConfiguration.from_dict(d["global_conf"]),
            vertices=vertices,
            vertex_inputs={k: tuple(v) for k, v in d["vertex_inputs"].items()},
            network_inputs=tuple(d["network_inputs"]),
            network_outputs=tuple(d["network_outputs"]),
            input_types=(None if d.get("input_types") is None else tuple(
                InputType.from_dict(it) for it in d["input_types"])),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_bwd_length=d.get("tbptt_bwd_length", 20),
        )


class GraphBuilder:
    """Fluent DAG builder (ComputationGraphConfiguration.GraphBuilder
    parity): addInputs -> addLayer/addVertex -> setOutputs -> build."""

    def __init__(self, global_conf: NeuralNetConfiguration):
        self._conf = global_conf
        self._vertices: Dict[str, object] = {}
        self._inputs: Dict[str, Tuple[str, ...]] = {}
        self._network_inputs: Tuple[str, ...] = ()
        self._network_outputs: Tuple[str, ...] = ()
        self._input_types = None
        self._backprop_type = "standard"
        self._tbptt = (20, 20)

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._network_inputs = self._network_inputs + tuple(names)
        return self

    def _add(self, name, conf, inputs):
        if name in self._vertices or name in self._network_inputs:
            raise ValueError(f"Duplicate vertex name '{name}'")
        if not inputs:
            raise ValueError(f"Vertex '{name}' needs at least one input")
        self._vertices[name] = conf
        self._inputs[name] = tuple(inputs)
        return self

    def add_layer(self, name: str, layer_conf: BaseLayerConfig,
                  *inputs: str) -> "GraphBuilder":
        return self._add(name, layer_conf.replace(name=name), inputs)

    def add_vertex(self, name: str, vertex_conf: GraphVertexConfig,
                   *inputs: str) -> "GraphBuilder":
        return self._add(name, vertex_conf, inputs)

    def get_vertex(self, name: str):
        """The layer/vertex config registered under ``name`` (or None) —
        used by importers to inspect partially-built graphs."""
        return self._vertices.get(name)

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._network_outputs = tuple(names)
        return self

    def set_input_types(self, *input_types: InputType) -> "GraphBuilder":
        self._input_types = tuple(input_types)
        return self

    def backprop_type(self, kind: str, tbptt_fwd: int = 20,
                      tbptt_bwd: int = 20) -> "GraphBuilder":
        self._backprop_type = kind
        self._tbptt = (tbptt_fwd, tbptt_bwd)
        return self

    def build(self) -> ComputationGraphConfiguration:
        return ComputationGraphConfiguration(
            global_conf=self._conf,
            vertices=dict(self._vertices),
            vertex_inputs=dict(self._inputs),
            network_inputs=self._network_inputs,
            network_outputs=self._network_outputs,
            input_types=self._input_types,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt[0],
            tbptt_bwd_length=self._tbptt[1],
        )
