"""InputType — shape metadata used for n_in inference and preprocessor
insertion (parity: nn/conf/inputs/InputType.java in the reference).

Kinds:
- feed_forward(size)
- recurrent(size, timesteps=None)           # [batch, time, size] on TPU
- convolutional(height, width, channels)    # stored HWC; runtime is NHWC
- convolutional_flat(height, width, channels)
"""

from __future__ import annotations

from dataclasses import dataclass, asdict


@dataclass(frozen=True)
class InputType:
    kind: str
    size: int | None = None
    timesteps: int | None = None
    height: int | None = None
    width: int | None = None
    channels: int | None = None

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feed_forward", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: int | None = None) -> "InputType":
        return InputType(kind="recurrent", size=int(size),
                         timesteps=None if timesteps is None else int(timesteps))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=int(height),
                         width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional_flat", height=int(height),
                         width=int(width), channels=int(channels),
                         size=int(height) * int(width) * int(channels))

    def flat_size(self) -> int:
        if self.kind in ("feed_forward", "recurrent"):
            return self.size
        return self.height * self.width * self.channels

    def to_dict(self):
        return {k: v for k, v in asdict(self).items() if v is not None}

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)
