"""Configuration DSL (the TPU-native equivalent of nn/conf in the reference:
NeuralNetConfiguration.java, MultiLayerConfiguration.java and the 28 layer
config classes — SURVEY.md §2.1). Configs are pure data with JSON round-trip."""

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.core import (
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ListBuilder,
)
from deeplearning4j_tpu.nn.conf import layers
from deeplearning4j_tpu.nn.conf import layers_conv
from deeplearning4j_tpu.nn.conf import layers_recurrent
from deeplearning4j_tpu.nn.conf import layers_attention
from deeplearning4j_tpu.nn.conf import layers_pretrain
