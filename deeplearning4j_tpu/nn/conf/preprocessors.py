"""Input preprocessors — pure shape/layout adapters between layer families.

Parity: nn/conf/preprocessor/ in the reference (CnnToFeedForward,
FeedForwardToCnn, CnnToRnn, RnnToCnn, FeedForwardToRnn, RnnToFeedForward —
SURVEY.md §2.3). In the reference these carry hand-written backprop; here
they are pure jnp reshapes, so autodiff derives the backward pass.

Layout note (TPU-native): convolutional tensors are NHWC (the reference is
NCHW); recurrent tensors are [batch, time, features] (the reference is
[batch, features, time]). The preprocessors below speak the TPU layouts.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

PREPROCESSOR_REGISTRY: dict[str, type] = {}


def register_preprocessor(cls):
    PREPROCESSOR_REGISTRY[cls.kind] = cls
    return cls


def preprocessor_to_dict(p):
    d = {k: v for k, v in p.__dict__.items()} if not hasattr(p, "__dataclass_fields__") else {
        f: getattr(p, f) for f in p.__dataclass_fields__}
    d["kind"] = p.kind
    return d


def preprocessor_from_dict(d):
    d = dict(d)
    kind = d.pop("kind")
    return PREPROCESSOR_REGISTRY[kind](**d)


@dataclass(frozen=True)
class InputPreProcessor:
    kind = "identity"

    def __call__(self, x):
        return x

    def output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_preprocessor
@dataclass(frozen=True)
class CnnToFeedForward(InputPreProcessor):
    """[b, h, w, c] -> [b, h*w*c] (CnnToFeedForwardPreProcessor.java parity)."""

    kind = "cnn_to_ff"
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(
            input_type.height * input_type.width * input_type.channels)


@register_preprocessor
@dataclass(frozen=True)
class FeedForwardToCnn(InputPreProcessor):
    """[b, h*w*c] -> [b, h, w, c] (FeedForwardToCnnPreProcessor.java parity)."""

    kind = "ff_to_cnn"
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclass(frozen=True)
class RnnToFeedForward(InputPreProcessor):
    """[b, t, f] -> [b*t, f] (RnnToFeedForwardPreProcessor.java parity)."""

    kind = "rnn_to_ff"

    def __call__(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)


@register_preprocessor
@dataclass(frozen=True)
class FeedForwardToRnn(InputPreProcessor):
    """[b*t, f] -> [b, t, f]; needs the time length at call time, so it takes
    it from the configured ``timesteps`` (FeedForwardToRnnPreProcessor.java
    parity)."""

    kind = "ff_to_rnn"
    timesteps: int = 0

    def __call__(self, x):
        return x.reshape(-1, self.timesteps, x.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size(), self.timesteps)


@register_preprocessor
@dataclass(frozen=True)
class CnnToRnn(InputPreProcessor):
    """[b, h, w, c] -> [b, t, h*w*c/t]? — the reference treats each example's
    flattened CNN activations as one timestep per batch entry is NOT what it
    does; it maps [b*t, h, w, c] -> [b, t, h*w*c]. We mirror that."""

    kind = "cnn_to_rnn"
    timesteps: int = 0

    def __call__(self, x):
        flat = x.reshape(x.shape[0], -1)
        return flat.reshape(-1, self.timesteps, flat.shape[-1])

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(
            input_type.height * input_type.width * input_type.channels,
            self.timesteps)


@register_preprocessor
@dataclass(frozen=True)
class RnnToCnn(InputPreProcessor):
    """[b, t, h*w*c] -> [b*t, h, w, c] (RnnToCnnPreProcessor.java parity)."""

    kind = "rnn_to_cnn"
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x):
        return x.reshape(-1, self.height, self.width, self.channels)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)
