"""Graph vertex configs — the complete DAG vertex algebra.

Parity: nn/conf/graph/ + nn/graph/vertex/impl/ in the reference
(ElementWiseVertex, L2NormalizeVertex, L2Vertex, LayerVertex, MergeVertex,
PreprocessorVertex, ScaleVertex, StackVertex, SubsetVertex, UnstackVertex,
rnn/DuplicateToTimeSeriesVertex, rnn/LastTimeStepVertex — SURVEY.md §2.3).

Each vertex is a frozen dataclass with JSON round-trip that knows its output
InputType and its forward computation (backward is autodiff). Layouts:
feed-forward [b, f], recurrent [b, t, f], convolutional NHWC — merge/subset
operate on the trailing (feature/channel) axis in all three.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

VERTEX_REGISTRY: dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.vertex_type] = cls
    return cls


def vertex_to_dict(v) -> dict:
    from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_to_dict
    d = {}
    for f in dataclasses.fields(v):
        val = getattr(v, f.name)
        if val is None:
            continue
        if f.name == "preprocessor":
            val = preprocessor_to_dict(val)
        elif isinstance(val, tuple):
            val = list(val)
        d[f.name] = val
    d["vertex_type"] = v.vertex_type
    return d


def vertex_from_dict(d: dict):
    from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict
    d = dict(d)
    vtype = d.pop("vertex_type")
    cls = VERTEX_REGISTRY[vtype]
    fields = {f.name for f in dataclasses.fields(cls)}
    if isinstance(d.get("preprocessor"), dict):
        d["preprocessor"] = preprocessor_from_dict(d["preprocessor"])
    for k, v in list(d.items()):
        if isinstance(v, list) and k in fields:
            d[k] = tuple(v)
    return cls(**{k: v for k, v in d.items() if k in fields})


@dataclass(frozen=True)
class GraphVertexConfig:
    """Base for parameter-free combining vertices. ``forward(*inputs,
    masks=...)`` computes the op; ``output_type(*input_types)`` infers
    shapes."""

    vertex_type = "base"

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def forward(self, *inputs, masks=None):
        raise NotImplementedError

    def feed_forward_mask(self, *masks):
        """Combine/propagate per-timestep masks (default: first non-None)."""
        for m in masks:
            if m is not None:
                return m
        return None


@register_vertex
@dataclass(frozen=True)
class MergeVertex(GraphVertexConfig):
    """Concatenate along the feature/channel (trailing) axis
    (MergeVertex.java parity)."""

    vertex_type = "merge"

    def output_type(self, *its: InputType) -> InputType:
        first = its[0]
        if first.kind == "convolutional":
            return InputType.convolutional(
                first.height, first.width, sum(it.channels for it in its))
        if first.kind == "recurrent":
            return InputType.recurrent(sum(it.size for it in its),
                                       first.timesteps)
        return InputType.feed_forward(sum(it.flat_size() for it in its))

    def forward(self, *inputs, masks=None):
        return jnp.concatenate(inputs, axis=-1)


@register_vertex
@dataclass(frozen=True)
class ElementWiseVertex(GraphVertexConfig):
    """Pointwise combine: add / subtract (2 inputs) / product / average /
    max (ElementWiseVertex.java parity)."""

    vertex_type = "element_wise"
    op: str = "add"

    def forward(self, *inputs, masks=None):
        if self.op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if self.op == "subtract":
            if len(inputs) != 2:
                raise ValueError("ElementWiseVertex subtract needs exactly 2 "
                                 "inputs (reference restriction)")
            return inputs[0] - inputs[1]
        if self.op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if self.op == "average":
            return sum(inputs) / len(inputs)
        if self.op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown ElementWise op {self.op}")


@register_vertex
@dataclass(frozen=True)
class ScaleVertex(GraphVertexConfig):
    """Multiply by a fixed scalar (ScaleVertex.java parity)."""

    vertex_type = "scale"
    factor: float = 1.0

    def forward(self, *inputs, masks=None):
        return inputs[0] * self.factor


@register_vertex
@dataclass(frozen=True)
class L2NormalizeVertex(GraphVertexConfig):
    """x / ||x||_2 per example over the trailing axes
    (L2NormalizeVertex.java parity)."""

    vertex_type = "l2_normalize"
    eps: float = 1e-8

    def forward(self, *inputs, masks=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / (norm + self.eps)


@register_vertex
@dataclass(frozen=True)
class L2Vertex(GraphVertexConfig):
    """Pairwise L2 distance between two inputs -> [b, 1]
    (L2Vertex.java parity)."""

    vertex_type = "l2"
    eps: float = 1e-8

    def output_type(self, *its: InputType) -> InputType:
        return InputType.feed_forward(1)

    def forward(self, *inputs, masks=None):
        a, b = inputs
        axes = tuple(range(1, a.ndim))
        d = jnp.sqrt(jnp.sum((a - b) ** 2, axis=axes) + self.eps)
        return d[:, None]


@register_vertex
@dataclass(frozen=True)
class StackVertex(GraphVertexConfig):
    """Concatenate along the batch (leading) axis (StackVertex.java)."""

    vertex_type = "stack"

    def forward(self, *inputs, masks=None):
        return jnp.concatenate(inputs, axis=0)

    def feed_forward_mask(self, *masks):
        if all(m is None for m in masks):
            return None
        if any(m is None for m in masks):
            raise ValueError(
                "StackVertex: either all or none of the stacked inputs must "
                "carry a mask (cannot synthesize a mask for an unmasked "
                "input without its time length)")
        return jnp.concatenate(masks, axis=0)


@register_vertex
@dataclass(frozen=True)
class UnstackVertex(GraphVertexConfig):
    """Take slice ``index`` of ``stack_size`` equal batch parts
    (UnstackVertex.java parity)."""

    vertex_type = "unstack"
    index: int = 0
    stack_size: int = 1

    def forward(self, *inputs, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.index * step:(self.index + 1) * step]

    def feed_forward_mask(self, *masks):
        m = masks[0]
        if m is None:
            return None
        step = m.shape[0] // self.stack_size
        return m[self.index * step:(self.index + 1) * step]


@register_vertex
@dataclass(frozen=True)
class SubsetVertex(GraphVertexConfig):
    """Feature range [from_index, to_index] inclusive on the trailing axis
    (SubsetVertex.java parity)."""

    vertex_type = "subset"
    from_index: int = 0
    to_index: int = 0

    def output_type(self, *its: InputType) -> InputType:
        n = self.to_index - self.from_index + 1
        it = its[0]
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timesteps)
        if it.kind == "convolutional":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feed_forward(n)

    def forward(self, *inputs, masks=None):
        return inputs[0][..., self.from_index:self.to_index + 1]


@register_vertex
@dataclass(frozen=True)
class LastTimeStepVertex(GraphVertexConfig):
    """[b, t, f] -> [b, f], last unmasked step using the mask of input
    ``mask_input`` (rnn/LastTimeStepVertex.java parity)."""

    vertex_type = "last_time_step"
    mask_input: Optional[str] = None

    def output_type(self, *its: InputType) -> InputType:
        return InputType.feed_forward(its[0].size)

    def forward(self, *inputs, masks=None):
        from deeplearning4j_tpu.ops.sequence import last_unmasked_step
        return last_unmasked_step(inputs[0], masks[0] if masks else None)

    def feed_forward_mask(self, *masks):
        return None


@register_vertex
@dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertexConfig):
    """[b, f] -> [b, t, f], tiled to the time length of input
    ``seq_input`` (rnn/DuplicateToTimeSeriesVertex.java parity). Takes two
    inputs: (vector, reference_sequence)."""

    vertex_type = "duplicate_to_time_series"
    seq_input: Optional[str] = None

    def output_type(self, *its: InputType) -> InputType:
        t = its[1].timesteps if len(its) > 1 else None
        return InputType.recurrent(its[0].flat_size(), t)

    def forward(self, *inputs, masks=None):
        x, seq = inputs[0], inputs[1]
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], seq.shape[1], x.shape[-1]))

    def feed_forward_mask(self, *masks):
        return masks[1] if len(masks) > 1 else None


@register_vertex
@dataclass(frozen=True)
class PreprocessorVertex(GraphVertexConfig):
    """Wrap an InputPreProcessor as a vertex (PreprocessorVertex.java)."""

    vertex_type = "preprocessor"
    preprocessor: object = None

    def output_type(self, *its: InputType) -> InputType:
        return self.preprocessor.output_type(its[0])

    def forward(self, *inputs, masks=None):
        return self.preprocessor(inputs[0])
