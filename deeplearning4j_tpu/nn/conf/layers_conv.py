"""Convolutional-family layer configs.

Parity: nn/conf/layers/{ConvolutionLayer, Convolution1DLayer,
SubsamplingLayer, Subsampling1DLayer, ZeroPaddingLayer, BatchNormalization,
LocalResponseNormalization, GlobalPoolingLayer, PoolingType}.java
(SURVEY.md §2.1). Conv/pool geometry follows the reference's
ConvolutionMode semantics (same/strict/truncate); layouts are NHWC
([batch, time, features] for the 1D variants).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayerConfig,
    FeedForwardLayerConfig,
    register_layer,
)
from deeplearning4j_tpu.ops.convolution import out_size
from deeplearning4j_tpu.ops.convolution import pair as _pair


@register_layer
@dataclass(frozen=True)
class Convolution2D(FeedForwardLayerConfig):
    """2D convolution (ConvolutionLayer.java parity; NHWC on TPU).

    n_in = input channels (inferred), n_out = output channels.
    ``mode`` is the ConvolutionMode: 'truncate' (reference default),
    'strict', or 'same'.
    """

    layer_type = "conv2d"
    expects_cnn_input = True

    kernel: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    mode: str = "truncate"
    has_bias: bool = True

    def with_n_in(self, input_type: InputType):
        if self.n_in is None:
            if input_type.kind not in ("convolutional", "convolutional_flat"):
                raise ValueError(
                    f"Convolution2D needs convolutional input, got "
                    f"{input_type.kind}")
            return self.replace(n_in=input_type.channels)
        return self

    def get_output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        h = out_size(input_type.height, kh, sh, ph, self.mode, dh)
        w = out_size(input_type.width, kw, sw, pw, self.mode, dw)
        return InputType.convolutional(h, w, self.n_out)

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
        return ConvolutionLayer(self, input_type, global_conf, policy)


# DL4J name alias
Convolution = Convolution2D


@register_layer
@dataclass(frozen=True)
class Convolution1D(FeedForwardLayerConfig):
    """1D convolution over [batch, time, features]
    (Convolution1DLayer.java parity — the reference runs [b, f, t])."""

    layer_type = "conv1d"
    expects_rnn_input = True

    kernel: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    mode: str = "truncate"
    has_bias: bool = True

    def with_n_in(self, input_type: InputType):
        if self.n_in is None:
            if input_type.kind != "recurrent":
                raise ValueError(
                    f"Convolution1D needs recurrent input, got {input_type.kind}")
            return self.replace(n_in=input_type.size)
        return self

    def get_output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        t_out = None if t is None else out_size(
            t, self.kernel, self.stride, self.padding, self.mode, self.dilation)
        return InputType.recurrent(self.n_out, t_out)

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.convolution import Convolution1DLayerImpl
        return Convolution1DLayerImpl(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class Subsampling(BaseLayerConfig):
    """2D pooling (SubsamplingLayer.java parity).
    ``pooling`` in {max, avg, pnorm}; ``pnorm`` is the p exponent."""

    layer_type = "subsampling"
    expects_cnn_input = True

    kernel: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    pooling: str = "max"
    pnorm: int = 2
    mode: str = "truncate"

    def get_output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        h = out_size(input_type.height, kh, sh, ph, self.mode)
        w = out_size(input_type.width, kw, sw, pw, self.mode)
        return InputType.convolutional(h, w, input_type.channels)

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.convolution import SubsamplingLayerImpl
        return SubsamplingLayerImpl(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class Subsampling1D(BaseLayerConfig):
    """1D pooling over [batch, time, features] (Subsampling1DLayer.java)."""

    layer_type = "subsampling1d"
    expects_rnn_input = True

    kernel: int = 2
    stride: int = 2
    padding: int = 0
    pooling: str = "max"
    pnorm: int = 2
    mode: str = "truncate"

    def get_output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        t_out = None if t is None else out_size(
            t, self.kernel, self.stride, self.padding, self.mode)
        return InputType.recurrent(input_type.size, t_out)

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.convolution import Subsampling1DLayerImpl
        return Subsampling1DLayerImpl(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class ZeroPadding(BaseLayerConfig):
    """Spatial zero padding (ZeroPaddingLayer.java parity);
    pad = (top, bottom, left, right)."""

    layer_type = "zero_padding"
    expects_cnn_input = True

    pad: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def get_output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self.pad
        return InputType.convolutional(
            input_type.height + t + b, input_type.width + l + r,
            input_type.channels)

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.convolution import ZeroPaddingLayerImpl
        return ZeroPaddingLayerImpl(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class BatchNorm(BaseLayerConfig):
    """Batch normalization (nn/conf/layers/BatchNormalization.java parity).

    Learnable gamma/beta params (unless ``lock_gamma_beta``); running
    mean/var live in layer state and update with ``decay`` during training
    (the reference's global mean/var with helper seam at
    nn/layers/normalization/BatchNormalization.java:53-60). Works on
    [b, f] (dense) and [b, h, w, c] (per-channel) inputs.
    """

    layer_type = "batch_norm"

    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False

    def has_params(self) -> bool:
        return True

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.normalization import BatchNormLayer
        return BatchNormLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class LocalResponseNormalization(BaseLayerConfig):
    """Across-channel LRN (LocalResponseNormalization.java parity;
    defaults k=2, n=5, alpha=1e-4, beta=0.75)."""

    layer_type = "lrn"
    expects_cnn_input = True

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.normalization import LRNLayer
        return LRNLayer(self, input_type, global_conf, policy)


@register_layer
@dataclass(frozen=True)
class GlobalPooling(BaseLayerConfig):
    """Global pooling over time ([b,t,f]) or spatial dims ([b,h,w,c]) with
    mask support (pooling/GlobalPoolingLayer.java parity).
    ``pooling`` in {max, avg, sum, pnorm}."""

    layer_type = "global_pooling"

    pooling: str = "max"
    pnorm: int = 2

    def get_output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "convolutional":
            return InputType.feed_forward(input_type.channels)
        return input_type

    def make_layer(self, input_type, global_conf, policy):
        from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayerImpl
        return GlobalPoolingLayerImpl(self, input_type, global_conf, policy)
