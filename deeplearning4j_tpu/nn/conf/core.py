"""Global training configuration + multi-layer configuration.

Parity: NeuralNetConfiguration.java (fluent Builder, defaults at :497-535 —
weightInit=XAVIER, learningRate=1e-1, updater=SGD, optimizationAlgo=SGD) and
MultiLayerConfiguration.java (list of layers + toJson/fromJson round-trip).

TPU-native extras: an explicit dtype policy (param dtype + compute dtype, so
bf16 compute with f32 master params is a config switch, not a rewrite) and
optional distribution hints consumed by the parallel package.
"""

from __future__ import annotations

import dataclasses
import json
import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayerConfig,
    layer_from_dict,
    layer_to_dict,
)
from deeplearning4j_tpu.nn.updater import (
    NoneSchedule,
    Schedule,
    Sgd,
    Updater,
    schedule_from_dict,
    updater_from_dict,
)


#: dtype strings a policy may name. Anything else (typos like "f32",
#: unsupported widths like "float8") is rejected eagerly at config-build
#: time — a policy typo must fail the builder, never silently train f32.
VALID_DTYPES = ("float16", "bfloat16", "float32", "float64")


def _validate_dtype(value, role: str) -> str:
    if value not in VALID_DTYPES:
        raise ValueError(
            f"DtypePolicy: unknown {role} {value!r}; expected one of "
            f"{list(VALID_DTYPES)}")
    return value


@dataclass(frozen=True)
class DtypePolicy:
    """Parameter/compute dtype policy (PRECISION.md). Matmuls and convs run
    in ``compute_dtype`` (bf16 feeds the MXU at full rate); params,
    optimizer state, LR schedules and loss reductions accumulate in
    ``param_dtype`` (the f32 master copy of the mixed-precision recipe).

    ``overrides`` keeps named sub-paths out of the global compute dtype:
    a tuple of ``(regex, dtype)`` pairs matched against the layer name
    with ``re.search`` (same per-path rule style as ``tp_rules``), first
    match wins — e.g. ``((".*_bn$", "float32"),)`` pins every batch-norm
    layer's compute to f32 under a bf16 policy.

    Loss scaling (Micikevicius et al.; needed for f16, whose 5-bit
    exponent underflows small gradients): ``loss_scale`` is ``"auto"``
    (dynamic scaling iff ``compute_dtype == "float16"``), ``"dynamic"``,
    ``"none"``, or a number (static scale). Dynamic scaling multiplies
    the loss by the current scale, unscales gradients in ``param_dtype``,
    SKIPS the update on any non-finite gradient while multiplying the
    scale by ``1/loss_scale_factor``, and regrows it by
    ``loss_scale_factor`` after ``loss_scale_growth_interval``
    consecutive finite steps, starting from ``loss_scale_init``.

    All fields are JSON-safe and round-trip through
    ``MultiLayerConfiguration.to_json``; validation happens HERE, at
    construction, so a bad policy fails the config builder with a clear
    error instead of surfacing as an XLA dtype mismatch mid-fit."""

    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    #: per-path compute-dtype overrides: ((regex, dtype), ...)
    overrides: Tuple[Tuple[str, str], ...] = ()
    #: "auto" | "dynamic" | "none" | number (static scale)
    loss_scale: Any = "auto"
    loss_scale_init: float = 2.0 ** 15
    loss_scale_factor: float = 2.0
    loss_scale_growth_interval: int = 200

    def __post_init__(self):
        _validate_dtype(self.param_dtype, "param_dtype")
        _validate_dtype(self.compute_dtype, "compute_dtype")
        norm = []
        for entry in self.overrides:
            if len(entry) != 2:
                raise ValueError(
                    "DtypePolicy.overrides entries must be (regex, dtype) "
                    f"pairs, got {entry!r}")
            pattern, dtype = entry
            try:
                re.compile(pattern)
            except re.error as e:
                raise ValueError(
                    f"DtypePolicy.overrides: bad regex {pattern!r}: {e}"
                ) from None
            _validate_dtype(dtype, f"override dtype for {pattern!r}")
            norm.append((str(pattern), str(dtype)))
        # JSON round-trips tuples as lists; normalize back so the policy
        # stays hashable (frozen dataclass in a frozen config)
        object.__setattr__(self, "overrides", tuple(norm))
        ls = self.loss_scale
        if isinstance(ls, str):
            if ls not in ("auto", "dynamic", "none"):
                raise ValueError(
                    f"DtypePolicy: unknown loss_scale {ls!r}; expected "
                    "'auto', 'dynamic', 'none', or a number")
        elif not isinstance(ls, (int, float)) or ls <= 0:
            raise ValueError(
                f"DtypePolicy: loss_scale must be > 0, got {ls!r}")
        if self.loss_scale_init <= 0:
            raise ValueError("DtypePolicy: loss_scale_init must be > 0, "
                             f"got {self.loss_scale_init!r}")
        if self.loss_scale_factor <= 1.0:
            raise ValueError("DtypePolicy: loss_scale_factor must be > 1, "
                             f"got {self.loss_scale_factor!r}")
        if self.loss_scale_growth_interval < 1:
            raise ValueError(
                "DtypePolicy: loss_scale_growth_interval must be >= 1, "
                f"got {self.loss_scale_growth_interval!r}")

    def compute_dtype_for(self, path: Optional[str]) -> str:
        """Effective compute dtype for a named layer/path: the first
        ``overrides`` rule whose regex ``re.search``-matches ``path``
        wins; otherwise the global ``compute_dtype``."""
        if path is not None:
            for pattern, dtype in self.overrides:
                if re.search(pattern, path):
                    return dtype
        return self.compute_dtype

    def loss_scale_mode(self):
        """Resolved scaling mode: None (off), "dynamic", or a static
        float. "auto" resolves to dynamic exactly for f16 compute — bf16
        keeps the f32 exponent range and needs no scaling."""
        ls = self.loss_scale
        if ls == "auto":
            return "dynamic" if self.compute_dtype == "float16" else None
        if ls == "none":
            return None
        if ls == "dynamic":
            return "dynamic"
        return float(ls)

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "DtypePolicy":
        d = dict(d)
        if "overrides" in d and d["overrides"] is not None:
            d["overrides"] = tuple(tuple(e) for e in d["overrides"])
        names = {f.name for f in dataclasses.fields(DtypePolicy)}
        return DtypePolicy(**{k: v for k, v in d.items() if k in names})


@dataclass(frozen=True)
class NeuralNetConfiguration:
    """Global (network-wide) hyperparameters; per-layer configs override
    field-by-field (reference: NeuralNetConfiguration.Builder defaults at
    NeuralNetConfiguration.java:497-535)."""

    seed: int = 123
    activation: str = "sigmoid"
    weight_init: Any = "xavier"
    bias_init: float = 0.0
    # None -> "use the updater's own learning_rate". Effective per-layer lr =
    # first set of (layer.learning_rate, global.learning_rate, updater.lr).
    learning_rate: Optional[float] = None
    lr_schedule: Schedule = field(default_factory=NoneSchedule)
    updater: Updater = field(default_factory=lambda: Sgd(0.1))
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    dropout: float = 0.0
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    minibatch: bool = True
    dtype: DtypePolicy = field(default_factory=DtypePolicy)

    # ---- builder ----------------------------------------------------------
    @staticmethod
    def builder() -> "NeuralNetConfBuilder":
        return NeuralNetConfBuilder()

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["updater"] = self.updater.to_dict()
        d["lr_schedule"] = self.lr_schedule.to_dict()
        d["dtype"] = self.dtype.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "NeuralNetConfiguration":
        d = dict(d)
        if isinstance(d.get("updater"), dict):
            d["updater"] = updater_from_dict(d["updater"])
        if isinstance(d.get("lr_schedule"), dict):
            d["lr_schedule"] = schedule_from_dict(d["lr_schedule"])
        if isinstance(d.get("dtype"), dict):
            d["dtype"] = DtypePolicy.from_dict(d["dtype"])
        names = {f.name for f in dataclasses.fields(NeuralNetConfiguration)}
        return NeuralNetConfiguration(**{k: v for k, v in d.items() if k in names})

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


class NeuralNetConfBuilder:
    """Fluent builder mirroring the reference's
    ``new NeuralNetConfiguration.Builder()....list()...build()`` idiom."""

    def __init__(self):
        self._kw = {}

    def __getattr__(self, name):
        # Generic fluent setter: .seed(123).learning_rate(1e-2)...
        fields = {f.name for f in dataclasses.fields(NeuralNetConfiguration)}
        if name in fields:
            def setter(value):
                self._kw[name] = value
                return self
            return setter
        raise AttributeError(name)

    def build(self) -> NeuralNetConfiguration:
        return NeuralNetConfiguration(**self._kw)

    def list(self) -> "ListBuilder":
        return ListBuilder(self.build())

    def graph_builder(self):
        """DAG builder (reference: .graphBuilder())."""
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
        return GraphBuilder(self.build())


class ListBuilder:
    """Builds a MultiLayerConfiguration (reference:
    NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, global_conf: NeuralNetConfiguration):
        self._conf = global_conf
        self._layers: List[BaseLayerConfig] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20
        self._preprocessors = {}

    def layer(self, layer_conf: BaseLayerConfig, index: int | None = None):
        if index is not None and index != len(self._layers):
            raise ValueError(
                f"Layers must be added in order; got index {index} at position "
                f"{len(self._layers)}")
        self._layers.append(layer_conf)
        return self

    def set_input_type(self, input_type: InputType):
        self._input_type = input_type
        return self

    def input_preprocessor(self, layer_index: int, preprocessor):
        self._preprocessors[int(layer_index)] = preprocessor
        return self

    def backprop_type(self, kind: str, tbptt_fwd: int = 20, tbptt_bwd: int = 20):
        self._backprop_type = kind
        self._tbptt_fwd = tbptt_fwd
        self._tbptt_bwd = tbptt_bwd
        return self

    def build(self) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            global_conf=self._conf,
            layers=tuple(self._layers),
            input_type=self._input_type,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            preprocessors=dict(self._preprocessors),
        )


@dataclass(frozen=True)
class MultiLayerConfiguration:
    """A sequential stack of layer configs (MultiLayerConfiguration.java
    parity) with JSON round-trip (the reference's Jackson toJson/fromJson is
    both the persistence format and the regression-test surface — kept)."""

    global_conf: NeuralNetConfiguration
    layers: tuple
    input_type: Optional[InputType] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    preprocessors: dict = field(default_factory=dict)

    def __post_init__(self):
        if (self.backprop_type == "tbptt"
                and self.tbptt_fwd_length != self.tbptt_bwd_length):
            raise ValueError(
                "tBPTT here chunks the sequence at tbptt_fwd_length and "
                "truncates gradients at the chunk boundary, so "
                f"tbptt_bwd_length ({self.tbptt_bwd_length}) must equal "
                f"tbptt_fwd_length ({self.tbptt_fwd_length}); a shorter "
                "backward window is not supported")

    def to_json(self) -> str:
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_to_dict
        return json.dumps(
            {
                "format_version": 1,
                "global_conf": self.global_conf.to_dict(),
                "layers": [layer_to_dict(l) for l in self.layers],
                "input_type": self.input_type.to_dict() if self.input_type else None,
                "backprop_type": self.backprop_type,
                "tbptt_fwd_length": self.tbptt_fwd_length,
                "tbptt_bwd_length": self.tbptt_bwd_length,
                "preprocessors": {
                    str(k): preprocessor_to_dict(v)
                    for k, v in self.preprocessors.items()
                },
            },
            indent=2,
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict
        d = json.loads(s)
        return MultiLayerConfiguration(
            global_conf=NeuralNetConfiguration.from_dict(d["global_conf"]),
            layers=tuple(layer_from_dict(l) for l in d["layers"]),
            input_type=(
                InputType.from_dict(d["input_type"]) if d.get("input_type") else None
            ),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_bwd_length=d.get("tbptt_bwd_length", 20),
            preprocessors={
                int(k): preprocessor_from_dict(v)
                for k, v in d.get("preprocessors", {}).items()
            },
        )
