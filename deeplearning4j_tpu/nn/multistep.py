"""Shared machinery for running n fused train steps in one XLA execution
(lax.scan over a network's raw step_fn) — used by both MultiLayerNetwork
and ComputationGraph fit_batch_repeated."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def _unroll() -> int:
    """Scan unroll factor (default 2: lets XLA overlap the tail of one
    step with the head of the next, measured ~2% on the ResNet-50 bench;
    4 was measured NEUTRAL there — more unrolling only grows the program.
    Override with DL4J_TPU_SCAN_UNROLL for experiments)."""
    return max(1, int(os.environ.get("DL4J_TPU_SCAN_UNROLL", "2")))


def build_multi_step(step_fn, n_steps: int):
    """jit(scan(step_fn, length=n_steps)). The returned callable has the
    same signature as step_fn; the rng argument is split once per inner
    step, and the returned score is the last step's."""
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")

    def multi(params, state, opt_state, it0, *data_args):
        rng = data_args[-1]
        rest = data_args[:-1]

        def body(carry, i):
            p, s, o, key = carry
            key, sub = jax.random.split(key)
            p, s, o, score = step_fn(p, s, o, it0 + i, *rest, sub)
            return (p, s, o, key), score

        (p, s, o, _), scores = jax.lax.scan(
            body, (params, state, opt_state, rng), jnp.arange(n_steps),
            unroll=min(_unroll(), n_steps))
        return p, s, o, scores[-1]

    return jax.jit(multi, donate_argnums=(0, 1, 2))


def get_multi_step(net, n_steps: int):
    """Cache-aware accessor for a network's scanned multi-step."""
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    jitted = net._multi_steps.get(n_steps)
    if jitted is None:
        jitted = build_multi_step(net._step_fn(), n_steps)
        net._multi_steps[n_steps] = jitted
    return jitted


def build_multi_batch_step(step_fn):
    """jit(scan(step_fn)) over a chunk of k DISTINCT batches (leading axis
    of every data leaf is the chunk), bit-identical to k sequential
    ``fit_batch`` calls: the scan body replays fit_batch's exact rng
    discipline — ``key, sub = split(key)``, the step consumes ``sub`` —
    and the final carried key is returned so the caller can store it back
    as the net's rng chain. (``build_multi_step`` above scans the SAME
    batch and burns one extra split; it is not sequentially identical,
    which is fine for benchmarking but not for the fit path.)

    Signature: ``(params, state, opt_state, it0, key, steps, data) ->
    (params, state, opt_state, key, scores)`` where ``steps`` is
    ``arange(k, int32)``, ``data`` is a pytree of stacked per-step args
    (``None`` leaves allowed for absent masks), and ``scores`` has shape
    ``(k,)``. One builder per net; jit re-specializes per (k, shapes).
    """

    def multi(params, state, opt_state, it0, key, steps, data):
        def body(carry, inp):
            p, s, o, k = carry
            i, args = inp
            k, sub = jax.random.split(k)
            p, s, o, score = step_fn(p, s, o, it0 + i, *args, sub)
            return (p, s, o, k), score

        # unroll is pinned to 1: unrolling lets XLA fuse across step
        # boundaries, which perturbs float rounding (~1 ulp, measured) —
        # the fit path's win is collapsed dispatch, and bit-identity with
        # the sequential loop is a hard contract here
        (p, s, o, key), scores = jax.lax.scan(
            body, (params, state, opt_state, key), (steps, data), unroll=1)
        return p, s, o, key, scores

    return jax.jit(multi, donate_argnums=(0, 1, 2))


def get_multi_batch_step(net):
    """Cache-aware accessor for a network's chunked-fit dispatcher (one
    jitted callable per net; distinct chunk sizes/shapes become jit cache
    entries). Invalidated with the rest of ``net._multi_steps`` by
    ``set_lr_scale`` and friends."""
    key = "multi_batch"
    jitted = net._multi_steps.get(key)
    if jitted is None:
        jitted = build_multi_batch_step(net._step_fn())
        net._multi_steps[key] = jitted
    return jitted
