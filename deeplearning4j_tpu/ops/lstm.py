"""Fused LSTM sequence op: XLA scan backend + Pallas TPU kernel backend.

Parity: the reference's hand-fused LSTM
(deeplearning4j-nn/.../recurrent/LSTMHelpers.java:57 activateHelper,
:271 backpropGradientHelper) whose perf bar is the cuDNN fused LSTM. The
registry seam (ops/registry.py) mirrors the reference's Helper loading
(ConvolutionLayer.java:69-76): ``lstm_sequence`` has an ``xla`` backend
(lax.scan of the cell — what autodiff differentiates) and a ``pallas``
backend (this file's hand-written forward+backward kernels), equivalence
-tested against each other in tests/test_backend_equivalence.py — the
CuDNNGradientChecks.java analogue.

Why a Pallas kernel: the scan path issues ~10 small XLA ops per timestep
and re-reads the recurrent weight Wh from HBM every step (measured 88us
per timestep on a v5e for batch 32, hidden 512 — 0.7% MFU). The Pallas
kernel runs the WHOLE time loop in one kernel launch with Wh and the
(h, c) carry resident in VMEM, streaming xz[t] in and (y[t], saves[t])
out — the cuDNN-class schedule.

Gate math (Graves formulation with peepholes, order i, f, o, g):
    i = sigmoid(zi + p_i * c_prev)      f = sigmoid(zf + p_f * c_prev)
    g = tanh(zg)                        c = f * c_prev + i * g
    o = sigmoid(zo + p_o * c)           h = o * tanh(c)
Masked steps carry (h, c) through unchanged and emit zero output.

The op consumes the PRE-PROJECTED input xz[t] = x[t] @ Wx + b (one big
MXU matmul outside the time loop); its backward emits dxz, from which
dWx/db/dx are recovered by the caller with dense matmuls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import activations as act_mod
from deeplearning4j_tpu.ops import registry


# ------------------------------------------------------------------ xla
def _cell_step(Wh, p, gate_act, cell_act, carry, inp):
    h_prev, c_prev = carry
    z, m = inp
    n = h_prev.shape[-1]
    z = z + h_prev @ Wh
    zi, zf, zo, zg = (z[:, :n], z[:, n:2 * n], z[:, 2 * n:3 * n],
                      z[:, 3 * n:])
    i = gate_act(zi + p[0] * c_prev)
    f = gate_act(zf + p[1] * c_prev)
    g = cell_act(zg)
    c = f * c_prev + i * g
    o = gate_act(zo + p[2] * c)
    h = o * cell_act(c)
    if m is None:
        return (h, c), h
    mcol = m[:, None]
    h_keep = jnp.where(mcol > 0, h, h_prev)
    c_keep = jnp.where(mcol > 0, c, c_prev)
    return (h_keep, c_keep), h * mcol


@registry.register("lstm_sequence", backend="xla")
def lstm_sequence_xla(xz_t, h0, c0, Wh, p, mask_t, *, gate_act="sigmoid",
                      cell_act="tanh"):
    """Time-major LSTM over pre-projected inputs.

    xz_t: [t, b, 4n]; h0, c0: [b, n]; Wh: [n, 4n]; p: [3, n] peepholes;
    mask_t: [t, b] or None. Returns (y_t [t, b, n], hT, cT)."""
    ga = act_mod.get(gate_act) if isinstance(gate_act, str) else gate_act
    ca = act_mod.get(cell_act) if isinstance(cell_act, str) else cell_act
    step = partial(_cell_step, Wh, p, ga, ca)
    if mask_t is None:
        (hT, cT), ys = jax.lax.scan(
            lambda carry, z: step(carry, (z, None)), (h0, c0), xz_t)
    else:
        (hT, cT), ys = jax.lax.scan(step, (h0, c0), (xz_t, mask_t))
    return ys, hT, cT


# --------------------------------------------------------------- pallas
_interpret = registry.pallas_interpret


def _pallas_supported(xz_t, h0, gate_act, cell_act):
    if gate_act != "sigmoid" or cell_act != "tanh":
        return False
    if xz_t.dtype not in (jnp.bfloat16, jnp.float32):
        return False
    b, n = h0.shape[-2], h0.shape[-1]
    sublane = 16 if xz_t.dtype == jnp.bfloat16 else 8
    if n % 128 != 0 or b % sublane != 0:
        return False
    if not _interpret() and jax.default_backend() != "tpu":
        return False
    return True


def _fwd_kernel(xz_ref, m_ref, h0_ref, c0_ref, Wh_ref, p_ref,
                y_ref, hT_ref, cT_ref, G_ref, hprev_ref, cprev_ref,
                h_scr, c_scr):
    import jax.experimental.pallas as pl

    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h_prev = h_scr[:]
    c_prev = c_scr[:]
    cd = xz_ref.dtype
    n = h_prev.shape[-1]

    z = xz_ref[0].astype(jnp.float32) + jnp.dot(
        h_prev.astype(cd), Wh_ref[:], preferred_element_type=jnp.float32)
    pvec = p_ref[:].astype(jnp.float32)
    i = jax.nn.sigmoid(z[:, :n] + pvec[0:1, :] * c_prev)
    f = jax.nn.sigmoid(z[:, n:2 * n] + pvec[1:2, :] * c_prev)
    g = jnp.tanh(z[:, 3 * n:])
    c = f * c_prev + i * g
    o = jax.nn.sigmoid(z[:, 2 * n:3 * n] + pvec[2:3, :] * c)
    h = o * jnp.tanh(c)

    m = m_ref[0].astype(jnp.float32)
    h_keep = jnp.where(m > 0, h, h_prev)
    c_keep = jnp.where(m > 0, c, c_prev)

    y_ref[0] = (h * m).astype(cd)
    G_ref[0] = jnp.concatenate([i, f, o, g], axis=-1).astype(cd)
    hprev_ref[0] = h_prev.astype(cd)
    cprev_ref[0] = c_prev.astype(cd)
    h_scr[:] = h_keep
    c_scr[:] = c_keep

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h_keep.astype(cd)
        cT_ref[:] = c_keep.astype(cd)


def _bwd_kernel(G_ref, hprev_ref, cprev_ref, m_ref, Wh_ref, p_ref,
                dy_ref, dhT_ref, dcT_ref,
                dxz_ref, dh0_ref, dc0_ref, dWh_ref, dp_ref,
                dh_scr, dc_scr, dWh_scr, dp_scr):
    import jax.experimental.pallas as pl

    pid = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(pid == 0)
    def _():
        dh_scr[:] = dhT_ref[:].astype(jnp.float32)
        dc_scr[:] = dcT_ref[:].astype(jnp.float32)
        dWh_scr[:] = jnp.zeros_like(dWh_scr)
        dp_scr[:] = jnp.zeros_like(dp_scr)

    cd = G_ref.dtype
    n = hprev_ref.shape[-1]
    G = G_ref[0].astype(jnp.float32)
    i, f, o, g = (G[:, :n], G[:, n:2 * n], G[:, 2 * n:3 * n], G[:, 3 * n:])
    h_prev = hprev_ref[0].astype(jnp.float32)
    c_prev = cprev_ref[0].astype(jnp.float32)
    pvec = p_ref[:].astype(jnp.float32)
    m = m_ref[0].astype(jnp.float32)

    c = f * c_prev + i * g
    tc = jnp.tanh(c)

    dh_next = dh_scr[:]
    dc_next = dc_scr[:]

    dh = m * (dh_next + dy_ref[0].astype(jnp.float32))
    do = dh * tc
    dzo = do * o * (1.0 - o)
    dc_in = m * dc_next + dh * o * (1.0 - tc * tc) + dzo * pvec[2:3, :]
    di = dc_in * g
    df = dc_in * c_prev
    dg = dc_in * i
    dzi = di * i * (1.0 - i)
    dzf = df * f * (1.0 - f)
    dzg = dg * (1.0 - g * g)

    dz = jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1)
    dz_cd = dz.astype(cd)

    # dh_prev = dz @ Wh^T  (contract the 4n dim)
    dh_prev = jax.lax.dot_general(
        dz_cd, Wh_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dh_prev = dh_prev + (1.0 - m) * dh_next
    dc_prev = dc_in * f + dzi * pvec[0:1, :] + dzf * pvec[1:2, :] \
        + (1.0 - m) * dc_next

    # dWh += h_prev^T @ dz  (contract the batch dim)
    dWh_scr[:] += jax.lax.dot_general(
        hprev_ref[0], dz_cd, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp_scr[0:1, :] += jnp.sum(dzi * c_prev, axis=0, keepdims=True)
    dp_scr[1:2, :] += jnp.sum(dzf * c_prev, axis=0, keepdims=True)
    dp_scr[2:3, :] += jnp.sum(dzo * c, axis=0, keepdims=True)

    dxz_ref[0] = dz_cd
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(pid == T - 1)
    def _():
        dh0_ref[:] = dh_prev.astype(cd)
        dc0_ref[:] = dc_prev.astype(cd)
        dWh_ref[:] = dWh_scr[:].astype(cd)
        dp_ref[:] = dp_scr[:].astype(cd)


def _fwd_call(xz_t, h0, c0, Wh, p, mask_t):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, b, n4 = xz_t.shape
    n = n4 // 4
    cd = xz_t.dtype
    sds = jax.ShapeDtypeStruct
    out_shapes = (
        sds((T, b, n), cd),    # y
        sds((b, n), cd),       # hT
        sds((b, n), cd),       # cT
        sds((T, b, n4), cd),   # G (gates i,f,o,g)
        sds((T, b, n), cd),    # h_prev per step
        sds((T, b, n), cd),    # c_prev per step
    )
    t_block = lambda width: pl.BlockSpec(
        (1, b, width), lambda t: (t, 0, 0), memory_space=pltpu.VMEM)
    full = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    fixed2 = lambda r, cdim: pl.BlockSpec(
        (r, cdim), lambda t: (0, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _fwd_kernel,
        grid=(T,),
        in_specs=[
            t_block(n4),                                     # xz
            pl.BlockSpec((1, b, 1), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),           # mask [t,b,1]
            fixed2(b, n), fixed2(b, n),                      # h0, c0
            fixed2(n, n4),                                   # Wh
            fixed2(3, n),                                    # p
        ],
        out_specs=(
            t_block(n),                                      # y
            fixed2(b, n), fixed2(b, n),                      # hT, cT
            t_block(n4),                                     # G
            t_block(n), t_block(n),                          # h_prev, c_prev
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((b, n), jnp.float32),
            pltpu.VMEM((b, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(xz_t, mask_t[:, :, None], h0, c0, Wh, p)


def _bwd_call(res, cts):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    G, hprev, cprev, mask_t, Wh, p = res
    dy, dhT, dcT = cts
    T, b, n = hprev.shape
    n4 = 4 * n
    cd = G.dtype
    dy = dy.astype(cd)
    dhT = dhT.astype(cd)
    dcT = dcT.astype(cd)
    sds = jax.ShapeDtypeStruct
    out_shapes = (
        sds((T, b, n4), cd),   # dxz
        sds((b, n), cd),       # dh0
        sds((b, n), cd),       # dc0
        sds((n, n4), cd),      # dWh
        sds((3, n), cd),       # dp
    )
    rev = lambda width: pl.BlockSpec(
        (1, b, width), lambda i: (T - 1 - i, 0, 0), memory_space=pltpu.VMEM)
    fixed2 = lambda r, cdim: pl.BlockSpec(
        (r, cdim), lambda i: (0, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _bwd_kernel,
        grid=(T,),
        in_specs=[
            rev(n4),                                         # G
            rev(n), rev(n),                                  # h_prev, c_prev
            pl.BlockSpec((1, b, 1), lambda i: (T - 1 - i, 0, 0),
                         memory_space=pltpu.VMEM),           # mask [t,b,1]
            fixed2(n, n4),                                   # Wh
            fixed2(3, n),                                    # p
            rev(n),                                          # dy
            fixed2(b, n), fixed2(b, n),                      # dhT, dcT
        ],
        out_specs=(
            rev(n4),                                         # dxz
            fixed2(b, n), fixed2(b, n),                      # dh0, dc0
            fixed2(n, n4),                                   # dWh
            fixed2(3, n),                                    # dp
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((b, n), jnp.float32),
            pltpu.VMEM((b, n), jnp.float32),
            pltpu.VMEM((n, n4), jnp.float32),
            pltpu.VMEM((3, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(G, hprev, cprev, mask_t[:, :, None], Wh, p, dy, dhT, dcT)


@jax.custom_vjp
def _lstm_seq_pallas(xz_t, h0, c0, Wh, p, mask_t):
    y, hT, cT, _, _, _ = _fwd_call(xz_t, h0, c0, Wh, p, mask_t)
    return y, hT, cT


def _lstm_seq_fwd(xz_t, h0, c0, Wh, p, mask_t):
    y, hT, cT, G, hprev, cprev = _fwd_call(xz_t, h0, c0, Wh, p, mask_t)
    return (y, hT, cT), (G, hprev, cprev, mask_t, Wh, p)


def _lstm_seq_bwd(res, cts):
    dxz, dh0, dc0, dWh, dp = _bwd_call(res, cts)
    return dxz, dh0, dc0, dWh, dp, None


_lstm_seq_pallas.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)


@registry.register("lstm_sequence", backend="pallas")
def lstm_sequence_pallas(xz_t, h0, c0, Wh, p, mask_t, *, gate_act="sigmoid",
                         cell_act="tanh"):
    """Pallas-fused LSTM sequence; silently delegates to the xla backend
    for configurations the kernel does not cover (non-sigmoid/tanh
    activations, unaligned shapes, non-TPU platforms) — the same graceful
    fallback the reference's helper loading performs when cuDNN is absent
    (ConvolutionLayer.java:69-76)."""
    if not _pallas_supported(xz_t, h0, gate_act, cell_act):
        return lstm_sequence_xla(xz_t, h0, c0, Wh, p, mask_t,
                                 gate_act=gate_act, cell_act=cell_act)
    if mask_t is None:
        mask_t = jnp.ones(xz_t.shape[:2], xz_t.dtype)
    else:
        mask_t = mask_t.astype(xz_t.dtype)
    return _lstm_seq_pallas(xz_t, h0, c0, Wh, p, mask_t)
