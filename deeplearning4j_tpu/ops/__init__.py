"""Low-level op layer: activations, losses, initializers, and the
op-lowering registry (the TPU analogue of the reference's cuDNN Helper seam,
see SURVEY.md §2.0 / deeplearning4j-cuda CudnnConvolutionHelper.java:49)."""
