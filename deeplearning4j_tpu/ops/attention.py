"""Causal multi-head attention through the op registry — the transformer
tier's one genuinely fusion-hungry op (ROADMAP item 1; the TVM thesis from
PAPERS.md applied to attention).

Three backends behind the ``causal_mha`` registry seam:

- ``xla`` (default): one scale/mask/softmax/matmul chain with both
  contractions lowered as fused multiply+reduce loops instead of dot
  primitives. A GEMM's k-accumulation order is tiled by shape — measured
  on this XLA, a tq=1 dot and a tq=T dot over the same rows disagree in
  the last ulp — while a fused reduce's order is independent of every
  non-reduced dimension. That lowering choice is the whole decode
  bit-identity contract (below). Scores and softmax run in f32 regardless
  of compute dtype (bf16 exponent range is not enough for long-sequence
  logits); masking uses ``-inf`` so masked positions contribute an EXACT
  0.0 to every reduction.
- ``xla_dot``: the same chain as batched f32-accumulating dots (the MXU
  lowering). Faster for big shapes off-TPU, tolerance-equivalent, NOT
  decode-stable — selectable via ``registry.use_backend`` where the
  contract is not in play.
- ``pallas``: a flash-style forward — online softmax over kv tiles with
  the running (m, l, acc) carried in f32 VMEM scratch, causal tile-skip
  above the diagonal, the [t, t] score matrix never materialized to HBM.
  Guarded by ``attention_supported`` per PERF.md §1: hand-DMA'd streaming
  kernels measured 13-73 GB/s against XLA's ~700-800 GB/s on this stack,
  so the kernel only runs where its VMEM-residency win (no score-matrix
  traffic) is structural, and it silently delegates to the xla backend
  everywhere else — the same graceful fallback as ops/fused_block.py. The
  backward recomputes through the xla_dot formulation (a custom_vjp):
  PERF.md §1's verdict makes a hand-written flash backward a net loss
  here, and grad parity against the xla backend is what
  tests/test_backend_equivalence.py pins either way.

Incremental decode (``decode_mha`` + ``extend_cache``): a step's new-token
queries attend over a KV cache instead of recomputing the prefix. The
**bit-identity contract** (the ``rnn_time_step`` contract from
nn/multilayer.py:485 extended to attention): decoding token-by-token
through a cache of length C produces bit-identical outputs to the
full-sequence causal forward run *at the same kv extent C* — every query
row's visible set {j <= q_start + i} is identical in both paths, and the
exact lowering's reduction order is independent of the q extent. The kv
extent must MATCH between the compared paths: measured on this XLA, even
the fused-reduce lowering regroups its accumulation when the reduced axis
length changes (zero-padding keys from tk=33 to 64 moved f32 outputs by
1 ulp), so the attention layers allocate the cache once at
``max_cache_len`` and run prefill AND every decode step against that full
fixed-extent cache. Padded cache slots must be FINITE (the pool
zero-fills pages) — garbage k rows are masked out, but an inf/nan would
poison 0 * v. Pinned in tests/test_transformer.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import registry

_NEG_INF = float("-inf")
# finite mask for the in-kernel tiles (f32 -inf breaks the m-subtraction
# when a row's running max is still the mask value; see the flash papers'
# convention). Every row's FIRST processed tile contains column 0 <= row,
# so the running max is always a real score by flush time.
_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _positions(q_start, tq):
    """Absolute position of every query row: [b|1, tq] int32."""
    qs = jnp.asarray(q_start, jnp.int32)
    if qs.ndim == 0:
        qs = qs[None]
    return qs[:, None] + jnp.arange(tq, dtype=jnp.int32)[None, :]


def _mask_softmax(s, q_start, tq, tk):
    """Shared mask + online-softmax tail: returns (p, l) with p the
    unnormalized exp-weights and l the per-row partition sum."""
    qpos = _positions(q_start, tq)                       # [b|1, tq]
    j = jnp.arange(tk, dtype=jnp.int32)
    visible = qpos[:, None, :, None] >= j[None, None, None, :]
    s = jnp.where(visible, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)               # >= one real score
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)               # [b, h, tq, 1]
    return p, l


def _causal_mha_exact(q, k, v, q_start):
    """The contract-bearing formulation: both contractions are explicit
    multiply+reduce chains, NOT dot primitives. A dot lowers to a
    shape-tiled GEMM whose k-accumulation order changes with the q extent
    (measured on this XLA: tq=1 and tq=T disagree in the last ulp), while
    a fused reduce loops the contracted axis per output element — the
    order is independent of every other dimension. That is what makes
    incremental decode (tq=1..n over a cache) bit-identical to the
    full-sequence forward (tq=T). Products reduce in f32 regardless of
    compute dtype (the preferred_element_type=f32 semantics)."""
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    cd = q.dtype
    scale = 1.0 / math.sqrt(dh)
    qh = jnp.moveaxis(q, 2, 1).astype(jnp.float32)       # [b, h, tq, dh]
    kh = jnp.moveaxis(k, 2, 1).astype(jnp.float32)       # [b, h, tk, dh]
    vh = jnp.moveaxis(v, 2, 1).astype(jnp.float32)
    s = jnp.sum(qh[:, :, :, None, :] * kh[:, :, None, :, :],
                axis=-1) * scale                         # [b, h, tq, tk]
    p, l = _mask_softmax(s, q_start, tq, tk)
    # normalize AFTER the weighted sum (the flash acc/l form) so no
    # division sits inside a reduction for XLA to reassociate
    out = jnp.sum(p[:, :, :, :, None] * vh[:, :, None, :, :],
                  axis=3)                                # [b, h, tq, dh]
    out = out / l
    return jnp.moveaxis(out, 1, 2).astype(cd)


def _causal_mha_dot(q, k, v, q_start):
    """The MXU formulation: both contractions as batched dots with f32
    accumulation — what the fused scale/mask/softmax/matmul chain should
    lower to on an accelerator. Tolerance-equivalent to the exact
    formulation (same math, GEMM-tiled reductions); NOT decode-stable,
    which is why it is a named backend rather than the default."""
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    cd = q.dtype
    scale = 1.0 / math.sqrt(dh)
    qh = jnp.moveaxis(q, 2, 1)
    kh = jnp.moveaxis(k, 2, 1)
    vh = jnp.moveaxis(v, 2, 1)
    s = jax.lax.dot_general(
        qh, kh, dimension_numbers=(((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) * scale      # [b, h, tq, tk]
    p, l = _mask_softmax(s, q_start, tq, tk)
    out = jax.lax.dot_general(
        p.astype(cd), vh, dimension_numbers=(((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)              # [b, h, tq, dh]
    out = out / l
    return jnp.moveaxis(out, 1, 2).astype(cd)


@registry.register("causal_mha", backend="xla")
def causal_mha_xla(q, k, v, *, q_start=0):
    """Causal MHA, the default backend: fused scale/mask/softmax/matmul
    semantics in the decode-stable multiply+reduce lowering (see
    ``_causal_mha_exact`` — this is the formulation the bit-identity
    contract is pinned on)."""
    return _causal_mha_exact(q, k, v, q_start)


@registry.register("causal_mha", backend="xla_dot")
def causal_mha_xla_dot(q, k, v, *, q_start=0):
    """Batched-GEMM lowering of the same chain (MXU-friendly; decode
    tolerance documented in the module docstring)."""
    return _causal_mha_dot(q, k, v, q_start)


# --------------------------------------------------------------- pallas
_interpret = registry.pallas_interpret

_BQ = 128
_BK = 128
# one grid step's resident set must fit beside double-buffered tiles
_VMEM_BUDGET = 12 * 1024 * 1024


def attention_supported(q, k, v, q_start=0) -> bool:
    """Does the flash kernel cover this configuration? Decode steps
    (traced/nonzero q_start, tiny tq) stay on xla — a per-step GEMV has no
    score-matrix traffic to save and PERF.md §1's per-grid-step overhead
    (~15-25us) would dominate it."""
    if not (isinstance(q_start, int) and q_start == 0):
        return False
    if q.dtype not in (jnp.bfloat16, jnp.float32):
        return False
    if k.dtype != q.dtype or v.dtype != q.dtype:
        return False
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    if tq != tk:
        return False
    if dh % 128 != 0 or tq % _BQ != 0 or tk % _BK != 0:
        return False
    itemsize = 2 if q.dtype == jnp.bfloat16 else 4
    foot = (3 * 2 * _BQ * dh * itemsize      # q/k/v tiles, double-buffered
            + _BQ * dh * (itemsize + 4)      # out tile + f32 accumulator
            + 2 * _BQ * 128 * 4              # m, l scratch
            + 2 * _BQ * _BK * 4)             # s, p intermediates
    if foot > _VMEM_BUDGET:
        return False
    if not _interpret() and jax.default_backend() != "tpu":
        return False
    return True


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale, bq, bk, kv_blocks):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal tile-skip: process only tiles touching or below the diagonal
    @pl.when(ki * bk <= qi * bq + bq - 1)
    def _():
        qb = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            qb, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, _MASK_VALUE)
        m_prev = m_scr[:][:, :1]
        l_prev = l_scr[:][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == kv_blocks - 1)
    def _():
        o_ref[0] = (acc_scr[:] / l_scr[:][:, :1]).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, tq, h, dh = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(dh)
    qh = jnp.moveaxis(q, 2, 1).reshape(b * h, tq, dh)
    kh = jnp.moveaxis(k, 2, 1).reshape(b * h, tk, dh)
    vh = jnp.moveaxis(v, 2, 1).reshape(b * h, tk, dh)
    qt, kt = tq // _BQ, tk // _BK

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, bq=_BQ, bk=_BK,
                          kv_blocks=kt),
        grid=(b * h, qt, kt),
        in_specs=[
            pl.BlockSpec((1, _BQ, dh), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BK, dh), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BK, dh), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, _BQ, dh), lambda bh, qi, ki: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((_BQ, 128), jnp.float32),
            pltpu.VMEM((_BQ, 128), jnp.float32),
            pltpu.VMEM((_BQ, dh), jnp.float32),
        ],
        interpret=_interpret(),
    )(qh, kh, vh)
    return jnp.moveaxis(out.reshape(b, h, tq, dh), 1, 2)


@jax.custom_vjp
def _flash(q, k, v):
    return _flash_fwd_impl(q, k, v)


def _flash_vjp_fwd(q, k, v):
    return _flash_fwd_impl(q, k, v), (q, k, v)


def _flash_vjp_bwd(res, g):
    # backward recomputes through the batched-dot formulation (module
    # docstring): PERF.md §1 prices a hand flash-backward as a net loss
    # on this stack, and the dot lowering keeps the recompute on the MXU
    q, k, v = res
    _, vjp = jax.vjp(
        lambda a, b_, c: _causal_mha_dot(a, b_, c, 0), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@registry.register("causal_mha", backend="pallas")
def causal_mha_pallas(q, k, v, *, q_start=0):
    """Flash-style tiled forward; silently delegates to the xla backend
    for configurations the kernel does not cover (decode steps, unaligned
    shapes, non-TPU without interpret — see ``attention_supported``)."""
    if not attention_supported(q, k, v, q_start):
        return causal_mha_xla(q, k, v, q_start=q_start)
    return _flash(q, k, v)


# --------------------------------------------------------------- decode
def causal_mha(q, k, v, *, q_start=0):
    """Resolve the registered backend order and apply (layer-facing)."""
    return registry.get("causal_mha")(q, k, v, q_start=q_start)


def causal_mha_exact(q, k, v, *, q_start=0):
    """The contract-bearing exact formulation, OUTSIDE the registry seam:
    the attention layers' streaming (prefill/decode) path calls this
    directly so a ``use_backend`` override can never break the pinned
    decode bit-identity contract. The registry-resolved ``causal_mha``
    stays the training/throughput seam."""
    return _causal_mha_exact(q, k, v, q_start)


def decode_mha(q, k_cache, v_cache, pos):
    """Incremental decode: ``q`` [b, t_new, h, dh] holds the new tokens'
    queries, the caches hold every earlier position (plus the new tokens,
    already written by ``extend_cache``), ``pos`` [b] is each row's prefix
    length. Row i of the step attends keys j <= pos + i — exactly the
    visible set the full-sequence forward gives that absolute position, so
    outputs are bit-identical to the full forward's corresponding slice
    (module docstring contract)."""
    return causal_mha(q, k_cache, v_cache, q_start=pos)


def extend_cache(k_cache, v_cache, k_new, v_new, pos):
    """Write t_new per-row projections into the caches at each row's own
    offset: cache[i, pos[i]:pos[i]+t_new] = new[i]. Caches [b, T, h, dh];
    caller guarantees pos + t_new <= T (the serving tier re-buckets the
    gathered cache before the step that would overflow)."""
    pos = jnp.asarray(pos, jnp.int32)

    def _write(cache, new, p):
        # literal-int starts would promote to int64 under jax_enable_x64
        # and clash with the int32 position row
        z = jnp.zeros((), p.dtype)
        return jax.lax.dynamic_update_slice(cache, new, (p, z, z))

    return (jax.vmap(_write)(k_cache, k_new, pos),
            jax.vmap(_write)(v_cache, v_new, pos))
