"""Batch-norm training op with a hand-written, dtype-controlled backward.

Parity: the cuDNN batch-norm helper
(deeplearning4j-cuda/.../CudnnBatchNormalizationHelper.java) — the reference
routes BN through a fused native kernel for exactly the reason this op
exists: the composed-op formulation is memory-bound and the autodiff
backward is wasteful.

Why a custom VJP: under the mixed bf16 policy, autodiff of
``mean``/``var`` over ``x.astype(f32)`` pushes *f32 activation-sized
cotangents* through the statistics path (measured: 96 f32[256,56,56,256]
tensors in the ResNet-50 step HLO, collapsing the step to ~9-14 flops/byte
on an HBM-bound roofline). The hand-written backward keeps every
activation-sized tensor in the compute dtype (bf16) and accumulates the
per-channel reductions in f32 — 4 activation reads + 1 write total:

    pass 1 (one fused read of g, x):  a = sum(g),  b = sum(g * xhat)
    pass 2 (one more read of g, x):   dx = gamma*inv * (g - a/N - xhat*b/N)

Forward is single-pass: the mean and variance reductions are siblings XLA
fuses into one read of x (on ResNet-50 they fuse straight into the
producing convolution's epilogue), using the shifted formulation
var = E[(x-K)^2] - E[x-K]^2. K is the caller-supplied ``shift`` vector —
the layer passes its RUNNING mean, which tracks the batch mean closely
after warm-up, killing the catastrophic cancellation the naive
E[x^2]-E[x]^2 suffers when |mean| >> std. Crucially K must NOT be
computed from x itself: a data-dependent K sequences the statistics after
a read of x and breaks the conv-epilogue fusion (measured +18 GB/step on
ResNet-50 when K was the first batch element's mean).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import registry


def _acc_dtype(x):
    """Accumulation dtype: at least f32, wider if x already is (f64 in the
    x64 test suite, where gradient checks run at double precision)."""
    return jnp.promote_types(x.dtype, jnp.float32)


def _stats(x, axes, shift):
    """Single-pass per-channel mean / variance with full-precision accum,
    shifted by the (data-independent) per-channel ``shift`` vector."""
    ad = _acc_dtype(x)
    k = jax.lax.stop_gradient(shift).astype(ad)
    xs = x.astype(ad) - k
    m1s = jnp.mean(xs, axis=axes)
    m2s = jnp.mean(xs * xs, axis=axes)
    var = jnp.maximum(m2s - m1s * m1s, 0.0)
    return m1s + k, var


@jax.custom_vjp
def batch_norm_train(x, gamma, beta, shift, eps):
    """Normalize ``x`` over all-but-last axes with batch statistics.

    ``shift`` is the variance-stabilization center (pass the running mean;
    zeros are exact too, just less stable for |mean| >> std inputs). It
    must not be computed from ``x`` — see the module docstring.

    Returns ``(y, mean, var)`` — mean/var are the f32 batch statistics the
    caller folds into its running averages (they receive zero cotangents;
    the running-statistics update is not differentiated, matching the
    reference's BatchNormalization.java train path).
    """
    y, mean, var, _ = _bn_fwd_impl(x, gamma, beta, shift, eps)
    return y, mean, var


def _bn_fwd_impl(x, gamma, beta, shift, eps):
    axes = tuple(range(x.ndim - 1))
    m1, var = _stats(x, axes, shift)
    inv = jax.lax.rsqrt(var + eps)
    ad = _acc_dtype(x)
    scale = gamma.astype(ad) * inv
    sh = beta.astype(ad) - m1 * scale
    y = x * scale.astype(x.dtype) + sh.astype(x.dtype)
    return y, m1, var, inv


def _bn_fwd(x, gamma, beta, shift, eps):
    y, m1, var, inv = _bn_fwd_impl(x, gamma, beta, shift, eps)
    return (y, m1, var), (x, gamma, m1, inv)


def _bn_bwd(res, cts):
    g = cts[0]  # cotangents for (mean, var) outputs are zero: stats feed
    # only the (undifferentiated) running-average update
    x, gamma, m1, inv = res
    cd = x.dtype
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]

    ad = _acc_dtype(x)
    m1c = m1.astype(cd)
    invc = inv.astype(cd)
    xhat = (x - m1c) * invc                       # bf16, fused
    a = jnp.sum(g.astype(ad), axis=axes)
    b = jnp.sum((g * xhat).astype(ad), axis=axes)

    scale = gamma.astype(ad) * inv
    dx = scale.astype(cd) * (
        g - (a / n).astype(cd) - xhat * (b / n).astype(cd))
    dgamma = b.astype(gamma.dtype)
    dbeta = a.astype(gamma.dtype)
    return dx, dgamma, dbeta, None, None


batch_norm_train.defvjp(_bn_fwd, _bn_bwd)


@registry.register("batch_norm_train", backend="xla")
def batch_norm_train_xla(x, gamma, beta, *, shift=None, eps):
    if shift is None:
        shift = jnp.zeros(x.shape[-1:], jnp.float32)
    return batch_norm_train(x, gamma, beta, shift, eps)
