"""Sequence/masking helpers shared by recurrent layers and graph vertices."""

from __future__ import annotations

import jax.numpy as jnp


def last_unmasked_step(x, mask):
    """[b, t, f] -> [b, f]: the last step, or the last *unmasked* step per
    example when a [b, t] mask is given (LastTimeStepVertex.java parity;
    an all-masked row clamps to step 0)."""
    if mask is None:
        return x[:, -1, :]
    m = mask.reshape(mask.shape[0], -1)
    # Index of the last nonzero mask entry (not sum-1, which is only right
    # for contiguous prefix masks): supports ALIGN_END padding and gaps.
    t = m.shape[1]
    last_nz = (t - 1) - jnp.argmax(jnp.flip(m > 0, axis=1).astype(jnp.int32),
                                   axis=1)
    idx = jnp.where(jnp.any(m > 0, axis=1), last_nz, 0).astype(jnp.int32)
    return x[jnp.arange(x.shape[0]), idx, :]
