"""Activation functions.

Capability parity with the reference's ND4J ``IActivation`` set (consumed by
deeplearning4j-nn layers, see SURVEY.md §1 L0: `IActivation` imported 18x in
deeplearning4j-nn). Implemented as pure jnp functions so XLA fuses them into
the surrounding matmul/conv; no manual backprop is needed (JAX autodiff).

Each activation is registered by its canonical lower-case name; configs store
the string name so JSON round-trips are trivial.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY: dict[str, callable] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        fn.activation_name = name
        return fn

    return deco


def get(name):
    """Resolve an activation by name (case-insensitive). Callables pass through."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)


@register("identity")
def identity(x):
    return x


@register("relu")
def relu(x):
    return jax.nn.relu(x)


@register("leakyrelu")
def leakyrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("softmax")
def softmax(x):
    # Row-wise softmax over the feature axis (last axis), matching the
    # reference's OldSoftMax-on-2d semantics.
    return jax.nn.softmax(x, axis=-1)


@register("logsoftmax")
def logsoftmax(x):
    return jax.nn.log_softmax(x, axis=-1)


@register("tanh")
def tanh(x):
    return jnp.tanh(x)


@register("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


@register("selu")
def selu(x):
    return jax.nn.selu(x)


@register("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@register("swish")
def swish(x):
    return jax.nn.swish(x)


@register("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@register("hardsigmoid")
def hardsigmoid(x):
    # DL4J/Keras-1 definition: clip(0.2*x + 0.5, 0, 1)
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@register("cube")
def cube(x):
    return x * x * x


@register("rationaltanh")
def rationaltanh(x):
    # tanh approximation used by ND4J: 1.7159 * tanh(2x/3), via the rational
    # approximation f(x) = 1.7159 * sgn(x) * (1 - 1/(1+|a|+a^2+1.41645 a^4)),
    # a = 2x/3. We keep the exact closed form (autodiff handles the rest).
    a = 2.0 * x / 3.0
    abs_a = jnp.abs(a)
    f = 1.0 - 1.0 / (1.0 + abs_a + a * a + 1.41645 * (a ** 4))
    return 1.7159 * jnp.sign(x) * f


@register("rectifiedtanh")
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@register("rrelu")
def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0):
    # Randomized ReLU: at inference the reference uses the midpoint slope.
    # The train-time randomized slope requires an rng; layers that care pass
    # one explicitly. Default = deterministic midpoint (eval semantics).
    mid = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, mid * x)


@register("thresholdedrelu")
def thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)
