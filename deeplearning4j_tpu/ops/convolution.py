"""Convolution / pooling / LRN ops, registered in the op-lowering registry.

These are the TPU-native equivalents of the reference's cuDNN helper surface
(deeplearning4j-cuda: CudnnConvolutionHelper.java:49,
CudnnSubsamplingHelper.java, CudnnLocalResponseNormalizationHelper.java) and
of the im2col+GEMM CPU path (nn/layers/convolution/ConvolutionLayer.java:287).
On TPU there is no im2col: ``lax.conv_general_dilated`` lowers straight to
MXU convolutions, and pooling lowers to ``lax.reduce_window``.

Layouts are NHWC / HWIO (TPU-preferred; the reference is NCHW — the layout
difference is absorbed here and in the preprocessors, never exposed to
kernels). Padding follows the reference's ConvolutionMode semantics
(nn/conf/ConvolutionMode.java): ``truncate`` floors partial windows,
``strict`` requires exact fit, ``same`` pads to ceil(in/stride).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops import registry


# ---------------------------------------------------------------------------
# ConvolutionMode shape math (shared by configs and runtime)
# ---------------------------------------------------------------------------

def pair(v):
    """Normalize an int-or-pair spec to a (h, w) tuple."""
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def out_size(in_size: int, kernel: int, stride: int, pad: int,
             mode: str, dilation: int = 1) -> int:
    """Output length along one spatial dim for a ConvolutionMode."""
    eff_k = (kernel - 1) * dilation + 1
    if mode == "same":
        return -(-in_size // stride)  # ceil
    n = in_size + 2 * pad - eff_k
    if mode == "strict":
        if n % stride != 0:
            raise ValueError(
                f"ConvolutionMode=strict: (in={in_size} + 2*pad={pad} - "
                f"kernel={eff_k}) = {n} is not divisible by stride={stride}. "
                f"Use mode='truncate' or 'same', or adjust the geometry "
                f"(ConvolutionMode.java parity)")
        return n // stride + 1
    if n < 0:
        raise ValueError(
            f"Kernel {eff_k} larger than padded input {in_size + 2 * pad}")
    return n // stride + 1  # truncate


def _same_pads(in_size: int, kernel: int, stride: int, dilation: int = 1):
    eff_k = (kernel - 1) * dilation + 1
    out = -(-in_size // stride)
    total = max((out - 1) * stride + eff_k - in_size, 0)
    return total // 2, total - total // 2


def spatial_padding(in_sizes, kernels, strides, pads, mode, dilations=None):
    """Per-dim (lo, hi) padding pairs implementing a ConvolutionMode."""
    dilations = dilations or [1] * len(in_sizes)
    if mode == "same":
        return [
            _same_pads(i, k, s, d)
            for i, k, s, d in zip(in_sizes, kernels, strides, dilations)
        ]
    return [(p, p) for p in pads]


# ---------------------------------------------------------------------------
# Convolution
# ---------------------------------------------------------------------------

@registry.register("conv2d", backend="xla")
def conv2d_xla(x, w, *, strides, padding, dilation=(1, 1)):
    """x: [N,H,W,C], w: [kH,kW,C_in,C_out], padding: [(lo,hi),(lo,hi)]."""
    return lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@registry.register("conv1d", backend="xla")
def conv1d_xla(x, w, *, stride, padding, dilation=1):
    """x: [N,T,C], w: [k,C_in,C_out], padding: [(lo,hi)]."""
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride,),
        padding=padding,
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )


# ---------------------------------------------------------------------------
# Exact stride-2 conv rewrites (TPU lowering details, invisible to configs)
# ---------------------------------------------------------------------------

def conv2d_space_to_depth(x, w, *, padding):
    """Exact space-to-depth lowering of an odd-kernel stride-2 conv.

    The standard TPU ResNet stem transform: a kxk/s2 conv on a
    few-channel input (e.g. 7x7/s2 on [N,224,224,3]) keeps the MXU's
    contracting dimension at C_in*kw = 21 lanes and makes XLA pad/relayout
    the big activation. Folding 2x2 spatial blocks into channels
    ([N,115,115,12] here) and re-blocking the kernel (7x7 zero-padded to
    8x8, reshaped to 4x4 over 4*C_in channels) yields a bit-identical
    stride-1 VALID conv with 4x the contracting depth and no strided
    window walk. Params keep their reference shape [kh,kw,C_in,C_out];
    the re-blocking is a per-step reshape of a tiny weight tensor, and
    autodiff derives the matching backward through it.

    Exactness: y[i,j] = sum_{di,dj,c} w[di,dj,c] * xp[2i+di, 2j+dj, c]
    with di = 2p+a, dj = 2q+b becomes a (kh+1)/2 x (kw+1)/2 window over
    the block grid; the zero row/col added to w absorbs the odd kernel.
    """
    n, h, wd, c = x.shape
    kh, kw, _, c_out = w.shape
    (lo_h, hi_h), (lo_w, hi_w) = padding
    big_kh, big_kw = kh + (kh % 2), kw + (kw % 2)
    out_h = (h + lo_h + hi_h - kh) // 2 + 1
    out_w = (wd + lo_w + hi_w - kw) // 2 + 1
    pad_h = 2 * (out_h - 1) + big_kh
    pad_w = 2 * (out_w - 1) + big_kw
    xp = jnp.pad(x, [(0, 0), (lo_h, pad_h - h - lo_h),
                     (lo_w, pad_w - wd - lo_w), (0, 0)])
    xsd = xp.reshape(n, pad_h // 2, 2, pad_w // 2, 2, c)
    xsd = xsd.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, pad_h // 2, pad_w // 2, 4 * c)
    w8 = jnp.pad(w, [(0, big_kh - kh), (0, big_kw - kw), (0, 0), (0, 0)])
    wsd = w8.reshape(big_kh // 2, 2, big_kw // 2, 2, c, c_out)
    wsd = wsd.transpose(0, 2, 1, 3, 4, 5).reshape(
        big_kh // 2, big_kw // 2, 4 * c, c_out)
    return lax.conv_general_dilated(
        xsd, wsd, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv2d_strided_1x1_as_slice(x, w, *, strides):
    """Exact rewrite of an unpadded 1x1 strided conv as slice + 1x1/s1.

    A 1x1/s2 projection conv reads every other row/column; XLA's strided
    conv lowering inserts layout copies around it (PERF.md lever #1).
    Slicing first hands XLA a dense quarter-size 1x1 conv (a plain GEMM)
    and lets the slice fuse with the producer.
    """
    sh, sw = strides
    return lax.conv_general_dilated(
        x[:, ::sh, ::sw, :], w, window_strides=(1, 1),
        padding=[(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


# ---------------------------------------------------------------------------
# Pooling (SubsamplingLayer.java semantics)
# ---------------------------------------------------------------------------

def _pool_dims(kernel, strides):
    return (1, *kernel, 1), (1, *strides, 1)


@registry.register("max_pool2d", backend="xla")
def max_pool2d_xla(x, *, kernel, strides, padding):
    window, strd = _pool_dims(kernel, strides)
    pads = [(0, 0), *padding, (0, 0)]
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    return lax.reduce_window(x, neg, lax.max, window, strd, pads)


@registry.register("avg_pool2d", backend="xla")
def avg_pool2d_xla(x, *, kernel, strides, padding):
    """Average pooling dividing by the FULL kernel size (including padding),
    matching the reference's AVG pooling (SubsamplingLayer divides by
    kernel area, not by the valid-element count)."""
    window, strd = _pool_dims(kernel, strides)
    pads = [(0, 0), *padding, (0, 0)]
    summed = lax.reduce_window(x, 0.0, lax.add, window, strd, pads)
    return summed / float(np.prod(kernel))


@registry.register("pnorm_pool2d", backend="xla")
def pnorm_pool2d_xla(x, *, kernel, strides, padding, p, eps=1e-8):
    """P-norm pooling: (sum |x|^p)^(1/p) (PoolingType.PNORM parity)."""
    window, strd = _pool_dims(kernel, strides)
    pads = [(0, 0), *padding, (0, 0)]
    powed = jnp.abs(x) ** p
    summed = lax.reduce_window(powed, 0.0, lax.add, window, strd, pads)
    return (summed + eps) ** (1.0 / p)


# ---------------------------------------------------------------------------
# Local response normalization (LocalResponseNormalization.java /
# CudnnLocalResponseNormalizationHelper.java parity)
# ---------------------------------------------------------------------------

@registry.register("lrn", backend="xla")
def lrn_xla(x, *, k=2.0, n=5, alpha=1e-4, beta=0.75):
    """Across-channel LRN on NHWC: y = x / (k + alpha*sum_{window n} x^2)^beta."""
    half = n // 2
    sq = x * x
    window = (1, 1, 1, n)
    strides = (1, 1, 1, 1)
    pads = [(0, 0), (0, 0), (0, 0), (half, n - 1 - half)]
    ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pads)
    return x / (k + alpha * ssum) ** beta
