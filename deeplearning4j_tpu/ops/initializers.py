"""Weight initialization.

Parity with the reference's ``WeightInit`` enum
(deeplearning4j-nn/.../nn/weights/WeightInit.java:47: DISTRIBUTION, ZERO,
SIGMOID_UNIFORM, UNIFORM, XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN,
XAVIER_LEGACY, RELU, RELU_UNIFORM) and WeightInitUtil.java's formulas.

TPU-native design: initializers are pure functions of (key, shape, fan_in,
fan_out, dtype) — per-layer params live in a pytree, not views into one flat
vector (XLA fusion makes the reference's contiguous-buffer trick obsolete,
see SURVEY.md §7).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_REGISTRY = {}


def register(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown weight init '{name}'. Available: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)


@register("zero")
def zero(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


@register("ones")
def ones(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


@register("uniform")
def uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # WeightInitUtil: U(-a, a), a = 1/sqrt(fanIn)
    a = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("xavier")
def xavier(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # Gaussian, var = 2/(fanIn + fanOut)
    std = math.sqrt(2.0 / (fan_in + fan_out))
    return std * jax.random.normal(key, shape, dtype)


@register("xavier_uniform")
def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("xavier_fan_in")
def xavier_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / fan_in)
    return std * jax.random.normal(key, shape, dtype)


@register("xavier_legacy")
def xavier_legacy(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / (fan_in + fan_out))
    return std * jax.random.normal(key, shape, dtype)


@register("relu")
def relu(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # He init: N(0, 2/fanIn)
    std = math.sqrt(2.0 / fan_in)
    return std * jax.random.normal(key, shape, dtype)


@register("relu_uniform")
def relu_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("sigmoid_uniform")
def sigmoid_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("normal")
def normal(key, shape, fan_in, fan_out, dtype=jnp.float32, std=1.0):
    return std * jax.random.normal(key, shape, dtype)


def distribution(dist: dict):
    """WeightInit.DISTRIBUTION: a user-supplied distribution spec, e.g.
    {"type": "normal", "mean": 0, "std": 0.01} or
    {"type": "uniform", "lower": -a, "upper": a}."""

    def init(key, shape, fan_in, fan_out, dtype=jnp.float32):
        t = dist.get("type", "normal")
        if t == "normal" or t == "gaussian":
            return dist.get("mean", 0.0) + dist.get("std", 1.0) * jax.random.normal(
                key, shape, dtype
            )
        if t == "uniform":
            return jax.random.uniform(
                key, shape, dtype,
                minval=dist.get("lower", -1.0), maxval=dist.get("upper", 1.0),
            )
        if t == "binomial":
            p = dist.get("probability", 0.5)
            n = dist.get("trials", 1)
            return jax.random.binomial(key, n, p, shape).astype(dtype)
        raise ValueError(f"Unknown distribution type {t}")

    return init


def resolve(spec):
    """Resolve a weight-init spec — a name ("xavier", ...) or a distribution
    dict ({"type": "normal", ...}) — to an init fn."""
    return distribution(spec) if isinstance(spec, dict) else get(spec)
