"""Loss functions.

Capability parity with ND4J's ``ILossFunction`` set as consumed by the
reference's output layers (SURVEY.md §1 L0: `ILossFunction` imported 15x in
deeplearning4j-nn; score + initial epsilon computed at
nn/layers/OutputLayer via ILossFunction).

Design (TPU-native): a loss is a pure function
``loss(labels, preout, activation_fn, mask=None, weights=None) -> per-example
losses`` — the *gradient* w.r.t. pre-output comes from JAX autodiff of the
whole network, so no `computeGradient` twin is needed. All losses support
per-timestep/per-example masks (broadcast against the example axis) and
optional per-output weights, matching the reference's masking semantics
(util/MaskedReductionUtil.java, GradientCheckTestsMasking).

Score convention: `score(...)` returns the mean over (unmasked) examples of
the per-example loss summed over output dims — matching DL4J's
"sum over outputs, average over minibatch" convention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import activations as _act

_REGISTRY: dict[str, "Loss"] = {}

_EPS = 1e-7


class Loss:
    """A named loss. ``per_example(labels, output)`` returns shape
    ``labels.shape`` elementwise losses (before output-dim reduction)."""

    name: str = "base"

    def elementwise(self, labels, output):
        raise NotImplementedError

    # Some losses (MCXENT+softmax) want the preoutput for numerical stability;
    # default path applies the activation then the elementwise loss.
    def per_example(self, labels, preout, activation_fn, weights=None):
        out = activation_fn(preout)
        l = self.elementwise(labels, out)
        if weights is not None:
            l = l * weights
        # Sum over output dims -> per-example scalar. Works for 2d
        # [batch, out] and, for time series, callers reshape to 2d first.
        return jnp.sum(l, axis=-1)

    def __call__(self, labels, preout, activation_fn, mask=None, weights=None):
        return self.score(labels, preout, activation_fn, mask, weights)

    def score(self, labels, preout, activation_fn, mask=None, weights=None):
        per_ex = self.per_example(labels, preout, activation_fn, weights)
        if mask is not None:
            mask = jnp.reshape(mask, per_ex.shape)
            per_ex = per_ex * mask
            denom = jnp.maximum(jnp.sum(mask), 1.0)
        else:
            denom = per_ex.size
        return jnp.sum(per_ex) / denom


def register(cls):
    inst = cls()
    _REGISTRY[inst.name] = inst
    return cls


def get(name):
    if isinstance(name, Loss):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)


@register
class MCXENT(Loss):
    """Multi-class cross entropy: -sum(y * log(p)). With a softmax activation
    the preoutput path uses log_softmax for stability (the fused
    softmax+xent the reference gets from native libnd4j ops)."""

    name = "mcxent"

    def elementwise(self, labels, output):
        return -labels * jnp.log(jnp.clip(output, _EPS, 1.0 - _EPS))

    def per_example(self, labels, preout, activation_fn, weights=None):
        if getattr(activation_fn, "activation_name", None) == "softmax":
            logp = jax.nn.log_softmax(preout, axis=-1)
            l = -labels * logp
        else:
            l = self.elementwise(labels, activation_fn(preout))
        if weights is not None:
            l = l * weights
        return jnp.sum(l, axis=-1)


@register
class NegativeLogLikelihood(MCXENT):
    name = "negativeloglikelihood"


@register
class MSE(Loss):
    """Mean squared error (per DL4J: squared error summed over outputs /
    averaged over examples... reference divides by nOut as well: LossMSE =
    LossL2 / nOut)."""

    name = "mse"

    def elementwise(self, labels, output):
        d = output - labels
        return d * d

    def per_example(self, labels, preout, activation_fn, weights=None):
        l = super().per_example(labels, preout, activation_fn, weights)
        return l / labels.shape[-1]


@register
class L2(Loss):
    name = "l2"

    def elementwise(self, labels, output):
        d = output - labels
        return d * d


@register
class L1(Loss):
    name = "l1"

    def elementwise(self, labels, output):
        return jnp.abs(output - labels)


@register
class MAE(Loss):
    name = "mae"

    def elementwise(self, labels, output):
        return jnp.abs(output - labels)

    def per_example(self, labels, preout, activation_fn, weights=None):
        l = super().per_example(labels, preout, activation_fn, weights)
        return l / labels.shape[-1]


@register
class XENT(Loss):
    """Binary cross entropy (per-output independent sigmoid)."""

    name = "xent"

    def elementwise(self, labels, output):
        p = jnp.clip(output, _EPS, 1.0 - _EPS)
        return -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))

    def per_example(self, labels, preout, activation_fn, weights=None):
        if getattr(activation_fn, "activation_name", None) == "sigmoid":
            # stable form: max(x,0) - x*y + log(1+exp(-|x|))
            x = preout
            l = jnp.maximum(x, 0.0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            l = self.elementwise(labels, activation_fn(preout))
        if weights is not None:
            l = l * weights
        return jnp.sum(l, axis=-1)


@register
class Hinge(Loss):
    name = "hinge"

    def elementwise(self, labels, output):
        # labels in {-1, +1} (or {0,1} mapped by caller)
        return jnp.maximum(0.0, 1.0 - labels * output)


@register
class SquaredHinge(Loss):
    name = "squaredhinge"

    def elementwise(self, labels, output):
        h = jnp.maximum(0.0, 1.0 - labels * output)
        return h * h


@register
class KLDivergence(Loss):
    name = "kldivergence"

    def elementwise(self, labels, output):
        y = jnp.clip(labels, _EPS, 1.0)
        p = jnp.clip(output, _EPS, 1.0)
        return y * (jnp.log(y) - jnp.log(p))


@register
class MAPE(Loss):
    name = "mape"

    def elementwise(self, labels, output):
        return 100.0 * jnp.abs((labels - output) / jnp.clip(jnp.abs(labels), _EPS))

    def per_example(self, labels, preout, activation_fn, weights=None):
        l = super().per_example(labels, preout, activation_fn, weights)
        return l / labels.shape[-1]


@register
class MSLE(Loss):
    name = "msle"

    def elementwise(self, labels, output):
        d = jnp.log1p(output) - jnp.log1p(labels)
        return d * d

    def per_example(self, labels, preout, activation_fn, weights=None):
        l = super().per_example(labels, preout, activation_fn, weights)
        return l / labels.shape[-1]


@register
class Poisson(Loss):
    name = "poisson"

    def elementwise(self, labels, output):
        p = jnp.clip(output, _EPS, None)
        return p - labels * jnp.log(p)


@register
class CosineProximity(Loss):
    name = "cosineproximity"

    def per_example(self, labels, preout, activation_fn, weights=None):
        out = activation_fn(preout)
        if weights is not None:
            out = out * weights
        num = jnp.sum(labels * out, axis=-1)
        den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
        return -num / jnp.clip(den, _EPS, None)
