"""Fused bottleneck-tail op: 1x1 expand conv + batch norm + residual add
+ ReLU in one lowering, with a recompute-based two-pass Pallas schedule.

Parity: the reference routes conv and BN through fused native kernels
(deeplearning4j-cuda/.../CudnnConvolutionHelper.java:49,
CudnnBatchNormalizationHelper.java) precisely because the composed
formulation is memory-bound. This op goes one step further than cuDNN's
per-layer helpers: it fuses the whole residual-block tail

    y = relu((x @ W - mean) * inv * gamma + beta + shortcut)

where mean/var are the BATCH statistics of the conv output z = x @ W.

Why recompute: BN needs all of z before it can normalize any of it, so a
single-pass fusion is impossible; the standard schedule (XLA's) therefore
materializes z to HBM (write) and re-reads it for the normalize+add+relu
fusion. On an HBM-bound step whose operational intensity sits ~10x below
the MXU ridge point, FLOPs are free and bytes are not: this kernel never
materializes z at all — a stats pass reads x and computes only the
per-channel sums, then an apply pass re-reads x, recomputes z on the MXU,
and writes the final block output directly. For an expand conv
(C_out = 4*C_in in ResNet bottlenecks) the extra read of x costs M*K
bytes and saves 2*M*N — profitable whenever 2*N > K. The backward applies
the same trick twice (reduction pass for the BN sums, then a pass emitting
dx/dW/dshortcut), so the conv output is never stored as an autodiff
residual either — the activation-memory saving is what the write-traffic
saving is.

The ``xla`` backend is the composed reference semantics (dot ->
ops.normalization.batch_norm_train -> add -> relu); the ``pallas`` backend
is equivalence-tested against it in tests/test_fused_block.py (the
CuDNNGradientChecks.java analogue for this kernel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import registry
from deeplearning4j_tpu.ops.normalization import batch_norm_train

# f32 intermediate tile cap: TM*TN*4 bytes <= 2 MiB
_TN_MAX = 512
_TM_CANDIDATES = (1024, 512, 256, 128, 64, 32, 16, 8)


# ------------------------------------------------------------------ xla
@registry.register("conv1x1_bn_add_relu", backend="xla")
def conv1x1_bn_add_relu_xla(x, W, gamma, beta, shortcut, *, shift, eps,
                            relu=True):
    """Composed reference semantics: z = x @ W (1x1 conv over the trailing
    channel axis); (zn, mean, var) = batch-norm(z); out = relu(zn +
    shortcut). Returns (out, mean, var) — mean/var feed the BN layer's
    running-statistics update exactly as in the unfused path."""
    K = x.shape[-1]
    N = W.shape[-1]
    z = jax.lax.dot_general(
        x.reshape(-1, K), W.reshape(K, N),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=x.dtype).reshape(x.shape[:-1] + (N,))
    zn, mean, var = batch_norm_train(z, gamma, beta, shift, eps)
    out = zn + shortcut
    if relu:
        out = jnp.maximum(out, 0)
    return out, mean, var


# --------------------------------------------------------------- pallas
_interpret = registry.pallas_interpret

# VMEM budget for one grid step of the heaviest pass (backward apply):
# the resident full [K, N] f32 dW accumulator + double-buffered tiles +
# f32 intermediates must fit comfortably in the ~16 MiB of VMEM
_VMEM_BUDGET = 12 * 1024 * 1024


def _footprint(tm, tn, K, N, itemsize):
    """Conservative VMEM estimate for the backward-apply grid step."""
    dw_acc = K * N * 4
    x_tiles = 2 * tm * K * itemsize + tm * K * (itemsize + 4)  # in+out+scr
    mn_tiles = 3 * 2 * tm * tn * itemsize        # dy, y, dsc double-buffered
    f32_inter = 3 * tm * tn * 4                  # z, xhat, dz
    return dw_acc + x_tiles + mn_tiles + f32_inter


def _pick_tm(M, dtype, K=64, N=128):
    sub = 16 if dtype == jnp.bfloat16 else 8
    itemsize = 2 if dtype == jnp.bfloat16 else 4
    tn = min(N, _TN_MAX)
    for tm in _TM_CANDIDATES:
        if (tm >= sub and M % tm == 0
                and _footprint(tm, tn, K, N, itemsize) <= _VMEM_BUDGET):
            return tm
    return None


def pallas_supported(x, W, shortcut=None):
    if x.dtype not in (jnp.bfloat16, jnp.float32):
        return False
    K, N = W.shape[-2], W.shape[-1]
    if K % 64 != 0 or N % 128 != 0:
        return False
    if shortcut is not None and shortcut.shape != x.shape[:-1] + (N,):
        # the xla backend broadcasts; the kernel needs a full-shape
        # shortcut — fall back rather than mis-tile
        return False
    M = 1
    for d in x.shape[:-1]:
        M *= d
    if _pick_tm(M, x.dtype, K, N) is None:
        return False
    if not _interpret() and jax.default_backend() != "tpu":
        return False
    return True


def _round_trip(z, cd):
    """Round the recomputed f32 conv output through the compute dtype so
    every pass (and the backward) sees the SAME values the unfused path
    would have materialized — keeps recompute bit-consistent across
    passes."""
    return z.astype(cd).astype(jnp.float32)


def _dot_f32(a, b):
    return jax.lax.dot_general(
        a, b, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# forward pass 1: per-channel sum / sum-of-squares of z = x @ W
def _stats_kernel(x_ref, w_ref, shift_ref, s1_ref, s2_ref):
    import jax.experimental.pallas as pl

    m = pl.program_id(1)
    z = _round_trip(_dot_f32(x_ref[:], w_ref[:]), x_ref.dtype)
    zs = z - shift_ref[:]
    p1 = jnp.sum(zs, axis=0, keepdims=True)
    p2 = jnp.sum(zs * zs, axis=0, keepdims=True)

    @pl.when(m == 0)
    def _():
        s1_ref[:] = p1
        s2_ref[:] = p2

    @pl.when(m != 0)
    def _():
        s1_ref[:] += p1
        s2_ref[:] += p2


# forward pass 2: recompute z, apply affine + shortcut + relu, write out
def _apply_kernel(x_ref, w_ref, scale_ref, sh_ref, sc_ref, y_ref, *, relu):
    z = _round_trip(_dot_f32(x_ref[:], w_ref[:]), x_ref.dtype)
    o = z * scale_ref[:] + sh_ref[:] + sc_ref[:].astype(jnp.float32)
    if relu:
        o = jnp.maximum(o, 0.0)
    y_ref[:] = o.astype(y_ref.dtype)


# backward pass 1: a = sum(g), b = sum(g * xhat) with g = dy * relu-mask
def _bwd_stats_kernel(x_ref, w_ref, mean_ref, inv_ref, dy_ref, y_ref,
                      a_ref, b_ref, *, relu):
    import jax.experimental.pallas as pl

    m = pl.program_id(1)
    z = _round_trip(_dot_f32(x_ref[:], w_ref[:]), x_ref.dtype)
    xhat = (z - mean_ref[:]) * inv_ref[:]
    g = dy_ref[:].astype(jnp.float32)
    if relu:
        g = jnp.where(y_ref[:].astype(jnp.float32) > 0, g, 0.0)
    pa = jnp.sum(g, axis=0, keepdims=True)
    pb = jnp.sum(g * xhat, axis=0, keepdims=True)

    @pl.when(m == 0)
    def _():
        a_ref[:] = pa
        b_ref[:] = pb

    @pl.when(m != 0)
    def _():
        a_ref[:] += pa
        b_ref[:] += pb


# backward pass 2: dz = scale*(g - a/M - xhat*b/M); dx += dz @ W^T;
# dW += x^T @ dz; dshortcut = g.  Grid is (MT, NT): m outer so the dx
# accumulator (and its out block) stays resident across the inner n loop;
# dW is a single full-size f32 block accumulated across the whole grid.
def _bwd_apply_kernel(x_ref, w_ref, mean_ref, inv_ref, scale_ref, ca_ref,
                      cb_ref, dy_ref, y_ref, dx_ref, dw_ref, dsc_ref,
                      dx_scr, *, relu, n_blocks, tn):
    import jax.experimental.pallas as pl

    m = pl.program_id(0)
    n = pl.program_id(1)
    cd = x_ref.dtype

    z = _round_trip(_dot_f32(x_ref[:], w_ref[:]), cd)
    xhat = (z - mean_ref[:]) * inv_ref[:]
    g = dy_ref[:].astype(jnp.float32)
    if relu:
        g = jnp.where(y_ref[:].astype(jnp.float32) > 0, g, 0.0)
    dz = scale_ref[:] * (g - ca_ref[:] - xhat * cb_ref[:])
    dz_cd = dz.astype(cd)

    dsc_ref[:] = g.astype(dsc_ref.dtype)

    # dx contribution: dz @ W^T (contract the N-block dim)
    dx_part = jax.lax.dot_general(
        dz_cd, w_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(n == 0)
    def _():
        dx_scr[:] = dx_part

    @pl.when(n != 0)
    def _():
        dx_scr[:] += dx_part

    @pl.when(n == n_blocks - 1)
    def _():
        dx_ref[:] = dx_scr[:].astype(dx_ref.dtype)

    # dW contribution: x^T @ dz into the n-th column block of the full
    # [K, N] f32 accumulator (resident for the whole grid; flushed once)
    dw_part = jax.lax.dot_general(
        x_ref[:], dz_cd, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(m == 0, n == 0))
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dw_ref[:, pl.dslice(n * tn, tn)] += dw_part


def _grids(M, K, N, dtype):
    tm = _pick_tm(M, dtype, K, N)
    tn = min(N, _TN_MAX)
    return tm, tn, M // tm, N // tn


def _vec(v):
    """[N] -> [1, N] f32 (TPU-friendly 2D vector block)."""
    return jnp.asarray(v, jnp.float32).reshape(1, -1)


def _fwd_impl(x2, W, gamma, beta, sc2, shift, eps, relu):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x2.shape
    N = W.shape[-1]
    tm, tn, mt, nt = _grids(M, K, N, x2.dtype)
    vspec = lambda: pl.BlockSpec((1, tn), lambda n, m: (0, n),
                                 memory_space=pltpu.VMEM)
    x_spec = pl.BlockSpec((tm, K), lambda n, m: (m, 0),
                          memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((K, tn), lambda n, m: (0, n),
                          memory_space=pltpu.VMEM)

    s1, s2 = pl.pallas_call(
        _stats_kernel,
        grid=(nt, mt),
        in_specs=[x_spec, w_spec, vspec()],
        out_specs=(vspec(), vspec()),
        out_shape=(jax.ShapeDtypeStruct((1, N), jnp.float32),
                   jax.ShapeDtypeStruct((1, N), jnp.float32)),
        interpret=_interpret(),
    )(x2, W, _vec(shift))

    k = jnp.asarray(shift, jnp.float32)
    m1 = s1[0] / M
    mean = m1 + k
    var = jnp.maximum(s2[0] / M - m1 * m1, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    scale = jnp.asarray(gamma, jnp.float32) * inv
    sh = jnp.asarray(beta, jnp.float32) - mean * scale

    y = pl.pallas_call(
        functools.partial(_apply_kernel, relu=relu),
        grid=(nt, mt),
        in_specs=[x_spec, w_spec, vspec(), vspec(),
                  pl.BlockSpec((tm, tn), lambda n, m: (m, n),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tm, tn), lambda n, m: (m, n),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), x2.dtype),
        interpret=_interpret(),
    )(x2, W, _vec(scale), _vec(sh), sc2)
    return y, mean, var, inv, scale


def _bwd_impl(x2, W, mean, inv, scale, dy2, y2, relu):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x2.shape
    N = W.shape[-1]
    tm, tn, mt, nt = _grids(M, K, N, x2.dtype)
    vspec_nm = lambda: pl.BlockSpec((1, tn), lambda n, m: (0, n),
                                    memory_space=pltpu.VMEM)
    a, b = pl.pallas_call(
        functools.partial(_bwd_stats_kernel, relu=relu),
        grid=(nt, mt),
        in_specs=[
            pl.BlockSpec((tm, K), lambda n, m: (m, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((K, tn), lambda n, m: (0, n),
                         memory_space=pltpu.VMEM),
            vspec_nm(), vspec_nm(),
            pl.BlockSpec((tm, tn), lambda n, m: (m, n),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, tn), lambda n, m: (m, n),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(vspec_nm(), vspec_nm()),
        out_shape=(jax.ShapeDtypeStruct((1, N), jnp.float32),
                   jax.ShapeDtypeStruct((1, N), jnp.float32)),
        interpret=_interpret(),
    )(x2, W, _vec(mean), _vec(inv), dy2, y2)

    ca = a[0] / M
    cb = b[0] / M

    vspec_mn = lambda: pl.BlockSpec((1, tn), lambda m, n: (0, n),
                                    memory_space=pltpu.VMEM)
    dx, dW, dsc = pl.pallas_call(
        functools.partial(_bwd_apply_kernel, relu=relu, n_blocks=nt, tn=tn),
        grid=(mt, nt),
        in_specs=[
            pl.BlockSpec((tm, K), lambda m, n: (m, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((K, tn), lambda m, n: (0, n),
                         memory_space=pltpu.VMEM),
            vspec_mn(), vspec_mn(), vspec_mn(), vspec_mn(), vspec_mn(),
            pl.BlockSpec((tm, tn), lambda m, n: (m, n),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, tn), lambda m, n: (m, n),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((tm, K), lambda m, n: (m, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((K, N), lambda m, n: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, tn), lambda m, n: (m, n),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((M, K), x2.dtype),
            jax.ShapeDtypeStruct((K, N), jnp.float32),
            jax.ShapeDtypeStruct((M, N), x2.dtype),
        ),
        scratch_shapes=[pltpu.VMEM((tm, K), jnp.float32)],
        interpret=_interpret(),
    )(x2, W, _vec(mean), _vec(inv), _vec(scale), _vec(ca), _vec(cb),
      dy2, y2)

    dgamma = b[0]
    dbeta = a[0]
    return dx, dW, dgamma, dbeta, dsc


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_pallas(x2, W, gamma, beta, sc2, eps, relu, shift):
    y, mean, var, _, _ = _fwd_impl(x2, W, gamma, beta, sc2, shift, eps, relu)
    return y, mean, var


def _fused_fwd(x2, W, gamma, beta, sc2, eps, relu, shift):
    y, mean, var, inv, scale = _fwd_impl(x2, W, gamma, beta, sc2, shift, eps,
                                         relu)
    return (y, mean, var), (x2, W, gamma, mean, inv, scale, y)


def _fused_bwd(eps, relu, res, cts):
    dy = cts[0]  # mean/var feed only the (undifferentiated) running update
    x2, W, gamma, mean, inv, scale, y = res
    dx, dW, dgamma, dbeta, dsc = _bwd_impl(
        x2, W, mean, inv, scale, dy.astype(x2.dtype), y, relu)
    return (dx, dW.astype(W.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype), dsc, None)


_fused_pallas.defvjp(_fused_fwd, _fused_bwd)


@registry.register("conv1x1_bn_add_relu", backend="pallas")
def conv1x1_bn_add_relu_pallas(x, W, gamma, beta, shortcut, *, shift, eps,
                               relu=True):
    """Two-pass recompute Pallas schedule (see module docstring); silently
    delegates to the composed xla backend for configurations the kernel
    does not cover — the same graceful fallback the reference's helper
    loading performs when cuDNN is absent (ConvolutionLayer.java:69-76)."""
    if not pallas_supported(x, W, shortcut):
        return conv1x1_bn_add_relu_xla(x, W, gamma, beta, shortcut,
                                       shift=shift, eps=eps, relu=relu)
    K = x.shape[-1]
    N = W.shape[-1]
    x2 = x.reshape(-1, K)
    sc2 = shortcut.astype(x.dtype).reshape(-1, N)
    y, mean, var = _fused_pallas(x2, W.reshape(K, N).astype(x.dtype),
                                 jnp.asarray(gamma, jnp.float32),
                                 jnp.asarray(beta, jnp.float32),
                                 sc2, float(eps), bool(relu),
                                 jnp.asarray(shift, jnp.float32))
    return y.reshape(shortcut.shape), mean, var


# ------------------------------------------------------- xla recompute
# The schedule the Pallas kernel above implements, expressed as pure XLA:
# measured on the axon TPU stack, Pallas DMA streams at 15-60 GB/s
# against XLA's ~700 GB/s (see PERF.md round 4), so the SAME two-pass
# recompute is lowered through XLA convs instead. Key facts this relies
# on (verified via compiled cost analysis on the v5e):
# - a conv whose output feeds ONLY sibling reductions fuses them into
#   its epilogue WITHOUT materializing the conv output (the stats pass
#   reads x and writes two [N] vectors — nothing else);
# - elementwise chains do NOT output-fuse into convs on this XLA, so
#   the composed formulation materializes z and re-reads it; the
#   recompute apply pass pays one z materialization but the stats pass
#   pays none, and z is never an autodiff residual;
# - jax.lax.optimization_barrier on x blocks CSE from merging the stats
#   conv with the apply conv (a merge would re-serialize the chain and
#   restore the status-quo schedule).


def _conv1x1(x, W):
    """1x1 conv over the trailing channel axis as a convolution HLO (NOT a
    dot: only the conv fuses sibling reductions into its epilogue on this
    XLA). Accepts any leading shape; non-4D inputs ride through a [M,1,1,K]
    view."""
    K, N = W.shape[-2], W.shape[-1]
    x4 = x if x.ndim == 4 else x.reshape(-1, 1, 1, K)
    z = jax.lax.conv_general_dilated(
        x4, W.reshape(1, 1, K, N), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return z if x.ndim == 4 else z.reshape(x.shape[:-1] + (N,))


def _chan_stats(z, shift):
    """Per-channel mean/var over all-but-last axes, f32 accumulation,
    shifted single-pass variance (see ops/normalization._stats)."""
    axes = tuple(range(z.ndim - 1))
    n = 1
    for a in axes:
        n *= z.shape[a]
    k = jax.lax.stop_gradient(jnp.asarray(shift, jnp.float32))
    zs = z.astype(jnp.float32) - k
    s1 = jnp.sum(zs, axis=axes)
    s2 = jnp.sum(zs * zs, axis=axes)
    m1 = s1 / n
    mean = m1 + k
    var = jnp.maximum(s2 / n - m1 * m1, 0.0)
    return mean, var, n


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def conv1x1_bn_add_relu_recompute(x, W, gamma, beta, shortcut, eps, relu,
                                  shift):
    y, mean, var, _, _ = _recompute_fwd_impl(x, W, gamma, beta, shortcut,
                                             eps, relu, shift)
    return y, mean, var


def _recompute_fwd_impl(x, W, gamma, beta, shortcut, eps, relu, shift):
    cd = x.dtype
    # stats pass: conv consumed ONLY by the fused reductions
    mean, var, _ = _chan_stats(_conv1x1(x, W), shift)
    inv = jax.lax.rsqrt(var + eps)
    scale = jnp.asarray(gamma, jnp.float32) * inv
    sh = jnp.asarray(beta, jnp.float32) - mean * scale
    # apply pass: recompute the conv (barrier blocks CSE with the stats
    # conv) and write the block output directly
    z2 = _conv1x1(jax.lax.optimization_barrier(x), W)
    o = z2 * scale.astype(cd) + sh.astype(cd) + shortcut.astype(cd)
    if relu:
        o = jnp.maximum(o, 0)
    return o, mean, var, inv, scale


def _recompute_fwd(x, W, gamma, beta, shortcut, eps, relu, shift):
    y, mean, var, inv, scale = _recompute_fwd_impl(
        x, W, gamma, beta, shortcut, eps, relu, shift)
    return (y, mean, var), (x, W, gamma, mean, inv, scale, y)


def _recompute_bwd(eps, relu, res, cts):
    g = cts[0]  # stats outputs feed only the running update: zero cotangent
    x, W, gamma, mean, inv, scale, y = res
    cd = x.dtype
    g = g.astype(cd)
    if relu:
        g = jnp.where(y > 0, g, jnp.zeros_like(g))
    axes = tuple(range(g.ndim - 1))
    n = 1
    for a in axes:
        n *= g.shape[a]

    meanc = mean.astype(cd)
    invc = inv.astype(cd)

    # reduction pass: recompute z, all reductions fuse into the conv
    z1 = _conv1x1(jax.lax.optimization_barrier(x), W)
    xhat1 = (z1 - meanc) * invc
    a = jnp.sum(g.astype(jnp.float32), axis=axes)
    b = jnp.sum((g * xhat1).astype(jnp.float32), axis=axes)

    # dz pass: recompute z again (second barrier keeps it separate), form
    # the BN input-cotangent in compute dtype (ops/normalization._bn_bwd
    # arithmetic), then the two matmuls
    z2 = _conv1x1(jax.lax.optimization_barrier(x), W)
    xhat2 = (z2 - meanc) * invc
    dz = scale.astype(cd) * (
        g - (a / n).astype(cd) - xhat2 * (b / n).astype(cd))

    K, N = W.shape[-2], W.shape[-1]
    dx = _conv1x1(dz, jnp.swapaxes(W, -1, -2))
    dW = jax.lax.dot_general(
        x.reshape(-1, K), dz.reshape(-1, N),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dgamma = b.astype(gamma.dtype)
    dbeta = a.astype(gamma.dtype)
    return dx, dW.astype(W.dtype), dgamma, dbeta, g, None


conv1x1_bn_add_relu_recompute.defvjp(_recompute_fwd, _recompute_bwd)


@registry.register("conv1x1_bn_add_relu", backend="xla_recompute")
def conv1x1_bn_add_relu_xla_recompute(x, W, gamma, beta, shortcut, *,
                                      shift, eps, relu=True):
    """Two-pass recompute schedule lowered through XLA (the backend the
    block-fusion pass uses on TPU). Same signature/semantics as the
    composed backend; equivalence-tested in tests/test_fused_block.py."""
    W2 = W.reshape(W.shape[-2], W.shape[-1]).astype(x.dtype)
    sc = jnp.broadcast_to(shortcut, x.shape[:-1] + (W2.shape[-1],))
    y, mean, var = conv1x1_bn_add_relu_recompute(
        x, W2, jnp.asarray(gamma, jnp.float32),
        jnp.asarray(beta, jnp.float32), sc, float(eps), bool(relu),
        jnp.asarray(shift, jnp.float32))
    return y, mean, var
