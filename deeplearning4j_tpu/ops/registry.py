"""Op-lowering registry — the TPU-native analogue of the reference's cuDNN
Helper seam.

In the reference, layers reflectively load a per-layer ``*Helper`` and route
forward/backward through cuDNN when present
(nn/layers/convolution/ConvolutionLayer.java:69-76, :274-275;
nn/layers/normalization/BatchNormalization.java:53-60). Here the same seam is
an explicit registry: every hot op has an ``xla`` implementation (jax.numpy /
lax — what XLA lowers and fuses) and may have a ``pallas`` override (a
hand-written TPU kernel) that is used when enabled. The backend-equivalence
test harness (tests/test_backend_equivalence.py, the CuDNNGradientChecks
analogue from SURVEY.md §4) asserts pallas == xla on identical inputs.

Usage:
    @ops.register("conv2d", backend="xla")
    def conv2d_xla(...): ...

    impl = ops.get("conv2d")          # resolves preference order
    y = impl(x, w, ...)
"""

from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()
_IMPLS: dict[str, dict[str, callable]] = {}

# Preference order; "pallas" first means use the hand kernel when one exists.
_DEFAULT_ORDER = ("pallas", "xla") if os.environ.get(
    "DL4J_TPU_PREFER_PALLAS", "1"
) == "1" else ("xla",)
_order = list(_DEFAULT_ORDER)


def pallas_interpret() -> bool:
    """Shared interpret-mode switch for every pallas backend (set
    DL4J_TPU_PALLAS_INTERPRET=1 to run the hand kernels through the
    Pallas interpreter off-TPU — how the equivalence tests exercise them
    on CPU)."""
    return os.environ.get("DL4J_TPU_PALLAS_INTERPRET", "0") == "1"


def register(name: str, backend: str = "xla"):
    def deco(fn):
        with _LOCK:
            _IMPLS.setdefault(name, {})[backend] = fn
        return fn

    return deco


def get(name: str, backend: str | None = None):
    impls = _IMPLS.get(name)
    if not impls:
        raise KeyError(f"No implementation registered for op '{name}'")
    if backend is not None:
        return impls[backend]
    for b in _order:
        if b in impls:
            return impls[b]
    raise KeyError(
        f"Op '{name}' has no implementation in preferred backends {_order}; "
        f"registered: {sorted(impls)}")


def backends(name: str):
    return sorted(_IMPLS.get(name, {}))


def available_ops():
    return sorted(_IMPLS)


def set_preference(order):
    """Set global backend preference order, e.g. ("xla",) to disable pallas."""
    global _order
    with _LOCK:
        _order = list(order)


class use_backend:
    """Context manager pinning the preference order (for equivalence tests)."""

    def __init__(self, *order):
        self.order = order

    def __enter__(self):
        self.prev = list(_order)
        set_preference(self.order)
        return self

    def __exit__(self, *exc):
        set_preference(self.prev)
        return False
