"""Load-anything model loader (ModelGuesser.java parity).

The reference's ``ModelGuesser`` sniffs a file and dispatches to the
right restore path (own zips vs Keras HDF5). Here four formats exist, so
the sniff covers: this framework's zip (``coefficients.npz`` member),
reference DL4J zips (``coefficients.bin`` member), Keras HDF5
(``model_config`` root attribute), and orbax checkpoint directories
(``meta.json`` + ``tree/``)."""

from __future__ import annotations

import json
import os
import zipfile


def guess_format(path: str) -> str:
    """One of {"tpu_zip", "dl4j_zip", "keras_h5", "orbax"}."""
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "meta.json")):
            return "orbax"
        raise ValueError(f"{path}: directory without an orbax meta.json")
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
        if "coefficients.npz" in names:
            return "tpu_zip"
        if "coefficients.bin" in names:
            return "dl4j_zip"
        raise ValueError(
            f"{path}: zip holds neither coefficients.npz (this framework) "
            "nor coefficients.bin (reference DL4J)")
    # HDF5 with a Keras model_config (a weights-only .h5 is not a model)
    with open(path, "rb") as f:
        is_hdf5 = f.read(8) == b"\x89HDF\r\n\x1a\n"
    if is_hdf5:
        import h5py
        with h5py.File(path, "r") as f:
            if "model_config" in f.attrs:
                return "keras_h5"
        raise ValueError(
            f"{path}: HDF5 file without a model_config attribute "
            "(weights-only files need the architecture too)")
    raise ValueError(f"{path}: unrecognized model file format")


def load_model(path: str, **kwargs):
    """Restore a network from any supported format (ModelGuesser.java's
    ``loadModelGuess``). kwargs pass through to the specific restorer
    (e.g. ``input_type=``/``dtype=`` for DL4J zips, ``mesh=`` for
    orbax)."""
    fmt = guess_format(path)
    if fmt == "tpu_zip":
        # restore_model dispatches on the zip's own metadata.json
        from deeplearning4j_tpu.utils.serialization import restore_model
        return restore_model(path, **kwargs)
    if fmt == "dl4j_zip":
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_multi_layer_network_from_dl4j)
        return restore_multi_layer_network_from_dl4j(path, **kwargs)
    if fmt == "keras_h5":
        from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
        from deeplearning4j_tpu.modelimport.keras import (
            import_keras_model, import_keras_sequential_model)
        archive = Hdf5Archive(path)
        try:
            cls = (archive.model_config() or {}).get("class_name")
        finally:
            archive.close()
        return (import_keras_sequential_model(path, **kwargs)
                if cls == "Sequential" else import_keras_model(path, **kwargs))
    # orbax
    from deeplearning4j_tpu.utils.checkpoint import (
        restore_computation_graph, restore_multi_layer_network)
    with open(os.path.join(path, "meta.json")) as f:
        kind = json.load(f)["kind"]
    return (restore_computation_graph(path, **kwargs) if kind == "graph"
            else restore_multi_layer_network(path, **kwargs))
