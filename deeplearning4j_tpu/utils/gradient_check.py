"""Numeric gradient checking — the backbone of correctness testing.

Parity: gradientcheck/GradientCheckUtil.java (496 LoC) — perturb each param
by ±epsilon, compare the central-difference numeric gradient against the
analytic gradient, flag relative errors above threshold. Here the "analytic"
gradient is JAX autodiff of the same jitted loss the train step uses, so a
pass validates the entire forward graph's differentiation.

Run under float64 (tests enable jax x64 and use a float64 DtypePolicy) with
epsilon ~1e-6, maxRelError 1e-5 — the reference's standard settings.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class GradCheckResult:
    total_checked: int = 0
    total_failed: int = 0
    max_rel_error: float = 0.0
    failures: list = field(default_factory=list)

    @property
    def passed(self) -> bool:
        return self.total_failed == 0 and self.total_checked > 0


def gradient_check_fn(loss_fn, params, *, epsilon: float = 1e-6,
                      max_rel_error: float = 1e-5,
                      min_abs_error: float = 1e-10,
                      sample_per_leaf: int | None = None,
                      seed: int = 0) -> GradCheckResult:
    """Check d loss_fn / d params at ``params``.

    ``loss_fn(params) -> scalar`` must be deterministic. ``sample_per_leaf``
    caps how many scalar entries are perturbed per parameter array (random
    subset) to bound runtime on big layers.
    """
    loss_jit = jax.jit(loss_fn)
    grads = jax.jit(jax.grad(loss_fn))(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    grad_leaves = jax.tree_util.tree_leaves(grads)
    rng = np.random.default_rng(seed)
    res = GradCheckResult()
    paths = [jax.tree_util.keystr(kp)
             for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]]

    for li, (leaf, gleaf, path) in enumerate(zip(leaves, grad_leaves, paths)):
        flat = np.asarray(leaf).reshape(-1).copy()
        gflat = np.asarray(gleaf).reshape(-1)
        n = flat.size
        idxs = np.arange(n)
        if sample_per_leaf is not None and n > sample_per_leaf:
            idxs = rng.choice(n, size=sample_per_leaf, replace=False)
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + epsilon
            new_leaves = list(leaves)
            new_leaves[li] = jnp.asarray(flat.reshape(leaf.shape), leaf.dtype)
            plus = float(loss_jit(jax.tree_util.tree_unflatten(treedef, new_leaves)))
            flat[i] = orig - epsilon
            new_leaves[li] = jnp.asarray(flat.reshape(leaf.shape), leaf.dtype)
            minus = float(loss_jit(jax.tree_util.tree_unflatten(treedef, new_leaves)))
            flat[i] = orig
            numeric = (plus - minus) / (2.0 * epsilon)
            analytic = float(gflat[i])
            denom = abs(numeric) + abs(analytic)
            rel = 0.0 if denom == 0 else abs(numeric - analytic) / denom
            res.total_checked += 1
            res.max_rel_error = max(res.max_rel_error, rel)
            if rel > max_rel_error and abs(numeric - analytic) > min_abs_error:
                res.total_failed += 1
                res.failures.append(
                    {"param": path, "index": int(i), "numeric": numeric,
                     "analytic": analytic, "rel_error": rel})
    return res


def check_network_gradients(net, ds, *, epsilon: float = 1e-6,
                            max_rel_error: float = 1e-5,
                            min_abs_error: float = 1e-9,
                            sample_per_leaf: int | None = 128,
                            seed: int = 0) -> GradCheckResult:
    """GradientCheckUtil.checkGradients equivalent for a MultiLayerNetwork
    (or any object exposing ``_loss``). Dropout must be 0 in the checked
    config (matching the reference's precondition)."""
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)

    def loss_fn(params):
        loss, _ = net._loss(params, net.state, x, y, fmask, lmask,
                            rng=None, train=True)
        return loss

    return gradient_check_fn(
        loss_fn, net.params, epsilon=epsilon, max_rel_error=max_rel_error,
        min_abs_error=min_abs_error, sample_per_leaf=sample_per_leaf,
        seed=seed)
