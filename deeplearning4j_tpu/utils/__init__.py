"""Utilities: gradient checking, model serialization, misc."""
