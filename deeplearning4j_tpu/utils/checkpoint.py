"""Sharded checkpoint/resume via orbax — the distributed tier.

Parity+: the reference's ModelSerializer zip (util/ModelSerializer.java,
covered by utils/serialization.py) is a single-file, single-process
format whose Spark master holds the only parameter copy (SURVEY.md §5.4
"no distributed checkpoint"). The TPU-native story (§5.3: preemption-
resume IS the fault-tolerance answer) is an orbax checkpoint of
{config JSON, param/state/opt pytrees, step, epoch}: every process
writes its own parameter shards in parallel, and restore re-shards onto
whatever mesh the restoring run provides — a multi-host run can resume
on a different topology.

Use::

    from deeplearning4j_tpu.utils.checkpoint import (
        save_checkpoint, restore_multi_layer_network,
        restore_computation_graph)

    save_checkpoint(net, "/ckpt/step_1000")          # any net, meshed or not
    net = restore_multi_layer_network("/ckpt/step_1000")
    net = restore_computation_graph("/ckpt/step_1000", mesh=my_mesh)
"""

from __future__ import annotations

import json
import os
import re

import jax
import numpy as np


class CheckpointError(RuntimeError):
    """Base error for checkpoint discovery/restore failures."""


class IncompleteCheckpointError(CheckpointError):
    """Restore hit a partial save (tree committed, ``meta.json`` never
    renamed in) — the footprint a crash between the two commits leaves.
    Names the offending directory instead of surfacing a raw orbax
    traceback; ``find_latest_checkpoint`` skips such directories."""


_CKPTR = None

# Fault-injection seam: called between the (atomic) orbax tree commit and
# the meta.json rename — the exact window a real preemption can hit.
# resilience/faultinject.py installs a crasher here so the partial-save
# recovery path is exercised by tests instead of hoped for.
_POST_COMMIT_HOOK = None


def _checkpointer():
    # one cached async checkpointer: constructing per call would spawn a
    # fresh background worker thread each save in a periodic-save loop
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _net_kind(net) -> str:
    if isinstance(net, CheckpointSnapshot):
        return net.kind
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    return "graph" if isinstance(net, ComputationGraph) else "multilayer"


class CheckpointSnapshot:
    """A frozen, donation-safe view of everything ``save_checkpoint``
    reads from a net (params/state/opt_state trees + counters + config).

    The fused train step donates the previous params/opt buffers to XLA,
    so a background checkpoint writer cannot safely hold references to
    the live ``net.params`` while the loop keeps stepping —
    :func:`snapshot_for_checkpoint` takes ``jnp.copy`` of every leaf
    (cheap asynchronous device-side copies) at submit time; the writer
    then serializes the snapshot at its leisure."""

    __slots__ = ("kind", "conf", "params", "state", "opt_state",
                 "iteration", "epoch")

    def __init__(self, kind, conf, params, state, opt_state, iteration,
                 epoch):
        self.kind = kind
        self.conf = conf
        self.params = params
        self.state = state
        self.opt_state = opt_state
        self.iteration = iteration
        self.epoch = epoch


def snapshot_for_checkpoint(net) -> CheckpointSnapshot:
    """Device-side copy of the net's checkpointable trees (see
    :class:`CheckpointSnapshot`). ``save_checkpoint(snapshot, path)``
    writes exactly what ``save_checkpoint(net, path)`` would have written
    at this moment."""
    import jax.numpy as jnp

    def copy_tree(tree):
        return jax.tree_util.tree_map(jnp.copy, tree)

    return CheckpointSnapshot(
        kind=_net_kind(net), conf=net.conf,
        params=copy_tree(net.params), state=copy_tree(net.state or {}),
        opt_state=copy_tree(net.opt_state),
        iteration=int(net.iteration), epoch=int(net.epoch))


def save_checkpoint(net, path: str, stats=None, extra_meta=None):
    """Write {config, params, state, opt_state, step, epoch} under
    ``path`` (a directory). In a multi-process runtime every process must
    call this (orbax coordinates the parallel shard writes).

    Crash-safety: the tree commit is atomic (orbax) and meta.json lands
    via rename AFTER it, so a preempted save leaves either a complete
    checkpoint or one missing meta.json (detected at restore). Write each
    periodic save to a FRESH step directory (``.../step_1000`` as in the
    module example) — overwriting one path in place cannot be made
    crash-atomic across the two commits.

    ``stats``: optional parallel.stats.TrainingStatsCollector — records
    the whole save (shard writes + cross-process barrier) as a
    ``checkpoint_barrier`` EventStats phase for the training timeline.

    ``extra_meta``: optional JSON-serializable dict merged into
    ``meta.json`` (reserved keys rejected) — the seam the resilience
    supervisor uses to make input-pipeline position
    (``Pipeline.state_dict()``, key ``"datapipe"``) part of the
    checkpoint."""
    if stats is not None:
        with stats.time_phase("checkpoint_barrier"):
            return _save_checkpoint_inner(net, path, extra_meta)
    return _save_checkpoint_inner(net, path, extra_meta)


def _save_checkpoint_inner(net, path: str, extra_meta=None):
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    tree = {"params": net.params, "state": net.state or {},
            "opt_state": net.opt_state}
    ckptr.save(os.path.join(path, "tree"), tree, force=True)
    ckptr.wait_until_finished()
    if _POST_COMMIT_HOOK is not None:
        _POST_COMMIT_HOOK(path)
    if jax.process_index() == 0:
        meta = {
            "kind": _net_kind(net),
            "config": net.conf.to_json(),
            "iteration": int(net.iteration),
            "epoch": int(net.epoch),
            "format_version": 1,
        }
        if extra_meta:
            clash = set(extra_meta) & set(meta)
            if clash:
                raise ValueError(f"extra_meta may not override reserved "
                                 f"meta.json keys: {sorted(clash)}")
            meta.update(extra_meta)
        tmp = os.path.join(path, ".meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, "meta.json"))
    if jax.process_count() > 1:
        # cross-process barrier AFTER the meta.json rename: without it a
        # non-zero process returns as soon as its own shard writes land
        # and can race a restore/guess_format against process 0 still
        # finalizing — save_checkpoint must mean "complete everywhere"
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("dl4j_tpu_ckpt_save_done")
    return path


_STEP_DIR = re.compile(r"^step_(\d+)$")


def read_checkpoint_meta(path: str) -> dict:
    """The checkpoint's ``meta.json`` dict (counters, config, plus any
    ``extra_meta`` a save recorded — e.g. the supervisor's ``datapipe``
    pipeline state)."""
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)


def is_valid_checkpoint(path: str) -> bool:
    """A complete save: the orbax tree directory AND ``meta.json`` (which
    lands via rename strictly after the tree commit, so its presence
    certifies the whole checkpoint)."""
    return (os.path.isdir(os.path.join(path, "tree"))
            and os.path.isfile(os.path.join(path, "meta.json")))


def find_latest_checkpoint(directory: str):
    """Newest *valid* ``step_<n>`` checkpoint under ``directory``, or None.

    Partial saves (a crash between the tree commit and the meta.json
    rename leaves a step directory with no meta.json) are skipped — the
    auto-resume contract is "newest checkpoint that is provably
    complete", never "newest directory". Ordering is by step number, not
    mtime: a rolled-back run may legitimately rewrite an older step
    later."""
    if not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        m = _STEP_DIR.match(name)
        if m is None:
            continue
        path = os.path.join(directory, name)
        if int(m.group(1)) > best_step and is_valid_checkpoint(path):
            best, best_step = path, int(m.group(1))
    return best


def _restore(path: str, expect_kind: str, mesh=None, data_axis: str = "data",
             model_axis=None, tp_rules=None):
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not os.path.isfile(os.path.join(path, "meta.json")):
        raise IncompleteCheckpointError(
            f"partial checkpoint at {path}: meta.json is missing (a save "
            "was interrupted between the tree commit and the meta rename)."
            " Resume from the previous step directory — "
            "find_latest_checkpoint() skips partial saves automatically")
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta["kind"] != expect_kind:
        raise ValueError(
            f"checkpoint at {path} holds a {meta['kind']} net, not a "
            f"{expect_kind}")

    if expect_kind == "graph":
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = ComputationGraphConfiguration.from_json(meta["config"])
        net = ComputationGraph(conf).init(structure_only=True)
    else:
        from deeplearning4j_tpu.nn.conf.core import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = MultiLayerConfiguration.from_json(meta["config"])
        net = MultiLayerNetwork(conf).init(structure_only=True)

    # target structure from the (structure-only) init; restore re-shards
    # onto the requested mesh (replicated params) or host memory
    target = {"params": net.params, "state": net.state or {},
              "opt_state": net.opt_state}

    def as_restore_type(x):
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            sharding = NamedSharding(mesh, P())
        else:
            # explicit local placement: falling back to the sharding
            # recorded in the checkpoint would break cross-topology
            # resume (saved on 8 devices, restored on 1)
            from jax.sharding import SingleDeviceSharding
            sharding = SingleDeviceSharding(jax.local_devices()[0])
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    abstract = jax.tree_util.tree_map(as_restore_type, target)
    ckptr = _checkpointer()
    tree = ckptr.restore(os.path.join(path, "tree"), abstract)

    net.params = tree["params"]
    net.state = tree["state"]
    net.opt_state = tree["opt_state"]
    net.iteration = int(meta["iteration"])
    net.epoch = int(meta["epoch"])
    if mesh is not None:
        # model_axis/tp_rules must ride through or a dp x tp net silently
        # resumes fully replicated (and may not even fit)
        net.use_mesh(mesh, data_axis, model_axis=model_axis,
                     tp_rules=tp_rules)
    return net


def restore_multi_layer_network(path: str, mesh=None, data_axis="data",
                                model_axis=None, tp_rules=None):
    """Resume a sequential net (+ optionally place it on ``mesh``;
    ``model_axis``/``tp_rules`` restore a tensor-parallel placement)."""
    return _restore(path, "multilayer", mesh, data_axis, model_axis,
                    tp_rules)


def restore_computation_graph(path: str, mesh=None, data_axis="data",
                              model_axis=None, tp_rules=None):
    """Resume a DAG net (+ optionally place it on ``mesh``;
    ``model_axis``/``tp_rules`` restore a tensor-parallel placement)."""
    return _restore(path, "graph", mesh, data_axis, model_axis, tp_rules)
