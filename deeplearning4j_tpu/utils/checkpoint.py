"""Sharded checkpoint/resume via orbax — the distributed tier.

Parity+: the reference's ModelSerializer zip (util/ModelSerializer.java,
covered by utils/serialization.py) is a single-file, single-process
format whose Spark master holds the only parameter copy (SURVEY.md §5.4
"no distributed checkpoint"). The TPU-native story (§5.3: preemption-
resume IS the fault-tolerance answer) is an orbax checkpoint of
{config JSON, param/state/opt pytrees, step, epoch}: every process
writes its own parameter shards in parallel, and restore re-shards onto
whatever mesh the restoring run provides — a multi-host run can resume
on a different topology.

Schema v2 (elastic resharding): every save also writes a
``layout.json`` manifest beside the tree — per-leaf partition specs as
actually placed (params AND optimizer slots), mesh axis names/shape,
process count/index, and the datapipe shard ``(n, i)`` cursor positions
the supervisor recorded. Restore onto ANY target mesh places each leaf
directly into its target ``NamedSharding`` (specs recomputed for the
target mesh via ``param_specs``/``opt_state_specs``, ``tp_rules``
accepted in exact-path or ``(regex, spec)`` form) — one materialization,
no replicate-then-``use_mesh`` double hop, so a run preempted on 8
devices resumes on 4 (or 1, or 16) with bit-identical params.

Use::

    from deeplearning4j_tpu.utils.checkpoint import (
        save_checkpoint, restore_multi_layer_network,
        restore_computation_graph)

    save_checkpoint(net, "/ckpt/step_1000")          # any net, meshed or not
    net = restore_multi_layer_network("/ckpt/step_1000")
    net = restore_computation_graph("/ckpt/step_1000", mesh=my_mesh)
"""

from __future__ import annotations

import json
import os
import re

import jax
import numpy as np


class CheckpointError(RuntimeError):
    """Base error for checkpoint discovery/restore failures."""


class IncompleteCheckpointError(CheckpointError):
    """Restore hit a partial save (tree committed, ``meta.json`` never
    renamed in) — the footprint a crash between the two commits leaves.
    Names the offending directory instead of surfacing a raw orbax
    traceback; ``find_latest_checkpoint`` skips such directories."""


_CKPTR = None

# Fault-injection seam: called between the (atomic) orbax tree commit and
# the meta.json rename — the exact window a real preemption can hit.
# resilience/faultinject.py installs a crasher here so the partial-save
# recovery path is exercised by tests instead of hoped for.
_POST_COMMIT_HOOK = None


def _checkpointer():
    # one cached async checkpointer: constructing per call would spawn a
    # fresh background worker thread each save in a periodic-save loop
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _net_kind(net) -> str:
    if isinstance(net, CheckpointSnapshot):
        return net.kind
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    return "graph" if isinstance(net, ComputationGraph) else "multilayer"


class CheckpointSnapshot:
    """A frozen, donation-safe view of everything ``save_checkpoint``
    reads from a net (params/state/opt_state trees + counters + config).

    The fused train step donates the previous params/opt buffers to XLA,
    so a background checkpoint writer cannot safely hold references to
    the live ``net.params`` while the loop keeps stepping —
    :func:`snapshot_for_checkpoint` takes ``jnp.copy`` of every leaf
    (cheap asynchronous device-side copies) at submit time; the writer
    then serializes the snapshot at its leisure."""

    __slots__ = ("kind", "conf", "params", "state", "opt_state",
                 "iteration", "epoch", "_mesh", "_mesh_detail")

    def __init__(self, kind, conf, params, state, opt_state, iteration,
                 epoch, mesh=None, mesh_detail=None):
        self.kind = kind
        self.conf = conf
        self.params = params
        self.state = state
        self.opt_state = opt_state
        self.iteration = iteration
        self.epoch = epoch
        self._mesh = mesh                  # (Mesh, data_axis) or None
        self._mesh_detail = mesh_detail    # {model_axis, tp_rules} or None


def snapshot_for_checkpoint(net) -> CheckpointSnapshot:
    """Device-side copy of the net's checkpointable trees (see
    :class:`CheckpointSnapshot`). ``save_checkpoint(snapshot, path)``
    writes exactly what ``save_checkpoint(net, path)`` would have written
    at this moment."""
    import jax.numpy as jnp

    def copy_tree(tree):
        return jax.tree_util.tree_map(jnp.copy, tree)

    return CheckpointSnapshot(
        kind=_net_kind(net), conf=net.conf,
        params=copy_tree(net.params), state=copy_tree(net.state or {}),
        opt_state=copy_tree(net.opt_state),
        iteration=int(net.iteration), epoch=int(net.epoch),
        mesh=getattr(net, "_mesh", None),
        mesh_detail=getattr(net, "_mesh_detail", None))


def save_checkpoint(net, path: str, stats=None, extra_meta=None):
    """Write {config, params, state, opt_state, step, epoch} under
    ``path`` (a directory). In a multi-process runtime every process must
    call this (orbax coordinates the parallel shard writes).

    Crash-safety: the tree commit is atomic (orbax) and meta.json lands
    via rename AFTER it, so a preempted save leaves either a complete
    checkpoint or one missing meta.json (detected at restore). Write each
    periodic save to a FRESH step directory (``.../step_1000`` as in the
    module example) — overwriting one path in place cannot be made
    crash-atomic across the two commits.

    ``stats``: optional parallel.stats.TrainingStatsCollector — records
    the whole save (shard writes + cross-process barrier) as a
    ``checkpoint_barrier`` EventStats phase for the training timeline.

    ``extra_meta``: optional JSON-serializable dict merged into
    ``meta.json`` (reserved keys rejected) — the seam the resilience
    supervisor uses to make input-pipeline position
    (``Pipeline.state_dict()``, key ``"datapipe"``) part of the
    checkpoint."""
    if stats is not None:
        with stats.time_phase("checkpoint_barrier"):
            return _save_checkpoint_inner(net, path, extra_meta)
    return _save_checkpoint_inner(net, path, extra_meta)


def _leaf_spec_json(leaf):
    """The leaf's PartitionSpec as JSON (None → replicated/unplaced;
    axis entries are names or lists of names), or None when the leaf
    carries no NamedSharding (host arrays, single-device placement)."""
    spec = getattr(getattr(leaf, "sharding", None), "spec", None)
    if spec is None:
        return None
    return [list(p) if isinstance(p, tuple) else p for p in spec]


def _tree_specs_json(tree) -> dict:
    return {jax.tree_util.keystr(kp): _leaf_spec_json(leaf)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]}


def _datapipe_shard_positions(extra_meta) -> list:
    """Every ``shard`` stage's ``(n, i, k)`` cursor found in the
    supervisor's ``datapipe`` pipeline state (nested ``upstream``
    dicts), outermost first."""
    out = []

    def walk(node):
        if not isinstance(node, dict):
            return
        if node.get("kind") == "shard":
            out.append({key: int(node[key]) for key in ("n", "i", "k")
                        if key in node})
        walk(node.get("upstream"))

    if extra_meta and isinstance(extra_meta.get("datapipe"), dict):
        walk(extra_meta["datapipe"])
    return out


def _layout_manifest(net, extra_meta) -> dict:
    """The schema-v2 elastic-resharding manifest: how this checkpoint
    was laid out when it was saved. Restore does NOT need it to re-lay
    the tree onto a target mesh (specs are recomputed there) — it exists
    so tooling and the supervisor can see the old world (mesh shape,
    process count, shard cursors) and stamp old→new transitions."""
    meshed = getattr(net, "_mesh", None)
    detail = getattr(net, "_mesh_detail", None) or {}
    mesh_json = None
    if meshed is not None:
        mesh, data_axis = meshed
        mesh_json = {
            "axis_names": [str(a) for a in mesh.axis_names],
            "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
            "device_count": int(mesh.size),
            "data_axis": data_axis,
            "model_axis": detail.get("model_axis"),
        }
    return {
        "format_version": 2,
        "mesh": mesh_json,
        "process_count": int(jax.process_count()),
        "process_index": int(jax.process_index()),
        "param_specs": _tree_specs_json(net.params),
        "opt_specs": _tree_specs_json(net.opt_state or {}),
        "datapipe_shards": _datapipe_shard_positions(extra_meta),
    }


def _mp_barrier(tag: str):
    """Deadline-capable cross-process barrier around the save sequence
    (parallel.distributed.barrier: coordination-service native, raises
    PeerLostError after DL4J_TPU_COLLECTIVE_TIMEOUT_S instead of
    hanging on a dead peer; no-op single-process)."""
    from deeplearning4j_tpu.parallel import distributed as _dist
    _dist.barrier(tag)


def _save_checkpoint_inner(net, path: str, extra_meta=None):
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    tree = {"params": net.params, "state": net.state or {},
            "opt_state": net.opt_state}
    ckptr.save(os.path.join(path, "tree"), tree, force=True)
    ckptr.wait_until_finished()
    if _POST_COMMIT_HOOK is not None:
        _POST_COMMIT_HOOK(path)
    multi = jax.process_count() > 1
    if multi:
        # pre-meta barrier: meta.json is the validity commit point, so
        # it may land ONLY once every process's tree shards are durable.
        # A peer dying mid-save times this barrier out (PeerLostError)
        # BEFORE meta exists — the partial save is never restorable,
        # which is the cross-host half of the crash-atomicity contract.
        _mp_barrier("dl4j_ckpt_tree_committed")
    if jax.process_index() == 0:
        # layout.json lands BEFORE the meta.json rename, so meta's
        # presence still certifies the complete checkpoint (tree +
        # layout + meta) exactly as in format 1
        layout = _layout_manifest(net, extra_meta)
        ltmp = os.path.join(path, ".layout.json.tmp")
        with open(ltmp, "w") as f:
            json.dump(layout, f, indent=1)
        os.replace(ltmp, os.path.join(path, "layout.json"))
        meta = {
            "kind": _net_kind(net),
            "config": net.conf.to_json(),
            "iteration": int(net.iteration),
            "epoch": int(net.epoch),
            "format_version": 2,
        }
        if extra_meta:
            clash = set(extra_meta) & set(meta)
            if clash:
                raise ValueError(f"extra_meta may not override reserved "
                                 f"meta.json keys: {sorted(clash)}")
            meta.update(extra_meta)
        tmp = os.path.join(path, ".meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, "meta.json"))
    if multi:
        # post-meta barrier: a non-zero process must not return (and the
        # supervisor must not GC / resume-scan) until the rename landed
        # everywhere — save_checkpoint means "complete everywhere"
        _mp_barrier("dl4j_ckpt_save_done")
    return path


_STEP_DIR = re.compile(r"^step_(\d+)$")


def read_checkpoint_meta(path: str) -> dict:
    """The checkpoint's ``meta.json`` dict (counters, config, plus any
    ``extra_meta`` a save recorded — e.g. the supervisor's ``datapipe``
    pipeline state)."""
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)


def read_checkpoint_layout(path: str):
    """The schema-v2 ``layout.json`` manifest (per-leaf partition specs,
    mesh axes/shape, process count, datapipe shard cursors), or None for
    a format-1 checkpoint saved before the manifest existed."""
    try:
        with open(os.path.join(path, "layout.json")) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def is_valid_checkpoint(path: str) -> bool:
    """A complete save: the orbax tree directory AND ``meta.json`` (which
    lands via rename strictly after the tree commit, so its presence
    certifies the whole checkpoint)."""
    return (os.path.isdir(os.path.join(path, "tree"))
            and os.path.isfile(os.path.join(path, "meta.json")))


def find_latest_checkpoint(directory: str):
    """Newest *valid* ``step_<n>`` checkpoint under ``directory``, or None.

    Partial saves (a crash between the tree commit and the meta.json
    rename leaves a step directory with no meta.json) are skipped — the
    auto-resume contract is "newest checkpoint that is provably
    complete", never "newest directory". Ordering is by step number, not
    mtime: a rolled-back run may legitimately rewrite an older step
    later.

    Concurrent retention GC is tolerated: a step directory the listdir
    saw but that vanishes before (or during) its meta read is skipped
    and the scan continues to the next-newest candidate — a reaper
    deleting old steps while a relaunch scans for the resume point must
    never crash the relaunch."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        m = _STEP_DIR.match(name)
        if m is not None:
            steps.append((int(m.group(1)), os.path.join(directory, name)))
    for _, path in sorted(steps, reverse=True):
        if not is_valid_checkpoint(path):
            continue
        try:
            read_checkpoint_meta(path)     # provably still readable
        except (OSError, ValueError):
            continue                        # GC won the race — next step
        return path
    return None


def _restore(path: str, expect_kind: str, mesh=None, data_axis: str = "data",
             model_axis=None, tp_rules=None):
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not os.path.isfile(os.path.join(path, "meta.json")):
        raise IncompleteCheckpointError(
            f"partial checkpoint at {path}: meta.json is missing (a save "
            "was interrupted between the tree commit and the meta rename)."
            " Resume from the previous step directory — "
            "find_latest_checkpoint() skips partial saves automatically")
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta["kind"] != expect_kind:
        raise ValueError(
            f"checkpoint at {path} holds a {meta['kind']} net, not a "
            f"{expect_kind}")

    if expect_kind == "graph":
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = ComputationGraphConfiguration.from_json(meta["config"])
        net = ComputationGraph(conf).init(structure_only=True)
    else:
        from deeplearning4j_tpu.nn.conf.core import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = MultiLayerConfiguration.from_json(meta["config"])
        net = MultiLayerNetwork(conf).init(structure_only=True)

    # target structure from the (structure-only) init; restore places
    # every leaf DIRECTLY into its final sharding on the target mesh —
    # the specs are recomputed for the mesh being restored onto (never
    # read back from the save-time layout), so any topology works:
    # saved on 8 devices, restored on 4, 1, or 16
    target = {"params": net.params, "state": net.state or {},
              "opt_state": net.opt_state}

    if tp_rules:
        # eager rule validation (the PR 6 dtype-policy style): a rule
        # matching no param silently no-ops today's placement and only
        # surfaces as OOM or wrong numerics much later
        from deeplearning4j_tpu.parallel.tensor import unmatched_rules
        missing = unmatched_rules(tp_rules, net.params)
        if missing:
            raise ValueError(
                f"tp_rules entries match no param path: {missing!r} "
                f"(checkpoint at {path}). Paths use jax.tree_util.keystr "
                "form, e.g. \"['layer_0']['W']\" for exact keys or a "
                "regex searched against that string for (pattern, spec) "
                "rules")

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        if model_axis is not None:
            from deeplearning4j_tpu.parallel.tensor import (
                opt_state_specs, param_specs)
            p_specs = param_specs(net.params, mesh, model_axis, tp_rules)
            specs = {"params": p_specs,
                     "state": jax.tree_util.tree_map(
                         lambda _: P(), net.state or {}),
                     "opt_state": opt_state_specs(net.opt_state, p_specs)}
        else:
            specs = jax.tree_util.tree_map(lambda _: P(), target)

        def as_restore_type(x, spec):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=NamedSharding(mesh, spec))

        abstract = jax.tree_util.tree_map(as_restore_type, target, specs)
    else:
        # explicit local placement: falling back to the sharding
        # recorded in the checkpoint would break cross-topology
        # resume (saved on 8 devices, restored on 1)
        from jax.sharding import SingleDeviceSharding
        dev = SingleDeviceSharding(jax.local_devices()[0])
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=dev),
            target)

    ckptr = _checkpointer()
    tree = ckptr.restore(os.path.join(path, "tree"), abstract)

    net.params = tree["params"]
    net.state = tree["state"]
    net.opt_state = tree["opt_state"]
    net.iteration = int(meta["iteration"])
    net.epoch = int(meta["epoch"])
    if mesh is not None:
        # leaves are already in their final shardings — just record the
        # placement (model_axis/tp_rules must ride through or a dp x tp
        # net silently resumes fully replicated and may not even fit)
        net._mark_meshed(mesh, data_axis, model_axis=model_axis,
                         tp_rules=tp_rules)
    return net


def restore_multi_layer_network(path: str, mesh=None, data_axis="data",
                                model_axis=None, tp_rules=None):
    """Resume a sequential net (+ optionally place it on ``mesh``;
    ``model_axis``/``tp_rules`` restore a tensor-parallel placement)."""
    return _restore(path, "multilayer", mesh, data_axis, model_axis,
                    tp_rules)


def restore_computation_graph(path: str, mesh=None, data_axis="data",
                              model_axis=None, tp_rules=None):
    """Resume a DAG net (+ optionally place it on ``mesh``;
    ``model_axis``/``tp_rules`` restore a tensor-parallel placement)."""
    return _restore(path, "graph", mesh, data_axis, model_axis, tp_rules)
