"""Shared perf accounting: device peak FLOP/s table + XLA cost-model
extraction. Single source of truth for bench.py, PerformanceListener, and
the networks' ``step_cost_analysis`` (SURVEY.md §5.1)."""

from __future__ import annotations

import os

# bf16 matmul peak FLOP/s by device kind prefix (public spec numbers)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e: 197 TFLOP/s bf16
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,        # trillium
}


def peak_flops(device) -> float | None:
    """Peak FLOP/s for the MFU denominator. The DL4J_TPU_PEAK_FLOPS env
    override wins over the table — it is the only way to get an MFU
    number on devices without an honest spec entry (CPU), and lets TPU
    users pin the f32 vs bf16 peak they are actually comparing against."""
    override = os.environ.get("DL4J_TPU_PEAK_FLOPS")
    if override:
        try:
            return float(override)
        except ValueError:
            pass
    kind = getattr(device, "device_kind", "")
    for prefix, peak in PEAK_FLOPS.items():
        if kind.startswith(prefix):
            return peak
    return None


def xla_step_cost(jitted_step, *args) -> dict:
    """Cost-model numbers for one compiled call of ``jitted_step(*args)``:
    {"flops", "bytes_accessed"}. Raises NotImplementedError for wrapped
    (non-jit) steps such as the meshed trainers."""
    if not hasattr(jitted_step, "lower"):
        raise NotImplementedError(
            "cost analysis needs a plain jitted step (meshed nets wrap it)")
    cost = jitted_step.lower(*args).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    cost = cost or {}
    return {"flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0))}


def xla_step_cost_lowered(jitted_step, *args) -> dict:
    """Like :func:`xla_step_cost` but from the *lowered* (pre-backend-
    compile) module — pure tracing, no second XLA compilation, so the
    fit loops can auto-derive per-step FLOPs at step-build time without
    doubling compile cost. Same return shape; flops matches the compiled
    path on jax 0.4.x. Raises NotImplementedError for wrapped steps."""
    if not hasattr(jitted_step, "lower"):
        raise NotImplementedError(
            "cost analysis needs a plain jitted step (meshed nets wrap it)")
    cost = jitted_step.lower(*args).cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    cost = cost or {}
    return {"flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0))}
