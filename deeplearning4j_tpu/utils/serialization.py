"""Model checkpointing.

Parity: util/ModelSerializer.java — a ZIP containing ``configuration.json``
(:90), ``coefficients.bin`` (:95) and ``updaterState.bin`` (:40). Here the
container is a ZIP holding:

- ``configuration.json`` — the MultiLayerConfiguration JSON round-trip
- ``coefficients.npz``   — param pytree, keys = tree paths
- ``updaterState.npz``   — optimizer-state pytree
- ``state.npz``          — layer state (e.g. batch-norm running stats)
- ``metadata.json``      — step/epoch/format version (beyond the reference,
  which loses step count on restore — SURVEY.md §5.4)

This ZIP is the portable single-file format and the regression-test
surface. Reference-written checkpoints (the Java stack's own zips) are
read by modelimport/dl4j.py; sharded many-host checkpoints can use orbax
directly on the param/opt pytrees (not wrapped here).
"""

from __future__ import annotations

import io
import json
import zipfile

import jax
import numpy as np

_FORMAT_VERSION = 1


def _tree_to_npz_bytes(tree) -> bytes:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    arrays = {}
    for kp, leaf in flat:
        arrays[jax.tree_util.keystr(kp)] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _npz_bytes_to_leaves(data: bytes, template) -> object:
    """Restore arrays into the structure of ``template``."""
    npz = np.load(io.BytesIO(data))
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for kp, leaf in flat:
        key = jax.tree_util.keystr(kp)
        if key not in npz:
            raise KeyError(f"Checkpoint missing array for {key}")
        arr = npz[key]
        leaves.append(jax.numpy.asarray(arr, dtype=leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _write(net, path, model_type: str, save_updater: bool):
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", net.conf.to_json())
        zf.writestr("coefficients.npz", _tree_to_npz_bytes(net.params))
        if net.state:
            zf.writestr("state.npz", _tree_to_npz_bytes(net.state))
        if save_updater and net.opt_state is not None:
            zf.writestr("updaterState.npz", _tree_to_npz_bytes(net.opt_state))
        zf.writestr("metadata.json", json.dumps({
            "format_version": _FORMAT_VERSION,
            "model_type": model_type,
            "iteration": int(net.iteration),
            "epoch": int(net.epoch),
        }))


def _restore(path, build_net, load_updater: bool):
    """Shared restore: ``build_net(conf_json) -> net`` initialized
    structure-only; trees not present in the file are materialized fresh."""
    with zipfile.ZipFile(path, "r") as zf:
        net = build_net(zf.read("configuration.json").decode("utf-8"))
        names = set(zf.namelist())
        net.params = _npz_bytes_to_leaves(zf.read("coefficients.npz"),
                                          net.params)
        if "state.npz" in names and net.state:
            net.state = _npz_bytes_to_leaves(zf.read("state.npz"), net.state)
        else:
            net.materialize_state()
        if load_updater and "updaterState.npz" in names:
            net.opt_state = _npz_bytes_to_leaves(zf.read("updaterState.npz"),
                                                 net.opt_state)
        else:
            net.materialize_opt_state()
        if "metadata.json" in names:
            meta = json.loads(zf.read("metadata.json"))
            net.iteration = meta.get("iteration", 0)
            net.epoch = meta.get("epoch", 0)
    return net


def write_model(net, path, save_updater: bool = True):
    """ModelSerializer.writeModel parity."""
    _write(net, path, "multi_layer_network", save_updater)


def restore_multi_layer_network(path, load_updater: bool = True):
    """ModelSerializer.restoreMultiLayerNetwork parity."""
    from deeplearning4j_tpu.nn.conf.core import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build(conf_json):
        conf = MultiLayerConfiguration.from_json(conf_json)
        return MultiLayerNetwork(conf).init(structure_only=True)

    return _restore(path, build, load_updater)


def write_computation_graph(net, path, save_updater: bool = True):
    _write(net, path, "computation_graph", save_updater)


def restore_model(path, load_updater: bool = True):
    """Restore either model kind by reading metadata.json's model_type
    (ModelGuesser.java parity)."""
    with zipfile.ZipFile(path, "r") as zf:
        names = set(zf.namelist())
        mtype = "multi_layer_network"
        if "metadata.json" in names:
            mtype = json.loads(zf.read("metadata.json")).get(
                "model_type", mtype)
    if mtype == "computation_graph":
        return restore_computation_graph(path, load_updater)
    return restore_multi_layer_network(path, load_updater)


def restore_computation_graph(path, load_updater: bool = True):
    """ModelSerializer.restoreComputationGraph parity."""
    try:
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
    except ImportError as e:
        raise NotImplementedError(
            "ComputationGraph is not available yet in this build") from e

    def build(conf_json):
        conf = ComputationGraphConfiguration.from_json(conf_json)
        return ComputationGraph(conf).init(structure_only=True)

    return _restore(path, build, load_updater)
