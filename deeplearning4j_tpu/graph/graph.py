"""Adjacency-list graph (parity: graph/api/IGraph.java + graph/graph/
Graph.java + data/GraphLoader.java in deeplearning4j-graph)."""

from __future__ import annotations

from typing import List, Optional, Tuple


class Graph:
    def __init__(self, num_vertices: int, directed: bool = False):
        self.num_vertices_count = num_vertices
        self.directed = directed
        self._adj: List[List[Tuple[int, float]]] = [
            [] for _ in range(num_vertices)]

    def num_vertices(self) -> int:
        return self.num_vertices_count

    def add_edge(self, a: int, b: int, weight: float = 1.0):
        self._adj[a].append((b, weight))
        if not self.directed:
            self._adj[b].append((a, weight))

    def neighbors(self, v: int) -> List[int]:
        return [b for b, _ in self._adj[v]]

    def weighted_neighbors(self, v: int) -> List[Tuple[int, float]]:
        return list(self._adj[v])

    def degree(self, v: int) -> int:
        return len(self._adj[v])

    @staticmethod
    def from_edge_list(edges, num_vertices: Optional[int] = None,
                       directed: bool = False) -> "Graph":
        """GraphLoader.loadUndirectedGraphEdgeListFile parity for in-memory
        edge lists: iterable of (a, b) or (a, b, weight)."""
        edges = list(edges)
        if num_vertices is None:
            num_vertices = 1 + max(max(e[0], e[1]) for e in edges)
        g = Graph(num_vertices, directed)
        for e in edges:
            g.add_edge(e[0], e[1], e[2] if len(e) > 2 else 1.0)
        return g
