"""Graph embeddings (parity: deeplearning4j-graph, 2,293 LoC — SURVEY.md
§2.7): graph API, random-walk iterators, DeepWalk, and a real node2vec
(stub-only in the reference)."""

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import (
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.node2vec import Node2Vec, Node2VecWalkIterator
