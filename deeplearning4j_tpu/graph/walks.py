"""Random-walk sequence generators (parity: iterator/RandomWalkIterator.java
and WeightedRandomWalkIterator.java in deeplearning4j-graph)."""

from __future__ import annotations

import numpy as np


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex
    (RandomWalkIterator.java parity; ``no_edge_handling`` SELF_LOOP keeps
    the walker in place at sinks)."""

    def __init__(self, graph, walk_length: int, seed: int = 0,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.walks_per_vertex = walks_per_vertex

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                cur = start
                for _ in range(self.walk_length - 1):
                    nbrs = self.graph.neighbors(cur)
                    cur = int(rng.choice(nbrs)) if nbrs else cur
                    walk.append(cur)
                yield walk

    def reset(self):
        pass


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks (WeightedRandomWalkIterator.java)."""

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                cur = start
                for _ in range(self.walk_length - 1):
                    wn = self.graph.weighted_neighbors(cur)
                    if wn:
                        ws = np.array([w for _, w in wn], dtype=np.float64)
                        cur = int(wn[rng.choice(len(wn),
                                                p=ws / ws.sum())][0])
                    walk.append(cur)
                yield walk
