"""node2vec: biased second-order random walks -> SkipGram embeddings.

Parity-plus: the reference ships only a STUB
(deeplearning4j-nlp/.../models/node2vec/ — empty scaffolding, SURVEY.md
§2.6 "models/node2vec/ (stub)"); this is the real algorithm (Grover &
Leskovec 2016) built on the same pieces DeepWalk uses: the adjacency
Graph (graph/graph.py) and the batched SequenceVectors trainer.

The walk bias: having stepped t -> v, the next hop x is drawn with
unnormalized probability

    w(v,x) * 1/p   if x == t            (return)
    w(v,x) * 1     if dist(t, x) == 1   (stay close — BFS-like)
    w(v,x) * 1/q   otherwise            (explore — DFS-like)

p == q == 1 degenerates to DeepWalk's first-order walks.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import DeepWalk


class Node2VecWalkIterator:
    """Second-order (p, q)-biased walk generator over a Graph."""

    def __init__(self, graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, walks_per_vertex: int = 1, seed: int = 0):
        if p <= 0 or q <= 0:
            raise ValueError("p and q must be positive")
        self.graph = graph
        self.walk_length = walk_length
        self.p = float(p)
        self.q = float(q)
        self.walks_per_vertex = walks_per_vertex
        self.seed = seed
        # neighbor sets for the dist(t, x) == 1 test
        self._nbr_sets = [set(graph.neighbors(v))
                          for v in range(graph.num_vertices())]

    def _step(self, rng, prev: Optional[int], cur: int) -> Optional[int]:
        nbrs = self.graph.weighted_neighbors(cur)
        if not nbrs:
            return None
        if prev is None:
            w = np.asarray([wt for _, wt in nbrs], np.float64)
        else:
            prev_nbrs = self._nbr_sets[prev]
            w = np.asarray(
                [wt / self.p if x == prev
                 else (wt if x in prev_nbrs else wt / self.q)
                 for x, wt in nbrs], np.float64)
        w /= w.sum()
        return nbrs[rng.choice(len(nbrs), p=w)][0]

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        n = self.graph.num_vertices()
        for _ in range(self.walks_per_vertex):
            for start in rng.permutation(n):
                walk: List[int] = [int(start)]
                prev: Optional[int] = None
                while len(walk) < self.walk_length:
                    nxt = self._step(rng, prev, walk[-1])
                    if nxt is None:
                        break
                    prev = walk[-1]
                    walk.append(int(nxt))
                yield walk

    def reset(self):
        pass


class Node2Vec(DeepWalk):
    """node2vec trainer: DeepWalk with (p, q)-biased second-order walks
    (and optional negative sampling); the fit/query surface is inherited."""

    def __init__(self, vector_size: int = 100, window: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 1,
                 p: float = 1.0, q: float = 1.0,
                 learning_rate: float = 0.025, epochs: int = 1,
                 negative: int = 0, seed: int = 42):
        super().__init__(vector_size=vector_size, window=window,
                         walk_length=walk_length,
                         walks_per_vertex=walks_per_vertex,
                         learning_rate=learning_rate, epochs=epochs,
                         negative=negative, seed=seed)
        self.p = p
        self.q = q

    def _default_walks(self, graph):
        return Node2VecWalkIterator(
            graph, self.walk_length, p=self.p, q=self.q,
            walks_per_vertex=self.walks_per_vertex, seed=self.seed)
