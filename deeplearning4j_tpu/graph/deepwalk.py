"""DeepWalk: random walks -> SkipGram with hierarchical softmax over vertex
"words".

Parity: models/deepwalk/DeepWalk.java (254 LoC; fit(IGraph, walkLength)
:95-103 — walks feed SkipGram-style updates on a GraphHuffman tree) +
models/embeddings/GraphVectorsImpl.java. Here the walks feed the same
batched SequenceVectors trainer the NLP stack uses (degree-weighted Huffman
tree replaces GraphHuffman).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequence_vectors import (
    SequenceVectors,
    SequenceVectorsConfig,
)


class DeepWalk:
    def __init__(self, vector_size: int = 100, window: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 1,
                 learning_rate: float = 0.025, epochs: int = 1,
                 negative: int = 0, seed: int = 42):
        self.vector_size = vector_size
        self.window = window
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.negative = negative
        self.seed = seed
        self.vectors: SequenceVectors | None = None

    def _default_walks(self, graph):
        return RandomWalkIterator(
            graph, self.walk_length, seed=self.seed,
            walks_per_vertex=self.walks_per_vertex)

    def _config(self) -> SequenceVectorsConfig:
        return SequenceVectorsConfig(
            vector_size=self.vector_size, window=self.window,
            min_word_frequency=1, epochs=self.epochs,
            learning_rate=self.learning_rate, negative=self.negative,
            seed=self.seed)

    def fit(self, graph, walk_iterator=None):
        """DeepWalk.fit(IGraph, walkLength) parity."""
        if walk_iterator is None:
            walk_iterator = self._default_walks(graph)
        walks = [[str(v) for v in walk] for walk in walk_iterator]
        self.vectors = SequenceVectors(self._config())
        self.vectors.build_vocab(walks)
        self.vectors.fit(walks)
        return self

    def vertex_vector(self, v: int) -> np.ndarray:
        return self.vectors.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self.vectors.similarity(str(a), str(b))

    def verts_nearest(self, v: int, top_n: int = 5):
        return [(int(w), s)
                for w, s in self.vectors.words_nearest(str(v), top_n)]
