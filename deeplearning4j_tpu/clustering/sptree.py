"""Space-partitioning tree (SPTree) with center-of-mass aggregation.

Parity: deeplearning4j-core clustering/sptree/SpTree.java (+ the 2D
special case clustering/quadtree/QuadTree.java — here ``QuadTree`` is the
d=2 instantiation). Used by Barnes-Hut t-SNE: cells far enough away
(cell_size / distance < theta) are approximated by their center of mass,
turning the O(N^2) repulsive-force sum into O(N log N).

Host-side numpy by design: tree construction and pointer-chasing
traversal are control-flow-heavy and tiny — the accelerator path is the
exact [N, N] kernel in plot/tsne.py; this exists for the reference's
large-N CPU regime and for capability parity.
"""

from __future__ import annotations

import numpy as np


class SPTree:
    """One node: either a leaf holding <= ``leaf_size`` points or 2^d
    children splitting the cell at its center."""

    __slots__ = ("center", "width", "n", "com", "children", "idx",
                 "points", "leaf_size")

    def __init__(self, points, center=None, width=None, leaf_size=1):
        points = np.asarray(points, np.float64)
        if center is None:
            lo = points.min(axis=0)
            hi = points.max(axis=0)
            center = (lo + hi) / 2.0
            width = np.maximum(hi - lo, 1e-10) * (1.0 + 1e-6)
        self.center = np.asarray(center, np.float64)
        self.width = np.asarray(width, np.float64)
        self.leaf_size = leaf_size
        self.children = None
        self.n = 0
        self.com = np.zeros_like(self.center)
        self.idx = []
        self.points = points
        for i in range(points.shape[0]):
            self._insert(i)

    # ------------------------------------------------------------ build
    def _child_index(self, p):
        return int(sum((1 << k) for k in range(p.shape[0])
                       if p[k] > self.center[k]))

    def _subdivide(self):
        d = self.center.shape[0]
        self.children = [None] * (1 << d)

    def _make_child(self, ci):
        d = self.center.shape[0]
        off = np.asarray([(1 if (ci >> k) & 1 else -1) for k in range(d)],
                         np.float64)
        child = SPTree.__new__(SPTree)
        child.center = self.center + off * self.width / 4.0
        child.width = self.width / 2.0
        child.leaf_size = self.leaf_size
        child.children = None
        child.n = 0
        child.com = np.zeros_like(self.center)
        child.idx = []
        child.points = self.points
        return child

    def _insert(self, i):
        p = self.points[i]
        self.com = (self.com * self.n + p) / (self.n + 1)
        self.n += 1
        if self.children is None:
            self.idx.append(i)
            if len(self.idx) > self.leaf_size and np.max(self.width) > 1e-8:
                self._subdivide()
                pending, self.idx = self.idx, []
                for j in pending:
                    self._route(j)
            return
        self._route(i)

    def _route(self, i):
        ci = self._child_index(self.points[i])
        if self.children[ci] is None:
            self.children[ci] = self._make_child(ci)
        c = self.children[ci]
        c.com = (c.com * c.n + self.points[i]) / (c.n + 1)
        c.n += 1
        if c.children is None:
            c.idx.append(i)
            if len(c.idx) > c.leaf_size and np.max(c.width) > 1e-8:
                c._subdivide()
                pending, c.idx = c.idx, []
                for j in pending:
                    c._route(j)
        else:
            c._route(i)

    # -------------------------------------------------------- traversal
    def non_edge_forces(self, point, skip_index, theta):
        """Barnes-Hut repulsive accumulation for one query point.

        Returns (neg_force [d], z_sum): contributions q^2 * N * (p - com)
        and q * N with q = 1/(1 + |p - com|^2), descending only into
        cells with cell_width / dist >= theta (SpTree.java
        computeNonEdgeForces parity)."""
        d = self.center.shape[0]
        neg = np.zeros(d)
        z = 0.0
        stack = [self]
        max_w = float(np.max(self.width))
        while stack:
            node = stack.pop()
            if node is None or node.n == 0:
                continue
            diff = point - node.com
            dist2 = float(diff @ diff)
            is_leaf = node.children is None
            w = float(np.max(node.width))
            if is_leaf or (w * w < theta * theta * dist2):
                if is_leaf and node.idx == [skip_index]:
                    continue
                n_eff = node.n
                if is_leaf and skip_index in node.idx:
                    n_eff -= 1
                    # remove the skipped point's own contribution from the
                    # leaf's aggregate
                    if n_eff == 0:
                        continue
                    com = (node.com * node.n - point) / n_eff
                    diff = point - com
                    dist2 = float(diff @ diff)
                q = 1.0 / (1.0 + dist2)
                z += n_eff * q
                neg += n_eff * q * q * diff
            else:
                stack.extend(c for c in node.children if c is not None)
        return neg, z


class QuadTree(SPTree):
    """2D SPTree (clustering/quadtree/QuadTree.java parity)."""

    def __init__(self, points, **kw):
        points = np.asarray(points)
        if points.shape[1] != 2:
            raise ValueError("QuadTree requires 2d points; use SPTree")
        super().__init__(points, **kw)
