"""K-means clustering, device-accelerated.

Parity: deeplearning4j-core clustering/kmeans/KMeansClustering.java (+ the
cluster/ClusterSet infrastructure). TPU-native: each Lloyd iteration is one
jitted step — an [N, K] distance matmul on the MXU + segment-sum centroid
update — instead of the reference's per-point Java loops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(2,))
def _lloyd_step(x, centroids, k):
    d2 = (jnp.sum(x * x, axis=1, keepdims=True)
          - 2.0 * x @ centroids.T
          + jnp.sum(centroids * centroids, axis=1))
    assign = jnp.argmin(d2, axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=x.dtype)          # [N, K]
    counts = one_hot.sum(axis=0)                                # [K]
    sums = one_hot.T @ x                                        # [K, D]
    new_centroids = jnp.where(counts[:, None] > 0,
                              sums / jnp.maximum(counts[:, None], 1.0),
                              centroids)
    cost = jnp.sum(jnp.min(d2, axis=1))
    return new_centroids, assign, cost


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-6,
                 seed: int = 0):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centroids = None
        self.cost = None

    def fit(self, x) -> "KMeansClustering":
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        rng = np.random.default_rng(self.seed)
        # k-means++ style seeding: first uniform, rest distance-weighted
        idx = [int(rng.integers(0, n))]
        for _ in range(1, self.k):
            c = x[jnp.asarray(idx)]
            d2 = np.asarray(jnp.min(
                jnp.sum((x[:, None, :] - c[None, :, :]) ** 2, axis=-1),
                axis=1))
            total = d2.sum()
            if total <= 0:
                # all remaining points coincide with chosen centroids
                # (duplicates / k > distinct points): fall back to uniform
                probs = np.full(n, 1.0 / n)
            else:
                probs = d2 / total
            idx.append(int(rng.choice(n, p=probs)))
        centroids = x[jnp.asarray(idx)]
        prev_cost = np.inf
        for _ in range(self.max_iterations):
            centroids, assign, cost = _lloyd_step(x, centroids, self.k)
            cost = float(cost)
            if abs(prev_cost - cost) < self.tol * max(abs(prev_cost), 1.0):
                break
            prev_cost = cost
        self.centroids = centroids
        self.cost = cost
        return self

    def predict(self, x) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        _, assign, _ = _lloyd_step(x, self.centroids, self.k)
        return np.asarray(assign)
