"""Spatial trees for nearest-neighbor queries: KD-tree and VP-tree.

Parity: deeplearning4j-core clustering/kdtree/KDTree.java and
clustering/vptree/VPTree.java (used by t-SNE and the NLP wordsNearest
paths). Host-side structures; brute-force device matmuls are usually faster
on TPU for bulk queries (see lookup.py), but the trees cover the
incremental/online API of the reference.
"""

from __future__ import annotations

import numpy as np


class KDTree:
    def __init__(self, points):
        self.points = np.asarray(points, np.float64)
        n = self.points.shape[0]
        self._root = self._build(np.arange(n), depth=0)

    def _build(self, idxs, depth):
        if len(idxs) == 0:
            return None
        axis = depth % self.points.shape[1]
        order = idxs[np.argsort(self.points[idxs, axis])]
        mid = len(order) // 2
        return {
            "idx": int(order[mid]),
            "axis": axis,
            "left": self._build(order[:mid], depth + 1),
            "right": self._build(order[mid + 1:], depth + 1),
        }

    def nn(self, query):
        return self.knn(query, 1)[0]

    def knn(self, query, k):
        query = np.asarray(query, np.float64)
        heap = []  # list of (dist, idx), kept sorted, max size k

        def visit(node):
            if node is None:
                return
            p = self.points[node["idx"]]
            d = float(np.linalg.norm(p - query))
            if len(heap) < k or d < heap[-1][0]:
                heap.append((d, node["idx"]))
                heap.sort()
                if len(heap) > k:
                    heap.pop()
            axis = node["axis"]
            diff = query[axis] - p[axis]
            near, far = ((node["left"], node["right"]) if diff < 0
                         else (node["right"], node["left"]))
            visit(near)
            if len(heap) < k or abs(diff) < heap[-1][0]:
                visit(far)

        visit(self._root)
        return [(idx, d) for d, idx in heap]


class VPTree:
    """Vantage-point tree over any metric (default euclidean)
    (VPTree.java parity)."""

    def __init__(self, points, metric=None, seed: int = 0):
        self.points = np.asarray(points, np.float64)
        self.metric = metric or (lambda a, b: float(np.linalg.norm(a - b)))
        self._rng = np.random.default_rng(seed)
        self._root = self._build(list(range(self.points.shape[0])))

    def _build(self, idxs):
        if not idxs:
            return None
        vp = idxs[self._rng.integers(0, len(idxs))]
        rest = [i for i in idxs if i != vp]
        if not rest:
            return {"vp": vp, "mu": 0.0, "inside": None, "outside": None}
        dists = np.array([self.metric(self.points[vp], self.points[i])
                          for i in rest])
        mu = float(np.median(dists))
        inside = [i for i, d in zip(rest, dists) if d < mu]
        outside = [i for i, d in zip(rest, dists) if d >= mu]
        return {"vp": vp, "mu": mu, "inside": self._build(inside),
                "outside": self._build(outside)}

    def knn(self, query, k):
        query = np.asarray(query, np.float64)
        heap = []

        def visit(node):
            if node is None:
                return
            d = self.metric(self.points[node["vp"]], query)
            if len(heap) < k or d < heap[-1][0]:
                heap.append((d, node["vp"]))
                heap.sort()
                if len(heap) > k:
                    heap.pop()
            tau = heap[-1][0] if len(heap) == k else np.inf
            if d < node["mu"]:
                visit(node["inside"])
                if d + tau >= node["mu"]:
                    visit(node["outside"])
            else:
                visit(node["outside"])
                if d - tau <= node["mu"]:
                    visit(node["inside"])

        visit(self._root)
        return [(idx, d) for d, idx in heap]
