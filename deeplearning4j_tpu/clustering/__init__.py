"""Clustering suite (parity: deeplearning4j-core clustering/ — kmeans +
spatial trees; SURVEY.md §2.5)."""

from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.trees import KDTree, VPTree
