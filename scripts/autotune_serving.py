"""Offline serving-schedule autotuner: replay a recorded serve_bench
traffic trace against the dispatcher simulator and pick the
(max_batch, batch_window_ms) that minimizes p99 x (1 + padding waste).

Input is a ``scripts/serve_bench.py --out results.json`` file — its
report embeds the per-request arrival trace of the highest-concurrency
coalesced run plus the measured per-bucket device times the simulator's
service model is fitted to (compilecache/autotune.py documents the
dispatch semantics and the objective). Output is a tuning report the
server boots with:

    python scripts/serve_bench.py --out results.json
    python scripts/autotune_serving.py --trace results.json \\
        --out tuning.json
    # then: ModelServer(net, tuning_report="tuning.json")
    #   or: serve(net, tuning_report="tuning.json")

The default config the bench ran with is always a grid point, so the
tuned objective is <= the default's on the replayed trace by
construction — the report's ``objective_ratio`` is the receipt.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True,
                    help="serve_bench --out results file (embeds the "
                         "arrival trace + per-bucket device times)")
    ap.add_argument("--out", default=None,
                    help="write the tuning report here (default: stdout "
                         "only)")
    ap.add_argument("--min-batch", type=int, default=2)
    ap.add_argument("--max-batch-grid", type=int, nargs="+", default=None)
    ap.add_argument("--window-grid-ms", type=float, nargs="+", default=None)
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.compilecache import autotune as at

    try:
        with open(args.trace) as f:
            results = json.load(f)
        report = at.autotune(results, min_batch=args.min_batch,
                             max_batch_grid=args.max_batch_grid,
                             window_grid_ms=args.window_grid_ms)
    except (OSError, ValueError) as e:
        print(f"autotune_serving: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2))
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
