"""Chaos resharding demo: preempt a run mid-epoch on an 8-device mesh,
resume it on FOUR devices, and prove nothing was lost in translation.

Drives the elastic-restore path (schema-v2 layout manifest +
``restore_*(mesh=...)`` re-layout + datapipe coverage remap) end to end:

1. **Old world** — a dp×tp-meshed MLP trains under the supervisor on an
   8-device ``(data=2, model=4)`` mesh, reading shard ``(n=2, i=0)`` of
   the record stream (one of two simulated hosts; host 1's consumption
   is replayed for the coverage ledger). A fault-injected preemption
   stops it mid-epoch with a clean checkpoint.
2. **Restore fidelity** — the checkpoint restores onto a 4-device
   ``(data=2, model=2)`` mesh, each leaf landing DIRECTLY in its target
   ``NamedSharding``; every param and optimizer-slot array must be
   bit-identical to the moment of preemption, and the restore span's
   fresh-compile count (the PR-10 ``compile_snapshot`` seam) is
   recorded and budget-gated.
3. **New world** — a fresh supervisor + net built for the 4-device mesh
   resumes from the same directory: the shard cursor baked for the
   2-host fleet is remapped by the coverage rule in
   ``datapipe/reshard.py``, a ``reshard`` RecoveryEvent fires, and the
   RunReport carries the old→new mesh stamp.
4. **Verdict** — (a) the records consumed across old shards + resumed
   run tile the epoch exactly (disjoint, covering, no record dropped or
   doubled); (b) the resumed run's final params are bit-identical to a
   control that restores the same checkpoint and replays the same
   remainder by hand (``np.testing.assert_array_equal``, not allclose).
5. **Serving tier** — the trained fleet restarts on half its replicas
   via ``ReplicaSet.restart_fleet``: still serving, scoreboard rows
   flagged ``degraded``.

Run: ``python scripts/chaos_reshard.py --out RESHARD_r01.json`` (CPU,
simulated devices, ~30s). The slow pytest wrapper is
``tests/test_reshard.py::test_chaos_reshard_script_slow``; the artifact
is gated by ``scripts/check_budgets.py --bench`` against the
``reshard`` section of BUDGETS.json.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8 simulated devices must exist before jax initializes
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_enable_x64", True)  # F64 policy: bit-exact verdicts

N_RECORDS = 64
BATCH = 4
PREEMPT_STEP = 3


def build_net(seed):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.core import DtypePolicy
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Adam
    f64 = DtypePolicy(param_dtype="float64", compute_dtype="float64")
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .dtype(f64).list()
            .layer(Dense(n_in=12, n_out=16, activation="tanh"))
            .layer(Output(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def make_mesh(n_devices, model_dim):
    devs = np.array(jax.devices()[:n_devices]).reshape(
        n_devices // model_dim, model_dim)
    return jax.sharding.Mesh(devs, ("data", "model"))


def build_data(seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N_RECORDS, 12))
    x[:, 0] = np.arange(N_RECORDS)  # record id rides in feature column 0
    y = np.eye(4)[rng.integers(0, 4, N_RECORDS)]
    return x, y


def build_pipeline(x, y, num_shards, index, tracker):
    """shard -> map(track record ids) -> batch. The tracking map is a
    1:1 stage (workers=0, no inflight), so the coverage remap accepts
    it; it logs each record id the moment a batch pulls it."""
    from deeplearning4j_tpu import datapipe

    def track(rec):
        tracker.append(int(round(float(rec[0][0]))))
        return rec

    return (datapipe.from_arrays(x, y).shard(num_shards, index)
            .map(track).batch(BATCH))


def flat_params(net):
    return {(n, k): np.asarray(v) for n, sub in net.params.items()
            for k, v in sub.items()}


def flat_opt(net):
    leaves, _ = jax.tree_util.tree_flatten(net.opt_state)
    return [np.asarray(v) for v in leaves]


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--checkpoint-every", type=int, default=5)
    ap.add_argument("--dir", default=None,
                    help="checkpoint directory (default: fresh tempdir)")
    ap.add_argument("--out", default=None,
                    help="write the receipt JSON here (RESHARD_r01.json)")
    args = ap.parse_args()

    from deeplearning4j_tpu.observability.metrics import (compile_delta,
                                                          compile_snapshot)
    from deeplearning4j_tpu.resilience import (FaultInjector,
                                               SupervisorConfig,
                                               TrainingSupervisor)
    from deeplearning4j_tpu.utils.checkpoint import (
        find_latest_checkpoint, read_checkpoint_layout, read_checkpoint_meta,
        restore_multi_layer_network)

    ckpt_dir = args.dir or tempfile.mkdtemp(prefix="chaos_reshard_")
    if args.dir:
        os.makedirs(ckpt_dir, exist_ok=True)
    x, y = build_data(args.seed)
    mesh8 = make_mesh(8, 4)
    mesh4 = make_mesh(4, 2)

    def supervisor(net, injector=None):
        return TrainingSupervisor(
            net, SupervisorConfig(checkpoint_dir=ckpt_dir,
                                  checkpoint_every_steps=args.checkpoint_every,
                                  backoff_initial_s=0.01,
                                  handle_sigterm=False),
            injector=injector)

    # -------------------------------------- 1. old world: 8 devices, 2 hosts
    print(f"[old] 8-device (data=2, model=4) mesh, shard (2, 0), "
          f"preempt at step {PREEMPT_STEP}, dir {ckpt_dir}")
    net_a = build_net(args.seed).use_mesh(mesh8, model_axis="model")
    seen_host0 = []
    pipe_a = build_pipeline(x, y, 2, 0, seen_host0)
    injector = FaultInjector().preempt_at_step(PREEMPT_STEP)
    with injector.installed():
        res_a = supervisor(net_a, injector).fit_pipeline(pipe_a, epochs=1)
    assert res_a.status == "preempted", res_a.status
    steps_done = res_a.final_step   # the armed step finishes in flight
    params_at_preempt = flat_params(net_a)
    opt_at_preempt = flat_opt(net_a)
    print(f"[old] preempted at step {steps_done}; host 0 consumed "
          f"{len(seen_host0)} records")

    # the second simulated host ran the same number of lockstep steps on
    # shard (2, 1) — replay its consumption for the coverage ledger
    seen_host1 = []
    pipe_phantom = build_pipeline(x, y, 2, 1, seen_host1)
    for _ in itertools.islice(iter(pipe_phantom), steps_done):
        pass

    latest = find_latest_checkpoint(ckpt_dir)
    assert latest is not None
    layout = read_checkpoint_layout(latest)
    assert layout and layout["mesh"]["device_count"] == 8, layout

    # ------------------------------ 2. restore fidelity onto the 4-dev mesh
    snap = compile_snapshot()
    net_r = restore_multi_layer_network(latest, mesh=mesh4,
                                        model_axis="model")
    delta = compile_delta(snap)
    restore_fresh = int(delta["count"])
    bit_identical = 1
    pr = flat_params(net_r)
    assert pr.keys() == params_at_preempt.keys()
    for key in pr:
        np.testing.assert_array_equal(
            pr[key], params_at_preempt[key],
            err_msg=f"restored param {key} diverged")
    for got, want in zip(flat_opt(net_r), opt_at_preempt):
        np.testing.assert_array_equal(got, want)
    for sub in net_r.params.values():
        for v in sub.values():
            assert getattr(v.sharding, "mesh", None) is not None
    print(f"[restore] {len(pr)} params + {len(opt_at_preempt)} optimizer "
          f"slots bit-identical on the 4-device mesh "
          f"({restore_fresh} fresh compiles during restore)")

    # ------------------- 2b. trajectory control: hand-replayed remainder
    # (restored now, before the resumed run's retention GC collects the
    # preemption step directory)
    from deeplearning4j_tpu.datapipe.reshard import remap_for
    net_c = restore_multi_layer_network(latest, mesh=mesh4,
                                        model_axis="model")
    seen_control = []
    pipe_c = build_pipeline(x, y, 1, 0, seen_control)
    pipe_c.load_state_dict(
        remap_for(pipe_c, read_checkpoint_meta(latest)["datapipe"]))
    for ds in pipe_c.stream(1):
        net_c.fit_batch(ds)

    # --------------------------- 3. new world: resume on 4 devices, 1 host
    print("[new] 4-device (data=2, model=2) mesh, lone survivor "
          "shard (1, 0)")
    net_b = build_net(args.seed).use_mesh(mesh4, model_axis="model")
    seen_resumed = []
    pipe_b = build_pipeline(x, y, 1, 0, seen_resumed)
    res_b = supervisor(net_b).fit_pipeline(pipe_b, epochs=1)
    assert res_b.status == "completed", res_b.status
    assert res_b.resumed_from == latest, (res_b.resumed_from, latest)
    reshard_events = [e for e in res_b.events if e.kind == "reshard"]
    assert reshard_events, [e.kind for e in res_b.events]
    assert res_b.stats.get("reshards_total", 0) >= 1, res_b.stats
    report_stamp = getattr(res_b.report, "reshard", None)
    assert report_stamp and report_stamp["from_mesh"]["device_count"] == 8
    assert report_stamp["to_mesh"]["device_count"] == 4
    assert report_stamp["datapipe"]["from"]["n"] == 2
    assert report_stamp["datapipe"]["to"]["n"] == 1
    print(f"[new] completed at step {res_b.final_step}; reshard event: "
          f"'{reshard_events[0].detail}'")

    # ------------------------------------------- 4a. datapipe exactness
    low_water = steps_done * BATCH * 2   # global records consumed
    assert seen_resumed == list(range(low_water, N_RECORDS)), (
        seen_resumed[:4], low_water)
    ledger = sorted(seen_host0 + seen_host1 + seen_resumed)
    assert ledger == list(range(N_RECORDS)), "records dropped or doubled"
    datapipe_exact = 1
    expected_final = steps_done + (N_RECORDS - low_water) // BATCH
    assert res_b.final_step == expected_final, (res_b.final_step,
                                                expected_final)
    print(f"[data] epoch tiled exactly: {len(seen_host0)} + "
          f"{len(seen_host1)} + {len(seen_resumed)} = {N_RECORDS} records, "
          f"low-water mark {low_water}")

    # -------------------- 4b. verdict on the hand-replayed control (2b)
    assert seen_control == seen_resumed
    pb, pc = flat_params(net_b), flat_params(net_c)
    for key in pb:
        np.testing.assert_array_equal(
            pb[key], pc[key],
            err_msg=f"resumed param {key} diverged from control replay")
    print(f"[trajectory] resumed run bit-identical to the control replay "
          f"({len(pb)} parameter arrays)")

    # ----------------------------- 5. serving fleet: restart on half width
    from deeplearning4j_tpu.serving import ReplicaSet
    fwd = lambda feats: np.asarray(feats[0], np.float64) * 2.0  # noqa: E731
    rs = ReplicaSet(fwd, 2, max_queue=64, batch_window_ms=0.0)
    rs.submit([np.ones(4)]).result(timeout=10)
    rs.restart_fleet(n=1)
    assert rs.degraded
    rows = rs.describe()
    assert len(rows) == 1 and rows[0]["degraded"] \
        and rows[0]["target_replicas"] == 2, rows
    out = rs.submit([np.ones(4)]).result(timeout=10)
    np.testing.assert_array_equal(np.asarray(out), np.full(4, 2.0))
    rs.stop()
    fleet_degraded_serving = 1
    print("[fleet] restarted on 1 of 2 replicas: still serving, "
          "scoreboard row flagged degraded")

    # ------------------------------------------------------------ receipt
    receipt = {
        "config": "reshard",
        "created_unix": round(time.time(), 2),
        "devices_before": 8, "devices_after": 4,
        "shards_before": 2, "shards_after": 1,
        "preempt_step": steps_done, "final_step": res_b.final_step,
        "records": N_RECORDS, "low_water_record": low_water,
        "bit_identical": bit_identical,
        "datapipe_exact": datapipe_exact,
        "restore_fresh_compiles": restore_fresh,
        "reshard_events": len(reshard_events),
        "fleet_degraded_serving": fleet_degraded_serving,
        "detail": {
            "checkpoint": os.path.basename(latest),
            "restore_compile_delta": delta,
            "reshard_event": reshard_events[0].detail,
            "report_stamp": report_stamp,
        },
    }
    print("\n[verdict] PASS — 8-device run resumed on 4 devices: params "
          "bit-identical, epoch coverage exact, "
          f"{restore_fresh} restore compiles")
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(receipt, fh, indent=1, sort_keys=False)
        print(f"[receipt] {args.out}")
    else:
        print(json.dumps(receipt, indent=1))
    if not args.dir:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
