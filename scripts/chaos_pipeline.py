"""Chaos datapipe demo: a SHUFFLED STREAMING input pipeline survives a
hostile schedule of injected failures and still lands on BIT-IDENTICAL
final parameters vs an uninterrupted run.

The harder twin of scripts/chaos_train.py: there the batch sequence is a
pure function of the step counter, so checkpointing the model was
enough. Here the data comes through a datapipe Pipeline — records stream
from a CSV file on disk, pass a windowed shuffle whose order depends on
RNG state, get batched, and are prefetched by a worker thread — so
"which record comes next" is pipeline STATE, not a function of the step
number. The supervisor now checkpoints that state too
(``Pipeline.state_dict()`` inside each checkpoint's ``meta.json``), and
this script proves the property end to end:

1. **Reference** — one uninterrupted supervised run over the pipeline.
2. **Chaos** — the same run, but each launch arms one fault (crash
   between the checkpoint tree commit and its ``meta.json`` rename,
   transient step errors, clean preemption mid-epoch) and every relaunch
   builds a FRESH net and a FRESH pipeline object: resume of both model
   and data position must come entirely from disk.
3. **Verdict** — every parameter array compared bit-for-bit
   (``np.testing.assert_array_equal``): a resume that replayed or
   skipped even one shuffled record would fail.

Run: ``python scripts/chaos_pipeline.py`` (CPU is fine, ~30s). The
pytest variant is ``tests/test_datapipe.py::test_chaos_resume_*``.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_enable_x64", True)  # F64 policy, like the tests


def build_net(seed):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.core import DtypePolicy
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Adam
    f64 = DtypePolicy(param_dtype="float64", compute_dtype="float64")
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .dtype(f64).list()
            .layer(Dense(n_in=12, n_out=16, activation="tanh"))
            .layer(Output(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def write_csv(path, seed, n_rows):
    """Label-first numeric CSV — the streaming source of truth on disk."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_rows):
            row = [rng.integers(0, 4)] + list(rng.normal(size=12))
            f.write(",".join(f"{v:.17g}" for v in row) + "\n")


def build_pipeline(csv_path, batch_size, seed):
    """Fresh pipeline object per launch: streaming CSV -> windowed
    shuffle -> batch -> worker prefetch. Every stage holds resumable
    state (cursor, RNG + window, partial buffers, prefetched batches)."""
    from deeplearning4j_tpu import datapipe
    return (datapipe.from_csv(csv_path, label_index=0, num_classes=4)
            .shuffle(window=4 * batch_size, seed=seed)
            .batch(batch_size, drop_last=True)
            .prefetch(2))


def flat_params(net):
    return {(n, k): np.asarray(v) for n, sub in net.params.items()
            for k, v in sub.items()}


def chaos_schedule(rows, batch_size):
    """Faults armed per launch. The preemption lands mid-epoch by
    construction (half-way through an epoch's batch count), which is the
    interesting case: resume must restart inside a half-consumed shuffle
    window. Deterministic, so reruns behave identically."""
    per_epoch = rows // batch_size
    return [
        [("crash_save", 1)],                         # kill the 2nd save
        [("transient", per_epoch + 1),               # retried in place...
         ("preempt", per_epoch + per_epoch // 2)],   # ...then die mid-epoch
        [("crash_save", 1)],                         # kill a save again
        [],                                          # clean final launch
    ]


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--rows", type=int, default=96,
                    help="CSV rows (default 96)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--checkpoint-every", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--dir", default=None,
                    help="work directory (default: fresh tempdir)")
    args = ap.parse_args()

    from deeplearning4j_tpu.resilience import (FaultInjector, InjectedCrash,
                                               SupervisorConfig,
                                               TrainingSupervisor)

    work = args.dir or tempfile.mkdtemp(prefix="chaos_pipeline_")
    os.makedirs(work, exist_ok=True)
    csv_path = os.path.join(work, "train.csv")
    write_csv(csv_path, args.seed, args.rows)

    def config(ckpt_dir):
        return SupervisorConfig(checkpoint_dir=ckpt_dir,
                                checkpoint_every_steps=args.checkpoint_every,
                                backoff_initial_s=0.01,
                                handle_sigterm=False)

    # ------------------------------------------------ 1. reference run
    steps = args.epochs * (args.rows // args.batch_size)
    print(f"[reference] {args.epochs} uninterrupted epochs "
          f"({steps} steps) over the streaming pipeline ...")
    t0 = time.perf_counter()
    ref = build_net(args.seed)
    ref_dir = os.path.join(work, "ckpt_ref")
    res = TrainingSupervisor(ref, config(ref_dir)).fit(
        build_pipeline(csv_path, args.batch_size, args.seed),
        epochs=args.epochs)
    assert res.status == "completed" and res.final_step == steps
    print(f"[reference] done in {time.perf_counter() - t0:.1f}s "
          f"(final score {float(ref.score_value):.4f})")

    # ---------------------------------------------------- 2. chaos run
    schedule = chaos_schedule(args.rows, args.batch_size)
    n_faults = sum(len(launch) for launch in schedule)
    ckpt_dir = os.path.join(work, "ckpt_chaos")
    print(f"\n[chaos] {args.epochs} epochs, checkpoint every "
          f"{args.checkpoint_every}, dir {ckpt_dir}")
    launches, net, result = 0, None, None
    totals = {}
    while True:
        launches += 1
        injector = FaultInjector()
        for fault, at in schedule[min(launches - 1, len(schedule) - 1)]:
            if fault == "crash_save":
                injector.crash_during_save(at)
            elif fault == "transient":
                injector.fail_step(at, times=2)
            elif fault == "preempt":
                injector.preempt_at_step(at)

        # fresh net AND fresh pipeline: model and data position both
        # resume from disk, exactly like a new process would
        net = build_net(args.seed)
        pipe = build_pipeline(csv_path, args.batch_size, args.seed)
        sup = TrainingSupervisor(net, config(ckpt_dir), injector=injector)
        try:
            with injector.installed():
                result = sup.fit(pipe, epochs=args.epochs)
        except InjectedCrash as e:
            print(f"[chaos] launch {launches}: KILLED mid-save ({e}) at "
                  f"step {net.iteration} — relaunching")
            for k, v in sup.stats.snapshot().items():
                totals[k] = totals.get(k, 0) + v
            continue
        for k, v in result.stats.items():
            totals[k] = totals.get(k, 0) + v
        if result.status == "preempted":
            print(f"[chaos] launch {launches}: preempted cleanly at step "
                  f"{result.final_step} (datapipe epoch {pipe.epoch}) "
                  "— relaunching")
            continue
        print(f"[chaos] launch {launches}: completed at step "
              f"{result.final_step}"
              + (f" (resumed from {os.path.basename(result.resumed_from)})"
                 if result.resumed_from else ""))
        break

    # ------------------------------------------------------ 3. verdict
    assert result.final_step == steps, (result.final_step, steps)
    pr, pc = flat_params(ref), flat_params(net)
    assert pr.keys() == pc.keys()
    for key in pr:
        np.testing.assert_array_equal(pr[key], pc[key],
                                      err_msg=f"param {key} diverged")

    print(f"\n[verdict] PASS — {launches} launches "
          f"({n_faults} injected faults, shuffled streaming source), "
          f"final step {result.final_step}, all {len(pr)} parameter "
          "arrays BIT-IDENTICAL to the uninterrupted run")
    print("[stats]  " + "  ".join(f"{k}={v}" for k, v in sorted(
        totals.items()) if v))
    if not args.dir:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
