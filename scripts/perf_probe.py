"""Perf probe: honest step timing + XLA cost breakdown for one bench config.

Usage: python scripts/perf_probe.py resnet50 --batch 256 [--image 224]
Prints a JSON line with step_ms (min-of-k, window>=min_ms), examples/sec,
MFU from XLA cost analysis, and the top HLO categories from the compiled
module's cost analysis.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from bench import (_peak_flops, bench_goodput_overhead, bench_host_loop,
                   bench_input_pipeline, bench_mixed_precision,
                   bench_trace_overhead, calibrated_step_time)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("config", choices=["resnet50", "lenet", "char_rnn",
                                       "mnist_mlp", "resnet18", "host_loop",
                                       "trace_overhead", "goodput_overhead",
                                       "input_pipeline", "mixed_precision",
                                       "serving", "transformer",
                                       "speculative"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--epochs", type=int, default=4,
                    help="host_loop: timed fit epochs")
    ap.add_argument("--n-batches", type=int, default=32,
                    help="host_loop: minibatches per epoch")
    ap.add_argument("--f32", action="store_true")
    ap.add_argument("--breakdown", action="store_true")
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="record the probe run in the span tracer and "
                    "export a Chrome trace-event file (open in Perfetto "
                    "or chrome://tracing)")
    ap.add_argument("--serving-results", metavar="RESULTS.json", default=None,
                    help="serving config: summarize an existing "
                    "serve_bench.py --out file instead of re-running the "
                    "load generator")
    args = ap.parse_args()

    tracer = None
    if args.trace:
        from deeplearning4j_tpu.observability.trace import Tracer, set_tracer
        tracer = Tracer(enabled=True)
        set_tracer(tracer)

    def finish(out):
        if tracer is not None:
            tracer.export_chrome_trace(args.trace)
            out["trace_file"] = args.trace
            out["trace_spans"] = len(tracer.spans())
        print(json.dumps(out))

    if args.config == "trace_overhead":
        # tracer on/off steps-per-sec guard (< 3% is the acceptance bar);
        # bench_trace_overhead manages its own tracers, so --trace here
        # only captures whatever the surrounding process recorded
        batch = args.batch if args.batch != 256 else 1024
        out = {"config": "trace_overhead"}
        out.update(bench_trace_overhead(
            batch=batch, n_batches=args.n_batches, epochs=args.epochs))
        finish(out)
        return

    if args.config == "goodput_overhead":
        # ledger on/off steps-per-sec guard: tracer stays ON in both
        # arms so the number isolates the goodput sink + FLOPs
        # derivation, not the span tracer itself (< 3% budget)
        batch = args.batch if args.batch != 256 else 1024
        out = {"config": "goodput_overhead"}
        out.update(bench_goodput_overhead(
            batch=batch, n_batches=args.n_batches, epochs=args.epochs))
        finish(out)
        return

    if args.config == "serving":
        # the serving round: either summarize a serve_bench.py --out
        # results file (--serving-results) or run the quick load
        # generator inline; the headline is the "summary" rollup
        # (p50/p99, rows/sec, coalesce ratio, padding-waste fraction)
        out = {"config": "serving"}
        if args.serving_results:
            with open(args.serving_results) as f:
                rep = json.load(f)
            out["results_file"] = args.serving_results
        else:
            from serve_bench import bench_serving
            rep = bench_serving(concurrencies=(16,), requests_per_client=10)
        out["model"] = rep.get("model")
        out.update(rep.get("summary") or {})
        for k, v in rep.items():
            if k.startswith("speedup_"):
                out[k] = v
        if rep.get("run_report"):
            rr = rep["run_report"]
            out["goodput_fraction"] = rr.get("goodput_fraction")
            out["device_s"] = rr.get("device_s")
        finish(out)
        return

    if args.config == "transformer" and args.serving_results:
        # summarize an existing serve_bench.py --decode --out receipt
        # (TRANSFORMER_r01.json) — the decode-serving half of the
        # transformer round; without --serving-results this config falls
        # through to the gpt_mini training-step probe below
        out = {"config": "transformer"}
        with open(args.serving_results) as f:
            rep = json.load(f)
        out["results_file"] = args.serving_results
        for k in ("model", "decode_tokens_per_sec", "inter_token_p50_ms",
                  "inter_token_p99_ms", "decode_bit_identical",
                  "kv_pool_occupancy", "kv_evictions", "reprefills",
                  "affinity_hit_rate", "train_mfu", "train_tokens_per_sec"):
            if k in rep:
                out[k] = rep[k]
        finish(out)
        return

    if args.config == "speculative":
        # speculative decode probe: either summarize an existing
        # serve_bench.py --decode --speculative --out receipt
        # (TRANSFORMER_r03.json) or run the bench.py fast entry inline
        # (draft-on vs draft-off tokens/sec on copy-task-trained nets)
        out = {"config": "speculative"}
        if args.serving_results:
            with open(args.serving_results) as f:
                rep = json.load(f)
            out["results_file"] = args.serving_results
        else:
            from bench import run_config
            rep = run_config("speculative")
        for k in ("model", "draft_model", "decode_tokens_per_sec",
                  "spec_off_tokens_per_sec", "spec_speedup_vs_off",
                  "spec_accept_tokens_per_step", "spec_rounds",
                  "spec_proposed", "spec_accepted", "spec_rejected",
                  "spec_bit_identical", "compile_delta_after_warm"):
            if k in rep:
                out[k] = rep[k]
        finish(out)
        return

    if args.config == "input_pipeline":
        # the datapipe round: records/sec + stall fraction through a
        # shuffle/batch/prefetch pipeline vs the bare in-memory gather,
        # and the pipeline's metrics/spans overhead (< 3% budget)
        batch = args.batch if args.batch != 256 else 1024
        out = {"config": "input_pipeline"}
        out.update(bench_input_pipeline(
            batch=batch, n_batches=args.n_batches, epochs=args.epochs))
        finish(out)
        return

    if args.config == "mixed_precision":
        # the precision round: lenet trained + served under the f32 vs
        # bf16 dtype policies — steps/sec and serving rows/sec ratios
        # (bench.bench_mixed_precision; PRECISION.md, PERF.md §10)
        out = {"config": "mixed_precision"}
        out.update(bench_mixed_precision(batch=args.batch))
        finish(out)
        return

    if args.config == "host_loop":
        # the fit-loop round: steps/sec through net.fit with the device
        # step subtracted (bench.bench_host_loop) — probes the host
        # dispatch path the async runtime pipelines, not the XLA step
        batch = args.batch if args.batch != 256 else 1024
        out = {"config": "host_loop"}
        out.update(bench_host_loop(batch=batch, n_batches=args.n_batches,
                                   epochs=args.epochs))
        finish(out)
        return

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet

    rng = np.random.default_rng(0)
    dtype = zoo.F32 if args.f32 else None
    is_graph = False

    if args.config == "resnet50":
        net = zoo.resnet50(image_size=args.image, dtype=dtype)
        x = rng.normal(size=(args.batch, args.image, args.image, 3)).astype(np.float32)
        y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, args.batch)]
        is_graph = True
    elif args.config == "resnet18":
        net = zoo.resnet18(image_size=args.image, dtype=dtype)
        x = rng.normal(size=(args.batch, args.image, args.image, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, args.batch)]
        is_graph = True
    elif args.config == "lenet":
        net = zoo.lenet(dtype=dtype)
        x = rng.normal(size=(args.batch, 28, 28, 1)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, args.batch)]
    elif args.config == "mnist_mlp":
        net = zoo.mnist_mlp(dtype=dtype)
        x = rng.normal(size=(args.batch, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, args.batch)]
    elif args.config == "transformer":
        # gpt_mini training step (the bench.py `transformer` shape);
        # --seq sets the window, default batch drops to 8
        b = args.batch if args.batch != 256 else 8
        t = args.seq if args.seq != 64 else 128
        args.batch = b
        net = zoo.gpt_mini(vocab_size=80, width=256, n_layers=4,
                           n_heads=4, max_len=t, dtype=dtype)
        ids = rng.integers(0, 80, (b, t))
        x = np.eye(80, dtype=np.float32)[ids]
        y = np.eye(80, dtype=np.float32)[rng.integers(0, 80, (b, t))]
    else:
        net = zoo.char_rnn(vocab_size=80, hidden=args.hidden, n_layers=2,
                           dtype=dtype)
        ids = rng.integers(0, 80, (args.batch, args.seq))
        x = np.eye(80, dtype=np.float32)[ids]
        y = np.eye(80, dtype=np.float32)[rng.integers(0, 80, (args.batch, args.seq))]

    xd, yd = jnp.asarray(x), jnp.asarray(y)
    ds = MultiDataSet([xd], [yd]) if is_graph else DataSet(xd, yd)

    t0 = time.perf_counter()
    sec_per_step, n = calibrated_step_time(net, ds, min_window_s=0.2, scan0=10)
    total = time.perf_counter() - t0

    out = {
        "config": args.config,
        "batch": args.batch,
        "step_ms": round(1000 * sec_per_step, 3),
        "examples_per_sec": round(args.batch / sec_per_step, 1),
        "scan_len": n,
        "bench_wall_s": round(total, 1),
    }
    if args.config in ("char_rnn", "transformer"):
        out["tokens_per_sec"] = round(
            args.batch * x.shape[1] / sec_per_step, 1)

    # cost analysis of the single fused step
    try:
        it = jnp.asarray(0, jnp.int32)
        k = jax.random.PRNGKey(0)
        if is_graph:
            sargs = (net.params, net.state, net.opt_state, it,
                     {net.conf.network_inputs[0]: xd}, [yd], {}, None, k)
        else:
            sargs = (net.params, net.state, net.opt_state, it, xd, yd,
                     None, None, k)
        compiled = net._train_step.lower(*sargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        bytes_ = float(cost.get("bytes accessed", 0.0))
        out["step_gflops"] = round(flops / 1e9, 2)
        out["step_gbytes"] = round(bytes_ / 1e9, 3)
        peak = _peak_flops(jax.devices()[0])
        if peak and sec_per_step > 0:
            out["mfu"] = round(flops / sec_per_step / peak, 4)
            out["achieved_tflops"] = round(flops / sec_per_step / 1e12, 1)
            out["hbm_gb_per_s"] = round(bytes_ / sec_per_step / 1e9, 1)
        mem = compiled.memory_analysis()
        if mem is not None:
            out["peak_mem_gb"] = round(
                (getattr(mem, "temp_size_in_bytes", 0)
                 + getattr(mem, "argument_size_in_bytes", 0)
                 + getattr(mem, "output_size_in_bytes", 0)) / 1e9, 2)
    except Exception as e:
        out["cost_error"] = repr(e)

    finish(out)


if __name__ == "__main__":
    main()
