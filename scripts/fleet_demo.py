"""Fleet observability demo: 3 worker processes, one merged view.

Proves the cross-process observability plane end to end:

1. The parent starts a UIServer (the aggregator) on an ephemeral port.
2. It spawns ``--workers`` child processes (this script with
   ``--worker``), all sharing one ``DL4J_TPU_RUN_ID`` but each with its
   own ``DL4J_TPU_INSTANCE``. Every worker trains a tiny MLP for
   ``--steps`` steps and pushes ``export_snapshot()`` (full-fidelity
   metric families + identity + health) to the aggregator's
   ``POST /api/metrics_push`` — once mid-fit, once at exit.
3. The parent then fetches:
   - ``GET /metrics`` (``Accept: text/plain``) — ONE merged Prometheus
     exposition: every child sample labeled ``instance="worker-N"``,
     the aggregator folded in as its own instance, and a fleet rollup
     sample per series (``instance="fleet"``: counters summed, gauges
     last-write);
   - ``GET /api/fleet`` — the health scoreboard (liveness from
     heartbeat age, readiness, queue depth, step progress).
4. It ASSERTS the merge is correct — per-instance ``dl4j_fit_steps_total``
   samples exist for every worker and the fleet rollup equals their sum
   — and that every worker scores live on the scoreboard.

``--out fleet.json`` saves the scoreboard payload;
``scripts/check_budgets.py --fleet fleet.json`` gates it in CI
(``max_heartbeat_age_s``, ``min_live``).

Run: ``python scripts/fleet_demo.py`` (CPU, ~30s — dominated by three
XLA compiles of the tiny net). The pytest variant is the slow-marked
``tests/test_distributed_obs.py::test_fleet_demo_subprocess_slow``.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ----------------------------------------------------------------- worker
def build_net(seed: int):
    import numpy as np

    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Adam
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(Dense(n_in=12, n_out=16, activation="tanh"))
            .layer(Output(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(96, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 96)]
    return MultiLayerNetwork(conf).init(), x, y


def run_worker(args) -> int:
    """One fleet member: tiny fit + snapshot pushes to the aggregator."""
    from deeplearning4j_tpu.observability import distributed as dist
    from deeplearning4j_tpu.observability import metrics as om
    om.install_runtime_metrics()
    ident = dist.get_identity()
    net, x, y = build_net(seed=17 + args.seed_offset)
    epochs = max(1, args.steps // (len(x) // 32))
    net.fit(x, y, epochs=epochs, batch_size=32)
    # push AFTER the fit so the snapshot carries real step counters;
    # a second push proves last-write-wins replacement at the aggregator.
    # attempts=5: an aggregator mid-restart costs a delayed heartbeat,
    # not a permanently dropped worker
    for _ in range(2):
        reply = dist.push_snapshot(args.push, health={"healthy": True},
                                   attempts=5)
        time.sleep(0.05)
    print(f"[worker {ident.instance}] pushed "
          f"(aggregator sees {reply['instances']} instance(s))")
    return 0


# ----------------------------------------------------------------- parent
def _fetch(url: str, accept: str = None) -> bytes:
    req = urllib.request.Request(
        url, headers={"Accept": accept} if accept else {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.read()


def _series_values(exposition: str, family: str) -> dict:
    """{instance: value} for one family's plain (suffix-less) samples."""
    out = {}
    pat = re.compile(
        rf'^{family}\{{([^}}]*)\}} ([^\s]+)$', re.M)
    for labels, value in pat.findall(exposition):
        m = re.search(r'instance="([^"]*)"', labels)
        if m:
            out[m.group(1)] = float(value)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--steps", type=int, default=6,
                    help="fit steps per worker (default 6)")
    ap.add_argument("--out", default=None,
                    help="write the /api/fleet payload here (feed to "
                         "check_budgets.py --fleet)")
    # worker mode (internal): spawned by the parent
    ap.add_argument("--worker", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--push", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--seed-offset", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.worker is not None:
        return run_worker(args)

    from deeplearning4j_tpu.observability import distributed as dist
    from deeplearning4j_tpu.ui.server import UIServer

    run_id = dist.get_identity().run_id
    ui = UIServer(port=0)
    push_url = f"{ui.url.rstrip('/')}/api/metrics_push"
    print(f"[fleet] run_id {run_id}; aggregator at {ui.url} "
          f"(push endpoint {push_url})")

    procs = []
    for i in range(args.workers):
        env = dict(os.environ)
        env["DL4J_TPU_RUN_ID"] = run_id
        env["DL4J_TPU_INSTANCE"] = f"worker-{i}"
        env.setdefault("JAX_PLATFORMS", "cpu")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", f"worker-{i}", "--push", push_url,
             "--steps", str(args.steps), "--seed-offset", str(i)],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    rcs = [p.wait(timeout=300) for p in procs]
    if any(rcs):
        print(f"[fleet] FAIL — worker exit codes {rcs}")
        return 1

    # ---- merged Prometheus exposition -------------------------------
    text = _fetch(f"{ui.url.rstrip('/')}/metrics",
                  accept="text/plain").decode()
    steps = _series_values(text, "dl4j_fit_steps_total")
    expected = {f"worker-{i}" for i in range(args.workers)}
    missing = expected - set(steps)
    assert not missing, f"no per-instance samples for {sorted(missing)}"
    worker_sum = sum(v for k, v in steps.items() if k in expected)
    # the fleet rollup also folds in the aggregator's own (0-step)
    # counter; for counters the rollup is the plain sum
    rollup = steps.get("fleet")
    total = sum(v for k, v in steps.items() if k != "fleet")
    assert rollup is not None and abs(rollup - total) < 1e-9, (
        f"fleet rollup {rollup} != sum {total}")
    hb = _series_values(text, "dl4j_heartbeat_timestamp_seconds")
    assert expected <= set(hb), "workers missing heartbeat samples"
    print(f"[fleet] merged exposition: {len(text.splitlines())} lines, "
          f"per-instance steps {{" + ", ".join(
              f"{k}: {int(v)}" for k, v in sorted(steps.items())) + "}")
    for line in text.splitlines():
        if line.startswith("dl4j_fit_steps_total"):
            print("         " + line)

    # ---- health scoreboard ------------------------------------------
    fleet = json.loads(_fetch(f"{ui.url.rstrip('/')}/api/fleet"))
    by_tag = {r["instance"]: r for r in fleet["instances"]}
    assert expected <= set(by_tag), by_tag.keys()
    stale = [t for t in expected if not by_tag[t]["live"]]
    assert not stale, f"workers scored stale: {stale}"
    print(f"[fleet] scoreboard: {fleet['ready']}/{len(fleet['instances'])} "
          "ready — " + "  ".join(
              f"{t}: hb_age={by_tag[t]['heartbeat_age_s']}s "
              f"steps={by_tag[t]['steps_total']}"
              for t in sorted(expected)))

    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(fleet, f, indent=2)
        os.replace(tmp, args.out)
        print(f"[fleet] scoreboard saved to {args.out} "
              "(gate: scripts/check_budgets.py --fleet)")

    ui.stop()
    print(f"\n[verdict] PASS — {args.workers} workers, one merged "
          "exposition with per-instance labels + correct fleet rollup, "
          "all members live on the scoreboard")
    return 0


if __name__ == "__main__":
    sys.exit(main())
