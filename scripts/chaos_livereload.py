"""Live-reload chaos drill: versioned hot swap under load, canary
gating, and rollback-as-a-verb — the receipt behind BUDGETS.json
``live_reload`` (LIVERELOAD_r01.json).

One topology, two arms, real HTTP end to end — a FrontDoorRouter
federating two in-process ModelServer hosts, closed-loop clients
hammering ``/predict`` the whole time:

- **Good update (zero-downtime promotion).** Train a tiny MLN with the
  resilience supervisor, publish the checkpoint (v1), train further,
  publish again (v2). Both hosts boot on v1. Under live client load,
  host B is hot-swapped to v2 and canaried at a pinned traffic
  fraction; the canary passes its gates (live federation deltas) and
  is promoted; host A then hot-swaps in a rolling pass over its
  replicas. Every reply in the whole window must classify bit-exactly
  as v1-weights or v2-weights output (no torn or garbage replies),
  zero requests may be lost or errored, the longest gap between
  successful completions across the swaps (the "blackout") is
  measured, and the swap must compile NOTHING fresh — the publication
  binds into the warmed jit cache (serving/publish.py fingerprint
  discipline).

- **Bad update (canary catch + rollback).** A poisoned v3 (all-NaN
  params — the classic corrupted-promotion failure) is published and
  boots on a third host, canaried at fraction 0.25. The serving NaN
  sentinel (ModelServer.predict) counts poisoned reply rows, the
  federation push carries them, and ``evaluate_canary`` kills the
  version on the ``max_nan_rows`` gate — before ``min_requests``, one
  poisoned reply is already the evidence. ``rollback_canary``
  quarantines the host and flushes a flight-recorder artifact (reason
  ``"rollback"``) naming the rejected version and the killing delta;
  ``WeightStore.rollback`` repoints LATEST back to v2. Containment is
  structural (token bucket: exposure can never exceed the fraction)
  and the receipt proves it, plus post-rollback replies bit-identical
  to the v2 reference.

Run::

    python scripts/chaos_livereload.py --out LIVERELOAD_r01.json
    python scripts/check_budgets.py --bench LIVERELOAD_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _mlp(seed: int = 7):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(Dense(n_in=8, n_out=16, activation="relu"))
            .layer(Output(n_in=16, n_out=4, activation="softmax",
                          loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(url, path, obj, timeout=30.0):
    req = urllib.request.Request(
        url.rstrip("/") + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class _Clients:
    """Closed-loop /predict load with per-reply bitwise version
    classification and completion timestamps — the lost/blackout
    evidence. ``tags``: "v1" / "v2" / "nan" / "other"."""

    def __init__(self, url, x, refs, n_threads=8, pause_s=0.002):
        import numpy as np
        self.url, self.x = url, x.tolist()
        self.refs = refs              # {"v1": ndarray, "v2": ndarray}
        self.np = np
        self.pause_s = pause_s
        self.lock = threading.Lock()
        self.sent = 0
        self.results = []             # (t_done, tag) for 200 replies
        self.http_errors = 0          # non-200 replies
        self.lost = 0                 # no reply at all (timeout/reset)
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._run, daemon=True)
                         for _ in range(n_threads)]

    def _classify(self, preds):
        arr = self.np.asarray(preds, self.np.float32)
        for tag, ref in self.refs.items():
            if arr.shape == ref.shape and self.np.array_equal(arr, ref):
                return tag
        if not self.np.isfinite(arr).all():
            return "nan"
        return "other"

    def _run(self):
        while not self._stop.is_set():
            with self.lock:
                self.sent += 1
            try:
                st, out = _post(self.url, "/predict",
                                {"features": self.x})
                t = time.time()
                if st == 200:
                    tag = self._classify(out["predictions"])
                    with self.lock:
                        self.results.append((t, tag))
                else:
                    with self.lock:
                        self.http_errors += 1
            except Exception:
                with self.lock:
                    self.lost += 1
            if self.pause_s:
                time.sleep(self.pause_s)

    def start(self):
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)

    def counts(self):
        with self.lock:
            tags = {}
            for _, tag in self.results:
                tags[tag] = tags.get(tag, 0) + 1
            return {"sent": self.sent, "ok": len(self.results),
                    "http_errors": self.http_errors, "lost": self.lost,
                    "tags": tags}

    def max_gap_ms(self, t_from, t_to):
        """Longest stretch inside [t_from, t_to] with no successful
        completion — the observed swap blackout."""
        with self.lock:
            ts = sorted(t for t, _ in self.results)
        marks = [t_from] + [t for t in ts if t_from <= t <= t_to] + [t_to]
        return round(max(b - a for a, b in zip(marks, marks[1:])) * 1000, 3)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="LIVERELOAD_r01.json")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--fraction", type=float, default=0.25)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from deeplearning4j_tpu.observability import metrics as obs
    from deeplearning4j_tpu.observability.flightrec import (
        install_flight_recorder)
    from deeplearning4j_tpu.serving import (FrontDoorRouter, ModelServer,
                                            WeightStore, load_net)
    from deeplearning4j_tpu.utils.checkpoint import save_checkpoint

    work = tempfile.mkdtemp(prefix="livereload_")
    install_flight_recorder(os.path.join(work, "flightrec"))
    rng = np.random.default_rng(args.seed)
    X = rng.normal(size=(256, 8)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=256)]
    x = X[:4]

    # ---- train -> publish v1, train more -> publish v2 (the seam) ----
    train_dir = os.path.join(work, "train")
    store = WeightStore(os.path.join(work, "store"), keep=3)
    net = _mlp(args.seed)
    net.resilient_fit(X, Y, checkpoint_dir=train_dir, epochs=1,
                      batch_size=32, checkpoint_every_steps=4,
                      keep_checkpoints=3)
    p1 = store.publish_latest(train_dir, source=train_dir)
    net.resilient_fit(X, Y, checkpoint_dir=train_dir, epochs=2,
                      batch_size=32, checkpoint_every_steps=4,
                      keep_checkpoints=3)
    p2 = store.publish_latest(train_dir, source=train_dir)
    assert p2.version > p1.version

    # ---- poisoned v3: all-NaN params, the corrupted promotion ----
    import jax
    import jax.numpy as jnp
    netP = load_net(p2.path)
    netP.params = jax.tree_util.tree_map(
        lambda a: jnp.full_like(a, jnp.nan), netP.params)
    poison_ckpt = os.path.join(work, "poison", "step_999")
    save_checkpoint(netP, poison_ckpt)
    p3 = store.publish(poison_ckpt, source="poisoned")

    ref_v1 = np.asarray(load_net(p1.path).output(x))
    ref_v2 = np.asarray(load_net(p2.path).output(x))
    assert not np.array_equal(ref_v1, ref_v2)

    # ---- topology: router + 2 hosts on v1, heartbeats pushing ----
    router = FrontDoorRouter(stale_after_s=5.0).start()
    push = router.url + "/api/metrics_push"
    host_a = ModelServer(load_net(p1.path), port=0, replicas=2,
                         batch_window_ms=1.0, push_url=push,
                         push_interval_s=0.25).start()
    host_b = ModelServer(load_net(p1.path), port=0, replicas=1,
                         batch_window_ms=1.0, push_url=push,
                         push_interval_s=0.25).start()
    router.add_host(host_a.url)
    router.add_host(host_b.url)

    receipt = {"config": "live_reload",
               "model": "mlp 8-16-4 (resilient_fit checkpoints)",
               "clients": args.clients,
               "canary_fraction": args.fraction,
               "created_unix": round(time.time(), 3),
               "store": store.describe(),
               "versions": {"v1": p1.version, "v2": p2.version,
                            "v3_poisoned": p3.version}}
    host_c = None
    clients = _Clients(router.url, x, {"v1": ref_v1, "v2": ref_v2},
                       n_threads=args.clients).start()
    try:
        time.sleep(1.0)  # load + first heartbeat pushes land

        # ---- arm 1: canary v2 on host B, promote, roll host A ----
        compile0 = obs.compile_snapshot()
        t_swap0 = time.time()
        swap_b = host_b.hot_swap(p2)
        router.start_canary(host_b.url, version=p2.version,
                            fraction=args.fraction, max_nan_rows=0,
                            min_requests=20, max_p99_ratio=10.0)
        verdict = None
        deadline = time.time() + 30
        while time.time() < deadline:
            verdict = router.evaluate_canary()
            if verdict["decision"] != "wait":
                break
            time.sleep(0.2)
        if verdict is None or verdict["decision"] != "pass":
            raise RuntimeError(f"good canary did not pass: {verdict}")
        promoted = router.promote_canary()
        swap_a = host_a.hot_swap(p2)
        t_swap1 = time.time()
        time.sleep(0.5)  # post-swap serving inside the compile window
        serve_delta = obs.compile_delta(compile0)
        blackout_ms = clients.max_gap_ms(t_swap0, t_swap1 + 0.25)
        receipt["good_update"] = {
            "swap_host_b": swap_b, "swap_host_a": swap_a,
            "canary_verdict": verdict, "promoted": promoted,
            "swap_window_s": round(t_swap1 - t_swap0, 3),
            "swap_window_compiles": serve_delta["count"]}
        receipt["swap_fresh_compiles"] = (swap_a["fresh_compiles"]
                                          + swap_b["fresh_compiles"]
                                          + serve_delta["count"])
        receipt["swap_blackout_ms"] = blackout_ms
        arm1 = clients.counts()

        # ---- arm 2: poisoned v3 canary on a fresh host C ----
        host_c = ModelServer(load_net(p3.path), port=0, replicas=1,
                             batch_window_ms=1.0, push_url=push,
                             push_interval_s=0.25).start()
        router.start_canary(host_c.url, version=p3.version,
                            fraction=args.fraction, max_nan_rows=0,
                            min_requests=50)
        verdict = None
        deadline = time.time() + 30
        while time.time() < deadline:
            verdict = router.evaluate_canary()
            if verdict["decision"] == "fail":
                break
            time.sleep(0.2)
        if verdict is None or verdict["decision"] != "fail":
            raise RuntimeError(f"poisoned canary not caught: {verdict}")
        rb = router.rollback_canary(verdict, reason="nan sentinel tripped")
        store_after = store.rollback(
            "canary v%d failed: %s" % (p3.version,
                                       verdict["killed_by"]["gate"]))
        host_c.stop()
        host_c = None
        clients.stop()
        arm2 = clients.counts()

        # flight-recorder artifact: parse it back, prove the verb left
        # a post-mortem trail naming the rejected version
        with open(rb["artifact"]) as f:
            flight = json.load(f)
        ev = next(e for e in flight["events"]
                  if e["kind"] == "canary_rollback")
        ev_detail = json.loads(ev["detail"])
        assert ev_detail["rejected_version"] == p3.version
        assert flight["reason"] == "rollback"

        # post-rollback: the fleet serves v2, bit for bit
        post_ok = 0
        for _ in range(20):
            st, out = _post(router.url, "/predict", {"features": x.tolist()})
            if st == 200 and np.array_equal(
                    np.asarray(out["predictions"], np.float32), ref_v2):
                post_ok += 1
        exposed = arm2["tags"].get("nan", 0) - arm1["tags"].get("nan", 0)
        arm2_reqs = arm2["ok"] - arm1["ok"]
        exposure = (exposed / arm2_reqs) if arm2_reqs else 0.0

        receipt["bad_update"] = {
            "canary_verdict": verdict, "rollback": {
                k: v for k, v in rb.items() if k != "artifact"},
            "rollback_artifact": rb["artifact"],
            "flight_reason": flight["reason"],
            "rejected_version_in_artifact": ev_detail["rejected_version"],
            "store_latest_after_rollback": store_after.version,
            "canary_requests_window": arm2_reqs,
            "canary_exposed_replies": exposed,
            "post_rollback_checks": post_ok}
        rstats = router.describe()
        receipt["router"] = {k: rstats[k] for k in (
            "requests_total", "canary_routed_total", "promotions_total",
            "rollbacks_total", "auto_evicted_total", "evicted_total",
            "quarantined")}
        receipt["traffic"] = arm2
        # ---- the gated scalars ----
        receipt["requests_total"] = arm2["sent"]
        receipt["lost_requests"] = arm2["lost"]
        receipt["client_errors"] = arm2["http_errors"]
        receipt["unclassified_replies"] = arm2["tags"].get("other", 0)
        receipt["promotions"] = rstats["promotions_total"]
        receipt["rollback_events"] = rstats["rollbacks_total"]
        receipt["nan_rows_detected"] = verdict["deltas"]["nan_rows"]
        receipt["canary_exposure_fraction"] = round(exposure, 4)
        receipt["canary_contained"] = int(
            0 < exposed and exposure <= args.fraction)
        receipt["post_rollback_bit_identical"] = int(post_ok == 20)
        receipt["store_latest_is_v2"] = int(store_after.version
                                            == p2.version)
    finally:
        clients.stop()
        if host_c is not None:
            host_c.stop()
        host_a.stop()
        host_b.stop()
        router.stop()

    with open(args.out + ".tmp", "w") as f:
        json.dump(receipt, f, indent=1)
    os.replace(args.out + ".tmp", args.out)
    print(json.dumps({k: receipt[k] for k in (
        "requests_total", "lost_requests", "client_errors",
        "swap_blackout_ms", "swap_fresh_compiles", "promotions",
        "rollback_events", "canary_exposure_fraction",
        "canary_contained", "post_rollback_bit_identical")}, indent=1))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
