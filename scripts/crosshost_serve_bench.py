"""Cross-host serving federation benchmark: rows/sec-vs-hosts through
one FrontDoorRouter, warm-boot compile counts off the shared cache, and
bit-identical decode failover across REAL host processes.

The receipt behind BUDGETS.json ``cross_host_serving``
(CROSSHOST_SERVE_r01.json). Four arms, one topology — a parent-process
``FrontDoorRouter`` federating 2 child ``ModelServer`` processes
(``--child-host`` mode), every host a real subprocess with its own
/predict + /decode, pushing heartbeats to the router:

- **warm boot**: both hosts share one persistent-compile-cache dir
  (``DL4J_TPU_COMPILE_CACHE`` semantics); host 0 pays the fresh XLA
  compiles, host 1 must boot with ``fresh_compiles == 0`` — the PR 10
  cold/warm arms measured ACROSS hosts instead of across boots.
- **scaling**: closed-loop /predict load through the router at 1 host,
  then again after host 1 joins live (``add_host`` mid-run): the gated
  ``host_scaling_ratio`` is rows/sec(2 hosts) / rows/sec(1 host)
  through the SAME front door. Hosts simulate the accelerator exactly
  like ``serve_bench --fleet``: real (tiny) forward for row
  correctness, then a GIL-released sleep standing in for the device —
  so N host processes model N accelerator hosts on this CPU box.
- **decode failover**: sessionful greedy decode through the router's
  session-affine /decode; mid-generation the bench SIGKILLs the host
  holding the pinned sessions. The router evicts it on the connection
  error and re-pins to the survivor, whose DecodeEngine re-prefills
  from the router-held token history — every completed stream must
  match the sequential ``rnn_time_step`` reference bit for bit.
- **degraded health**: router /healthz must read ``ok`` with both
  hosts live and ``degraded`` (still 200) after the kill.

Run: ``python scripts/crosshost_serve_bench.py --out
CROSSHOST_SERVE_r01.json`` then ``python scripts/check_budgets.py
--bench CROSSHOST_SERVE_r01.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# decode model config — shared by every host AND the parent's reference
# net, so all processes compile identical programs and produce
# identical logits (gpt_mini is seed-deterministic)
DECODE_CFG = dict(vocab_size=31, width=32, n_layers=2, n_heads=2,
                  max_len=96, max_cache_len=96)


# ------------------------------------------------------------------- child
def child_main(args) -> int:
    """One serving host in a pristine process: warmed ModelServer
    (predict MLP + gpt_mini DecodeEngine) against the SHARED compile
    cache, heartbeats pushed to the router, simulated device patched in
    AFTER warm-up (so every warm-up compile is real). Prints one ready
    JSON line, then serves until stdin closes (or SIGKILL)."""
    import numpy as np

    from deeplearning4j_tpu.observability import metrics as obs
    from deeplearning4j_tpu.serving import DecodeEngine
    from deeplearning4j_tpu.serving.server import ModelServer
    from deeplearning4j_tpu.zoo import gpt_mini
    from serve_bench import _serving_mlp

    net = _serving_mlp(args.hidden, args.depth)
    engine = DecodeEngine(gpt_mini(**DECODE_CFG), n_pages=64,
                          page_tokens=8)
    server = ModelServer(net, port=0, max_batch=args.max_batch,
                         batch_window_ms=1.0, max_queue=4096,
                         compile_cache_dir=args.cache_dir,
                         decode_engine=engine,
                         push_url=args.push_url or None,
                         push_interval_s=0.5).start()
    engine.warm()
    snap = obs.compile_snapshot()
    # backend_compile_duration fires on cache hits too (it times the
    # retrieve-or-compile), so fresh XLA compiles = events - hits
    boot = {"ready": True, "port": server.port, "url": server.url,
            "pid": os.getpid(),
            "compile_count": snap["count"],
            "cache_hits": snap["cache_hits"],
            "cache_misses": snap["cache_misses"],
            "fresh_compiles": snap["count"] - snap["cache_hits"]}

    # the simulated accelerator (serve_bench.bench_fleet pattern): the
    # real forward keeps rows bit-identical, the GIL-released sleep is
    # the device executing the bucket — patched AFTER warm-up so the
    # compile counts above measure real XLA work
    real = server._device_forward

    def simulated(feats, _real=real):
        out = _real(feats)
        np.asarray(out)
        time.sleep(args.device_sim_ms / 1000.0)
        return out

    for rep in server.fleet.replicas:
        rep.batcher._forward = simulated

    print(json.dumps(boot), flush=True)
    try:
        for _ in sys.stdin:   # parent closes stdin (or SIGKILLs us)
            pass
    except Exception:
        pass
    server.stop()
    return 0


# ------------------------------------------------------------------ parent
def spawn_host(idx: int, cache_dir: str, push_url: str, run_id: str,
               args, timeout_s: float = 900.0) -> dict:
    """Launch one ``--child-host`` process and block for its ready
    line. Returns {proc, url, port, boot} — ``boot`` carries the
    compile receipts."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child-host",
           "--cache-dir", cache_dir, "--push-url", push_url or "",
           "--hidden", str(args.hidden), "--depth", str(args.depth),
           "--max-batch", str(args.max_batch),
           "--device-sim-ms", str(args.device_sim_ms)]
    env = {**os.environ,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
           "DL4J_TPU_RUN_ID": run_id,
           "DL4J_TPU_INSTANCE": f"host{idx}"}
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=_REPO, env=env)
    deadline = time.monotonic() + timeout_s
    line = proc.stdout.readline()
    while line and not line.startswith("{"):
        line = proc.stdout.readline()   # skip any stray warnings
        if time.monotonic() > deadline:
            break
    if not line:
        proc.kill()
        err = proc.stderr.read()
        raise RuntimeError(f"host{idx} died before ready:\n{err[-2000:]}")
    boot = json.loads(line)
    return {"proc": proc, "url": boot["url"], "port": boot["port"],
            "boot": boot}


def stop_host(host: dict) -> None:
    proc = host["proc"]
    if proc.poll() is None:
        try:
            proc.stdin.close()   # EOF -> graceful server.stop()
        except Exception:
            pass
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def kill_host(host: dict) -> None:
    """SIGKILL — the host-death arm. No drain, no goodbye: pooled
    router connections see RST, exactly like a crashed machine."""
    proc = host["proc"]
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)


def _post(url: str, path: str, obj: dict, timeout: float = 120.0):
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url: str, path: str, timeout: float = 30.0):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url.rstrip("/") + path,
                                    timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def reference_streams(prompts, n_tokens: int):
    """Per-session greedy reference: the sequential ``rnn_time_step``
    path on a fresh same-config net — the bit-identity oracle for
    every routed (and failed-over) decode stream."""
    import numpy as np

    from deeplearning4j_tpu.zoo import gpt_mini

    net = gpt_mini(**DECODE_CFG)
    v = DECODE_CFG["vocab_size"]

    def one_hot(tok):
        oh = np.zeros((1, 1, v), np.float32)
        oh[0, 0, tok] = 1.0
        return oh

    streams = []
    for ids in prompts:
        net.rnn_clear_previous_state()
        logits = None
        for tok in ids:
            logits = np.asarray(net.rnn_time_step(one_hot(tok)))[0, -1]
        toks = []
        for _ in range(n_tokens):
            nxt = int(np.argmax(logits))
            toks.append(nxt)
            logits = np.asarray(net.rnn_time_step(one_hot(nxt)))[0, -1]
        streams.append(toks)
    return streams


def decode_failover_arm(router, hosts, n_sessions: int = 6,
                        kill_after: int = None,
                        n_tokens: int = 18) -> dict:
    """Greedy-decode ``n_sessions`` concurrent sessions through the
    router; after every session has ``kill_after`` tokens, SIGKILL one
    host that holds pinned sessions; finish the streams on the
    survivor(s). Returns the bit-identity and affinity receipts."""
    import numpy as np

    if kill_after is None:
        # kill with a real post-kill tail: ~2/3 through the stream
        kill_after = max(1, n_tokens * 2 // 3)
    rng = np.random.default_rng(7)
    v = DECODE_CFG["vocab_size"]
    prompts = [[int(t) for t in rng.integers(1, v, size=4)]
               for _ in range(n_sessions)]
    refs = reference_streams(prompts, n_tokens)

    results = [None] * n_sessions
    recovered = [0] * n_sessions
    barrier = threading.Barrier(n_sessions + 1)

    def session(i: int):
        sid = f"bench-s{i}"
        st, out = _post(router.url, "/decode",
                        {"op": "prefill", "sid": sid, "ids": prompts[i]})
        assert st == 200, (st, out)
        logits = np.asarray(out["logits"], np.float32)
        toks = []
        for t in range(n_tokens):
            nxt = int(np.argmax(logits))
            toks.append(nxt)
            st, out = _post(router.url, "/decode",
                            {"op": "step", "sid": sid, "token": nxt})
            assert st == 200, (st, out)
            if out.get("recovered"):
                recovered[i] += 1
            logits = np.asarray(out["logits"], np.float32)
            if t + 1 == kill_after:
                barrier.wait(timeout=600)   # all sessions mid-stream
                barrier.wait(timeout=600)   # ...until the kill landed
        _post(router.url, "/decode", {"op": "close", "sid": sid})
        results[i] = toks

    threads = [threading.Thread(target=session, args=(i,), daemon=True)
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    barrier.wait(timeout=600)
    # kill a host that actually holds pinned sessions (affinity spreads
    # them; either way at least one host carries some)
    pinned_urls = {h.base_url for h in router._affinity.values()}
    victim = next((h for h in hosts
                   if h["url"].rstrip("/") in pinned_urls), hosts[0])
    kill_host(victim)
    barrier.wait(timeout=600)
    for t in threads:
        t.join(timeout=600)

    done = [r for r in results if r is not None]
    identical = sum(1 for r, ref in zip(results, refs) if r == ref)
    d = router.describe()
    hits, misses = d["affinity_hits"], d["affinity_misses"]
    return {
        "sessions": n_sessions,
        "tokens_per_session": n_tokens,
        "kill_after_tokens": kill_after,
        "killed_host": victim["url"],
        "sessions_completed": len(done),
        "sessions_bit_identical": identical,
        "failover_bit_identical": round(identical / n_sessions, 4),
        "failover_recoveries": sum(recovered),
        "failovers_total": d["failovers_total"],
        "session_affinity_hit_rate": round(hits / (hits + misses), 4)
        if hits + misses else None,
        "affinity_hits": hits, "affinity_misses": misses,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child-host", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--cache-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--push-url", default="", help=argparse.SUPPRESS)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--depth", type=int, default=2)
    # sized so the HOST tier is the bottleneck even on a 1-core box:
    # per-host capacity = max_batch/device_sim_ms = 160 rows/s, well
    # under what the shared-core client+router tier can push (~550+),
    # so the 1->2 host ratio measures host scaling, not the generator
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--device-sim-ms", type=float, default=70.0)
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--requests", type=int, default=30,
                    help="requests per client per load phase")
    ap.add_argument("--sessions", type=int, default=6)
    ap.add_argument("--gen-tokens", type=int, default=18)
    ap.add_argument("--out", default=None,
                    help="artifact path (check_budgets --bench gates it)")
    args = ap.parse_args(argv)
    if args.child_host:
        return child_main(args)

    import numpy as np

    from deeplearning4j_tpu.compilecache import atomic_publish
    from deeplearning4j_tpu.serving import FrontDoorRouter
    from serve_bench import _serving_mlp, run_load

    report: dict = {
        "config": "cross_host_serving",
        "model": f"serving_mlp 64-{args.hidden}x{args.depth}-10 "
                 f"+ gpt_mini decode",
        "device_sim_ms": args.device_sim_ms,
        "max_batch": args.max_batch, "clients": args.clients,
        "created_unix": round(time.time(), 3),
    }
    # the /predict bit-identity reference (children build the SAME
    # seed-deterministic MLP)
    net = _serving_mlp(args.hidden, args.depth)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 64)).astype(np.float32)
    reference = np.asarray(net.output(x))

    run_id = f"crosshost-{os.getpid()}"
    router = FrontDoorRouter(stale_after_s=3.0).start()
    push_url = router.url + "/api/metrics_push"
    hosts = []
    try:
        with tempfile.TemporaryDirectory(
                prefix="dl4j_crosshost_") as tmp:
            cache = os.path.join(tmp, "shared-xla-cache")

            print("== host 0: cold boot (fresh compiles) ==",
                  file=sys.stderr)
            h0 = spawn_host(0, cache, push_url, run_id, args)
            hosts.append(h0)
            print("== host 1: warm boot off host 0's cache ==",
                  file=sys.stderr)
            h1 = spawn_host(1, cache, push_url, run_id, args)
            hosts.append(h1)
            report["hosts"] = {"host0": h0["boot"], "host1": h1["boot"]}

            print("== scaling: load at 1 host, then 2, same router ==",
                  file=sys.stderr)
            router.add_host(h0["url"])
            r1 = run_load(router.port, x, reference, args.clients,
                          args.requests)
            if "error" in r1:
                raise RuntimeError(f"1-host load failed: {r1['error']}")
            router.add_host(h1["url"])
            time.sleep(1.0)   # let host1's first pushes land
            r2 = run_load(router.port, x, reference, args.clients,
                          args.requests)
            if "error" in r2:
                raise RuntimeError(f"2-host load failed: {r2['error']}")
            report["scaling"] = {"hosts1": r1, "hosts2": r2}

            code, hz = _get(router.url, "/healthz")
            report["healthz_both_live"] = {"code": code,
                                           "status": hz["status"]}

            print("== decode failover: SIGKILL mid-generation ==",
                  file=sys.stderr)
            report["decode_failover"] = decode_failover_arm(
                router, hosts, n_sessions=args.sessions,
                n_tokens=args.gen_tokens)

            code, hz = _get(router.url, "/healthz")
            report["healthz_after_kill"] = {"code": code,
                                            "status": hz["status"]}
            report["router"] = router.describe()
            report["routing_table"] = router.route_table()
    finally:
        for h in hosts:
            try:
                kill_host(h)
            except Exception:
                pass
        router.stop()

    fo = report["decode_failover"]
    # gated scalars, top-level so check_budgets' generic resolver sees
    # them (BUDGETS.json "cross_host_serving" section)
    report.update({
        "host_scaling_ratio": round(
            report["scaling"]["hosts2"]["rows_per_sec"]
            / report["scaling"]["hosts1"]["rows_per_sec"], 3),
        "second_host_fresh_compiles":
            report["hosts"]["host1"]["fresh_compiles"],
        "second_host_cache_misses":
            report["hosts"]["host1"]["cache_misses"],
        "first_host_fresh_compiles":
            report["hosts"]["host0"]["fresh_compiles"],
        "session_affinity_hit_rate": fo["session_affinity_hit_rate"],
        "failover_bit_identical": fo["failover_bit_identical"],
        "failover_recoveries": fo["failover_recoveries"],
        "predict_bit_identical":
            int(report["scaling"]["hosts1"]["bit_identical"]
                and report["scaling"]["hosts2"]["bit_identical"]),
        "healthz_degraded_after_kill":
            int(report["healthz_after_kill"]["status"] == "degraded"),
    })

    print(json.dumps(report, indent=1))
    if args.out:
        out = os.path.abspath(args.out)
        atomic_publish(os.path.dirname(out), os.path.basename(out),
                       report)
        print(f"wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
