"""Closed-loop serving load generator: before/after for the
continuous-batching inference runtime.

Measures end-to-end HTTP rows/sec and latency percentiles for the MNIST
MLP at client concurrency 1 / 8 / 64, against BOTH server designs:

- ``serialized`` — the seed design, reimplemented inline as the
  baseline: one forward per HTTP request under a global lock (the
  accelerator idles between per-request dispatches).
- ``coalesced``  — the continuous micro-batching ModelServer
  (serving/batcher.py): handler threads enqueue, one device thread
  coalesces pending requests into padded power-of-two bucket forwards.

Every client is closed-loop (fires its next request only after the
previous reply) over a persistent HTTP/1.1 connection, and every reply
is checked BIT-IDENTICAL against the sequential ``net.output()``
reference rows — a speedup that changed the numbers would not count.

Run: ``python scripts/serve_bench.py`` (CPU is fine; add ``--quick``
for the fast variant bench.py embeds in its ``extra`` dict).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------- baseline
class SerializedServer:
    """The seed lock-serialized server, kept verbatim as the bench
    baseline: pad each request to its own power-of-two bucket, run ONE
    forward per request under a global lock."""

    def __init__(self, net, max_batch: int = 1024):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from deeplearning4j_tpu.serving.batcher import next_bucket

        self.net = net
        self.max_batch = max_batch
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *args):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n).decode())
                x = np.asarray(payload["features"], np.float32)
                rows = x.shape[0]
                # same min-bucket floor as the coalescing server so both
                # designs produce identical rows and the comparison
                # isolates the dispatch architecture, not the gemv/gemm
                # code-path split
                bucket = next_bucket(rows, outer.max_batch, 2)
                if bucket != rows:
                    x = np.pad(x, [(0, bucket - rows), (0, 0)])
                with outer._lock:
                    out = np.asarray(outer.net.output(x))[:rows]
                body = json.dumps({"predictions": out.tolist()}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Server(ThreadingHTTPServer):
            request_queue_size = 128  # survive a 64-client connect burst

        self._httpd = Server(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


# ------------------------------------------------------------ load client
def run_load(port: int, x: np.ndarray, reference: np.ndarray,
             concurrency: int, requests_per_client: int,
             capture_trace: bool = False) -> dict:
    """``concurrency`` closed-loop clients, each firing
    ``requests_per_client`` single-row /predict posts over one
    persistent connection. Returns rows/sec + latency percentiles and a
    row-exactness verdict. ``capture_trace`` also records each request's
    arrival offset (seconds since the start gate) so the run can be
    replayed offline by the schedule autotuner
    (compilecache.autotune)."""
    from deeplearning4j_tpu.observability.distributed import (TRACE_HEADER,
                                                              new_trace_id)
    lats: list[float] = []
    lock = threading.Lock()
    errors: list[str] = []
    mismatches = [0]
    # trace-context propagation receipts: ids sent, ids echoed back
    trace_ids = {"sent": 0, "echoed": 0}
    arrivals: list = []   # (perf_counter at send, rows) when capturing
    start_gate = threading.Event()

    def client(tid: int):
        import socket

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        my_lats = []
        my_arr = []
        my_sent = my_echoed = 0
        try:
            conn.connect()
            # Nagle off: header and body go out as separate sends, and
            # Nagle + delayed ACK turns that into a 40 ms stall per post
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            start_gate.wait()
            for r in range(requests_per_client):
                i = (tid * requests_per_client + r) % x.shape[0]
                body = json.dumps({"features": x[i:i + 1].tolist()})
                # every request carries its own trace id; a conforming
                # server echoes it and stamps it onto its batcher spans
                trace_id = new_trace_id()
                my_sent += 1
                t0 = time.perf_counter()
                if capture_trace:
                    my_arr.append((t0, 1))
                conn.request("POST", "/predict", body,
                             {"Content-Type": "application/json",
                              TRACE_HEADER: trace_id})
                resp = conn.getresponse()
                data = resp.read()
                my_lats.append(time.perf_counter() - t0)
                if resp.getheader(TRACE_HEADER) == trace_id:
                    my_echoed += 1
                if resp.status != 200:
                    with lock:
                        errors.append(f"HTTP {resp.status}: {data[:120]!r}")
                    return
                got = np.asarray(json.loads(data)["predictions"])
                if not np.array_equal(got[0], reference[i]):
                    with lock:
                        mismatches[0] += 1
        except Exception as e:
            with lock:
                errors.append(f"{type(e).__name__}: {e}")
        finally:
            conn.close()
            with lock:
                lats.extend(my_lats)
                arrivals.extend(my_arr)
                trace_ids["sent"] += my_sent
                trace_ids["echoed"] += my_echoed

    threads = [threading.Thread(target=client, args=(t,), daemon=True)
               for t in range(concurrency)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join(timeout=600.0)
    wall = time.perf_counter() - t0
    if errors:
        return {"error": errors[0], "concurrency": concurrency}
    total = concurrency * requests_per_client
    s = sorted(lats)

    def pct(q):
        return round(1000.0 * s[min(len(s) - 1, int(round(q * (len(s) - 1))))],
                     3)

    if capture_trace:
        trace = {"concurrency": concurrency,
                 "arrivals": sorted(
                     [round(t - t0, 6), r] for t, r in arrivals)}
    return {
        **({"trace": trace} if capture_trace else {}),
        "concurrency": concurrency,
        "requests": total,
        "rows_per_sec": round(total / wall, 1),
        "wall_s": round(wall, 3),
        "p50_ms": pct(0.50), "p95_ms": pct(0.95), "p99_ms": pct(0.99),
        "bit_identical": mismatches[0] == 0,
        "mismatched_rows": mismatches[0],
        # echo rate is 1.0 against ModelServer; the serialized baseline
        # predates trace propagation and reports 0.0 honestly
        "trace_ids_sent": trace_ids["sent"],
        "trace_id_echo_rate": round(
            trace_ids["echoed"] / trace_ids["sent"], 4)
        if trace_ids["sent"] else None,
    }


# ---------------------------------------------------------------- harness
def _serving_mlp(hidden: int = 4096, depth: int = 3):
    """The bench model: a 64-in MLP with ``depth`` x ``hidden`` layers
    (~34M params at the default). Small input dim keeps the JSON wire
    cost off the measurement; the wide hidden stack makes every forward
    weight-streaming-bound, so a single-row forward costs nearly as much
    as a full bucket — exactly the regime where per-request dispatch
    wastes the device and cross-request coalescing multiplies
    throughput (the accelerator-serving shape of the problem, on CPU)."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    b = (NeuralNetConfiguration.builder().seed(1).list()
         .layer(Dense(n_in=64, n_out=hidden, activation="relu")))
    for _ in range(depth - 1):
        b = b.layer(Dense(n_in=hidden, n_out=hidden, activation="relu"))
    b = b.layer(Output(n_in=hidden, n_out=10, activation="softmax",
                       loss="mcxent"))
    return MultiLayerNetwork(b.build()).init()


def bench_serving(concurrencies=(1, 8, 64), requests_per_client=25,
                  max_batch: int = 64, batch_window_ms: float = 2.0,
                  hidden: int = 4096, depth: int = 3) -> dict:
    """Run the serialized baseline and the coalescing server over the
    same traffic; returns the full before/after report (the dict
    bench.py embeds under ``extra["serving"]``)."""
    from deeplearning4j_tpu.serving import serve

    net = _serving_mlp(hidden, depth)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 64)).astype(np.float32)
    reference = np.asarray(net.output(x))  # sequential reference rows

    report: dict = {"model": f"serving_mlp 64-{hidden}x{depth}-10 f32 "
                             f"({int(net.num_params()) / 1e6:.1f}M params)",
                    "max_batch": max_batch,
                    "batch_window_ms": batch_window_ms,
                    "platform": _platform(),
                    "serialized": {}, "coalesced": {}}

    base = SerializedServer(net, max_batch=max_batch)
    try:
        for c in concurrencies:
            report["serialized"][f"c{c}"] = run_load(
                base.port, x, reference, c, requests_per_client)
    finally:
        base.stop()

    server = serve(net, port=0, max_batch=max_batch,
                   batch_window_ms=batch_window_ms)
    try:
        for c in concurrencies:
            # capture the arrival trace once, at the highest-concurrency
            # coalesced run (the traffic shape worth autotuning for);
            # run_load returns it inline and it moves to report["trace"]
            res = run_load(server.port, x, reference, c,
                           requests_per_client,
                           capture_trace=(c == max(concurrencies)))
            if "trace" in res:
                report["trace"] = res.pop("trace")
            report["coalesced"][f"c{c}"] = res
        report["metrics"] = server.metrics()
    finally:
        server.stop()
    if server.run_report is not None:
        # the serving goodput ledger closed on drain: device-time share
        # and the bucket ladder's padding waste ride the results file
        report["run_report"] = server.run_report.to_dict()
        # SLO attainment over the bench's own load — the engine's
        # sliding windows closed with the drain, so the --out receipt
        # carries attainment / burn-rate / budget-remaining per SLO
        if report["run_report"].get("slo"):
            report["slo"] = report["run_report"]["slo"]

    for c in concurrencies:
        a = report["serialized"][f"c{c}"].get("rows_per_sec")
        b = report["coalesced"][f"c{c}"].get("rows_per_sec")
        if a and b:
            report[f"speedup_c{c}"] = round(b / a, 2)

    # headline rollup for downstream consumers (perf_probe, budgets):
    # worst-case p99 + best rows/sec across the coalesced runs, plus the
    # batcher's coalesce ratio and padding-waste fraction
    coal = [v for v in report["coalesced"].values() if "p99_ms" in v]
    if coal:
        rr = report.get("run_report") or {}
        report["summary"] = {
            "p50_ms": min(v["p50_ms"] for v in coal),
            "p99_ms": max(v["p99_ms"] for v in coal),
            "rows_per_sec": max(v["rows_per_sec"] for v in coal),
            "coalesce_rows_per_batch":
                report["metrics"].get("coalesce_rows_per_batch"),
            "padding_waste_fraction":
                report["metrics"].get("padding_waste_fraction"),
            "bit_identical": all(v.get("bit_identical") for v in coal),
            # cold-start numbers from the server's own goodput report:
            # process start -> first successful reply, and the warm-up
            # ladder's wall time (check_budgets gates these)
            "cold_start_s": rr.get("cold_start_s"),
            "warmup_s": rr.get("warmup_s"),
            # headline SLO: availability attainment over the bench load
            "slo_availability": (((report.get("slo") or {}).get("slos")
                                  or {}).get("availability")
                                 or {}).get("attainment"),
        }
    return report


def _platform() -> str:
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return "unknown"


# ------------------------------------------------------------ decode bench
def bench_decode(sessions: int = 12, gen_tokens: int = 24,
                 replicas: int = 2, n_pages: int = 40,
                 page_tokens: int = 16, max_batch: int = 16,
                 batch_window_ms: float = 2.0, vocab: int = 32,
                 width: int = 64, n_layers: int = 2, n_heads: int = 4,
                 max_cache_len: int = 128, shared_prefix: int = 32,
                 stagger_s: float = 0.04, net=None,
                 speculative_k: int = 0, draft_net=None) -> dict:
    """Mixed prefill/decode open-arrival load (config ``transformer``,
    the TRANSFORMER_r02 arm): ``sessions`` greedy-decode clients arrive
    STAGGERED (``stagger_s`` apart, open arrival — not a closed-loop
    start gate), so long prompt prefills land while earlier sessions are
    mid-decode: exactly the head-of-line collision chunked prefill
    exists to break. Prompt lengths are heavy-tailed (most short, every
    fourth group 48-64 suffix tokens), every prompt opens with the same
    ``shared_prefix``-token system prompt, and sessions arrive in small
    groups asking the SAME prompt (the millions-of-users shape) — the
    traffic prefix sharing deduplicates.

    Every session's generated token stream is checked against a
    sequential ``rnn_time_step`` reference computed beforehand, and one
    session's logits are checked bit-for-bit — so the published
    inter-token p99 and dedup ratio are for decoding that provably
    chunks, shares, and coalesces without changing a single output (the
    fixed-extent-cache contract, ops/attention.py). The receipt also
    carries the post-warm compile delta: the chunk ladder must add no
    fresh compiles during the timed run.

    ``net=`` substitutes a prebuilt (possibly trained) target;
    ``speculative_k``/``draft_net`` turn on speculative decoding — the
    references stay sequential ``rnn_time_step``, so the bit-identity
    check then covers chunking + sharing + speculation stacked."""
    from deeplearning4j_tpu.observability.metrics import (compile_delta,
                                                          compile_snapshot)
    from deeplearning4j_tpu.serving.decode import DecodeEngine
    from deeplearning4j_tpu.zoo import F32, gpt_mini

    if net is None:
        net = gpt_mini(vocab_size=vocab, width=width, n_layers=n_layers,
                       n_heads=n_heads, max_len=max_cache_len,
                       max_cache_len=max_cache_len, dtype=F32)
    rng = np.random.default_rng(0)
    # shared system prompt + per-group suffix; 3-ish sessions per group
    prefix = [int(t) for t in rng.integers(0, vocab, shared_prefix)]
    n_groups = max(2, sessions // 3)
    suffix_lens = [int(rng.integers(4, 16)) for _ in range(n_groups)]
    for g in range(0, n_groups, 3):
        suffix_lens[g] = int(rng.integers(48, 65))   # the heavy tail
    group_prompts = [
        prefix + [int(t) for t in rng.integers(0, vocab, n)]
        for n in suffix_lens]
    # arrival order starts on a SHORT group so the heavy-tail prompts
    # land while earlier sessions are mid-decode — the head-of-line
    # collision this arm exists to measure
    gid = [(i + 1) % n_groups for i in range(sessions)]
    prompts = [group_prompts[g] for g in gid]

    def oh(ids):
        xx = np.zeros((1, len(ids), vocab), np.float32)
        xx[0, np.arange(len(ids)), ids] = 1.0
        return xx

    def ref_generate(ids):
        net.rnn_clear_previous_state()
        o = np.asarray(net.rnn_time_step(oh(ids)))[0, -1]
        seq = []
        for _ in range(gen_tokens):
            nxt = int(np.argmax(o))
            seq.append(nxt)
            o = np.asarray(net.rnn_time_step(oh([nxt])))[0, 0]
        return seq

    group_refs = [ref_generate(ids) for ids in group_prompts]
    refs = [group_refs[g] for g in gid]

    eng = DecodeEngine(net, replicas=replicas, n_pages=n_pages,
                       page_tokens=page_tokens, max_batch=max_batch,
                       batch_window_ms=batch_window_ms,
                       speculative=int(speculative_k),
                       draft_net=draft_net)
    t0 = time.perf_counter()
    eng.warm()
    warmup_s = time.perf_counter() - t0

    # logit-level exactness spot check (token equality below could in
    # principle survive a small numeric drift; this cannot)
    net.rnn_clear_previous_state()
    ref_l = np.asarray(net.rnn_time_step(oh(prompts[0])))[0, -1]
    logits_exact = bool(np.array_equal(ref_l, eng.prefill("check",
                                                          prompts[0])))
    tok = int(np.argmax(ref_l))
    ref_l2 = np.asarray(net.rnn_time_step(oh([tok])))[0, 0]
    logits_exact &= bool(np.array_equal(ref_l2, eng.step("check", tok)))
    eng.close_session("check")
    net.rnn_clear_previous_state()
    snap = compile_snapshot()
    pre = eng.describe()   # so the spot check doesn't pollute run counters

    results: list = [None] * sessions
    step_times: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()
    gate = threading.Event()

    def worker(i: int):
        ts: list[float] = []
        try:
            gate.wait()
            time.sleep(stagger_s * i)   # open arrival: staggered starts
            out = eng.generate(f"s{i}", prompts[i], gen_tokens,
                               step_times=ts)
            with lock:
                results[i] = out
        except Exception as e:
            with lock:
                errors.append(f"{type(e).__name__}: {e}")
        finally:
            with lock:
                step_times.extend(ts)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(sessions)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    gate.set()
    for t in threads:
        t.join(timeout=600.0)
    wall = time.perf_counter() - t0
    desc = eng.describe()
    cdelta = compile_delta(snap)
    eng.stop()
    if errors:
        return {"config": "transformer", "error": errors[0]}

    matched = sum(1 for i in range(sessions) if results[i] == refs[i])
    s = sorted(step_times)

    def pct(q):
        return round(
            1000.0 * s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)

    hits, misses = desc["affinity_hits"], desc["affinity_misses"]
    spec: dict = {}
    if speculative_k:
        # run-delta accepted-tokens-per-step: tokens emitted per target
        # decode launch (plain steps emit 1; a verify round emits
        # 1 + its accepts) — the speculative speedup lever the budget
        # gates at > 1.0
        steps_run = ((desc["decode_steps"] - pre["decode_steps"])
                     + (desc["spec_rounds"] - pre["spec_rounds"]))
        acc_run = desc["spec_accepted"] - pre["spec_accepted"]
        spec = {
            "speculative_k": speculative_k,
            "spec_rounds": desc["spec_rounds"] - pre["spec_rounds"],
            "spec_proposed": desc["spec_proposed"] - pre["spec_proposed"],
            "spec_accepted": acc_run,
            "spec_rejected": desc["spec_rejected"] - pre["spec_rejected"],
            "spec_accept_tokens_per_step":
                round((steps_run + acc_run) / steps_run, 4)
                if steps_run else None,
            "spec_draft_truncations": desc.get("spec_draft_truncations"),
        }
    return {
        "config": "transformer",
        "model": f"gpt_mini vocab{vocab} w{width} L{n_layers} "
                 f"h{n_heads} f32 (cache {max_cache_len})",
        "platform": _platform(),
        "sessions": sessions, "gen_tokens": gen_tokens,
        "replicas": replicas,
        "prompt_lens": sorted(len(p) for p in prompts),
        "prompt_groups": n_groups,
        "shared_prefix_tokens": shared_prefix,
        "arrival_stagger_s": stagger_s,
        "warmup_s": round(warmup_s, 3),
        "wall_s": round(wall, 3),
        "decode_tokens_per_sec": round(sessions * gen_tokens / wall, 1),
        "inter_token_p50_ms": pct(0.50),
        "inter_token_p99_ms": pct(0.99),
        "decode_bit_identical":
            1 if (matched == sessions and logits_exact) else 0,
        "sessions_matched": matched,
        "logits_exact": logits_exact,
        "kv_pool_occupancy": round(desc["occupancy"], 4),
        "kv_pool_pages": desc["n_pages"],
        "kv_page_tokens": desc["page_tokens"],
        "kv_evictions": desc["evictions"],
        "reprefills": desc["reprefills"],
        "decode_steps": desc["decode_steps"],
        # -- chunked prefill + prefix sharing (the r02 arm's raison d'etre);
        #    counters are run-deltas so the warm-up spot check stays out
        "prefill_chunk_tokens": desc["prefill_chunk_tokens"],
        "prefill_chunks": desc["prefill_chunks"] - pre["prefill_chunks"],
        "chunked_prefills":
            desc["chunked_prefills"] - pre["chunked_prefills"],
        "interleaved_prefills":
            desc["interleaved_prefills"] - pre["interleaved_prefills"],
        "chunk_interleave_ratio":
            round((desc["interleaved_prefills"]
                   - pre["interleaved_prefills"])
                  / (desc["chunked_prefills"] - pre["chunked_prefills"]), 4)
            if desc["chunked_prefills"] > pre["chunked_prefills"] else None,
        "prefix_hits": desc["prefix_hits"] - pre["prefix_hits"],
        "shared_prompt_tokens":
            desc["shared_tokens"] - pre["shared_tokens"],
        "kv_shared_pages": desc["shared_pages"],
        "kv_store_pages": desc["store_pages"],
        "kv_logical_pages": desc["logical_pages"],
        "pool_dedup_ratio": desc["dedup_ratio"],
        "compile_delta_after_warm": cdelta["count"],
        "affinity_hit_rate": round(hits / (hits + misses), 4)
        if hits + misses else None,
        **spec,
    }


def _fit_copy_lm(net, vocab: int = 32, steps: int = 80, batch: int = 8,
                 seq: int = 32, max_run: int = 5, seed: int = 0) -> int:
    """Briefly fit ``net`` on a run-structured copy task: sequences are
    short constant runs, so "next token = current token" is usually
    right. Fitting BOTH the target and the draft on this makes their
    greedy continuations genuinely correlate — the speculative bench's
    acceptance rate is then measured, not assumed (random-weight models
    would agree only by 1/vocab chance)."""
    from deeplearning4j_tpu.datasets import DataSet
    rng = np.random.default_rng(seed)
    rows = np.arange(seq)
    for _ in range(steps):
        toks = np.empty((batch, seq), np.int64)
        for b in range(batch):
            pos = 0
            while pos < seq:
                t = int(rng.integers(0, vocab))
                end = min(seq, pos + int(rng.integers(2, max_run + 1)))
                toks[b, pos:end] = t
                pos = end
        x = np.zeros((batch, seq, vocab), np.float32)
        y = np.zeros((batch, seq, vocab), np.float32)
        for b in range(batch):
            x[b, rows, toks[b]] = 1.0
            y[b, rows, np.concatenate([toks[b, 1:], toks[b, :1]])] = 1.0
        net.fit_batch(DataSet(x, y))
    return steps


def bench_decode_speculative(sessions: int = 12, gen_tokens: int = 24,
                             spec_k: int = 3, fit_steps: int = 80,
                             **kw) -> dict:
    """The TRANSFORMER_r03 arm: the r02 mixed open-arrival decode load
    with chunked prefill + COW prefix sharing + SPECULATIVE DECODING all
    on. Builds a copy-task-trained gpt_mini target and gpt_mini_draft
    draft (same vocab, half width, one layer), runs the r02 load once
    with speculation OFF and once with it ON (same trained nets, same
    prompts), and publishes the comparison: accepted-tokens-per-step,
    tokens/sec vs the off arm, and the bit-identity verdict for the
    fully stacked path (check_budgets gates
    ``min_spec_accept_tokens_per_step`` and ``min_spec_bit_identical``
    on this receipt)."""
    from deeplearning4j_tpu.zoo import F32, gpt_mini, gpt_mini_draft

    vocab, cache = 32, 128
    target = gpt_mini(vocab_size=vocab, width=64, n_layers=2, n_heads=4,
                      max_len=cache, max_cache_len=cache, dtype=F32)
    draft = gpt_mini_draft(vocab_size=vocab, width=32, n_layers=1,
                           n_heads=2, max_len=cache, max_cache_len=cache,
                           dtype=F32)
    _fit_copy_lm(target, vocab=vocab, steps=fit_steps)
    _fit_copy_lm(draft, vocab=vocab, steps=fit_steps)

    off = bench_decode(sessions=sessions, gen_tokens=gen_tokens,
                       net=target, **kw)
    if "error" in off:
        return off
    on = bench_decode(sessions=sessions, gen_tokens=gen_tokens,
                      net=target, speculative_k=spec_k, draft_net=draft,
                      **kw)
    if "error" in on:
        return on
    on["model"] += " [copy-task-trained]"
    on["draft_model"] = (f"gpt_mini_draft vocab{vocab} w32 L1 h2 f32 "
                         f"(cache {cache})")
    on["copy_fit_steps"] = fit_steps
    on["spec_off_tokens_per_sec"] = off["decode_tokens_per_sec"]
    on["spec_speedup_vs_off"] = (
        round(on["decode_tokens_per_sec"] / off["decode_tokens_per_sec"], 4)
        if off["decode_tokens_per_sec"] else None)
    # bit-identity for the fully stacked path (chunking + sharing +
    # speculation): same check as r02's, named so the budget gate can
    # pin it independently
    on["spec_bit_identical"] = on["decode_bit_identical"]
    return on


# ------------------------------------------------------------- fleet bench
def run_load_inproc(server, x: np.ndarray, reference: np.ndarray,
                    clients: int, requests_per_client: int,
                    rows_per_request: int = 4) -> dict:
    """Closed-loop clients over ``server.predict`` directly (no HTTP).
    The replica-scaling question is about the DISPATCH tier — admission,
    routing, N device threads — and this container has one CPU core, so
    per-request HTTP/JSON handling would be pure serial overhead that
    caps any measured scaling long before the replica tier does. Every
    reply is still checked bit-identical against the reference rows."""
    lats: list[float] = []
    lock = threading.Lock()
    errors: list[str] = []
    mismatches = [0]
    start_gate = threading.Event()
    k = rows_per_request

    def client(tid: int):
        my_lats = []
        try:
            start_gate.wait()
            for r in range(requests_per_client):
                i = ((tid * requests_per_client + r) * k) % (x.shape[0] - k)
                t0 = time.perf_counter()
                got = np.asarray(server.predict(x[i:i + k]))
                my_lats.append(time.perf_counter() - t0)
                if not np.array_equal(got, reference[i:i + k]):
                    with lock:
                        mismatches[0] += 1
        except Exception as e:
            with lock:
                errors.append(f"{type(e).__name__}: {e}")
        finally:
            with lock:
                lats.extend(my_lats)

    threads = [threading.Thread(target=client, args=(t,), daemon=True)
               for t in range(clients)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join(timeout=600.0)
    wall = time.perf_counter() - t0
    if errors:
        return {"error": errors[0], "clients": clients}
    total = clients * requests_per_client
    s = sorted(lats)

    def pct(q):
        return round(1000.0 * s[min(len(s) - 1, int(round(q * (len(s) - 1))))],
                     3)

    return {
        "clients": clients,
        "requests": total,
        "rows_per_request": k,
        "rows_per_sec": round(total * k / wall, 1),
        "wall_s": round(wall, 3),
        "p50_ms": pct(0.50), "p95_ms": pct(0.95), "p99_ms": pct(0.99),
        "bit_identical": mismatches[0] == 0,
        "mismatched_requests": mismatches[0],
    }


def bench_fleet(replicas=(1, 2, 4), device_sim_ms: float = 20.0,
                clients: int = 128, requests_per_client: int = 8,
                max_batch: int = 8, hidden: int = 64) -> dict:
    """Rows/sec vs replica count on SIMULATED devices. Each replica's
    forward runs the real (tiny) model for row correctness, then sleeps
    ``device_sim_ms`` with the GIL released — the sleep stands in for an
    accelerator executing the bucket, so N device threads model N
    accelerators draining in parallel even on this 1-core host. The
    published scaling number measures the dispatch tier (global
    admission + queue-depth routing + N device threads), which is
    exactly the subsystem this sweep exists to gate."""
    from deeplearning4j_tpu.serving.server import ModelServer

    net = _serving_mlp(hidden=hidden, depth=2)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 64)).astype(np.float32)
    reference = np.asarray(net.output(x))

    report: dict = {"device_sim_ms": device_sim_ms, "max_batch": max_batch,
                    "clients": clients,
                    "transport": "in-process closed-loop predict() "
                                 "(see run_load_inproc)",
                    "replica_sweep": {}}
    for r in replicas:
        server = ModelServer(net, port=0, max_batch=max_batch,
                             batch_window_ms=1.0, max_queue=4096,
                             replicas=r)
        real = server._device_forward

        def simulated(feats, _real=real):
            out = _real(feats)
            np.asarray(out)             # block until real compute lands
            time.sleep(device_sim_ms / 1000.0)  # the simulated device
            return out

        for rep in server.fleet.replicas:
            rep.batcher._forward = simulated
        server._fleet.warm([(64,)])
        try:
            res = run_load_inproc(server, x, reference, clients,
                                  requests_per_client)
            res["requeued"] = server.fleet.requeued
            report["replica_sweep"][f"r{r}"] = res
        finally:
            server.stop()
    r1 = report["replica_sweep"].get("r1", {}).get("rows_per_sec")
    r4 = report["replica_sweep"].get("r4", {}).get("rows_per_sec")
    if r1 and r4:
        report["replica_scaling"] = round(r4 / r1, 2)
    return report


def bench_mesh(hidden: int = 128, depth: int = 3, concurrency: int = 16,
               requests_per_client: int = 10, max_batch: int = 32) -> dict:
    """Tensor-parallel f32 serving over HTTP against the 8-device mesh:
    every reply row must be bit-identical to the single-device
    ``net.output()`` reference computed BEFORE the params were sharded.
    ``hidden`` stays under 256 so XLA:CPU blocks the local gemm's K loop
    identically at sharded and full width (SERVING.md "Fleet" — on TPU
    the MXU K loop is width-independent)."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.serving import serve

    n_dev = len(jax.devices())
    net = _serving_mlp(hidden=hidden, depth=depth)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 64)).astype(np.float32)
    reference = np.asarray(net.output(x))   # pre-shard, single-device

    mesh = make_mesh({"model": n_dev})
    server = serve(net, port=0, max_batch=max_batch, batch_window_ms=1.0,
                   mesh=mesh)
    try:
        res = run_load(server.port, x, reference, concurrency,
                       requests_per_client)
    finally:
        server.stop()
    res.update({"mesh_axes": f"model:{n_dev}",
                "model": f"serving_mlp 64-{hidden}x{depth}-10 f32"})
    return res


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=25,
                    help="requests per client (per concurrency level)")
    ap.add_argument("--concurrency", type=int, nargs="+",
                    default=[1, 8, 64])
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--batch-window-ms", type=float, default=2.0)
    ap.add_argument("--hidden", type=int, default=4096)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--quick", action="store_true",
                    help="small fast run (bench.py integration)")
    ap.add_argument("--fleet", action="store_true",
                    help="replica-tier scaling sweep on simulated devices"
                         " + mesh bit-identity check (config "
                         "serving_fleet, gated by check_budgets)")
    ap.add_argument("--mesh", action="store_true",
                    help="only the tensor-parallel bit-identity serve")
    ap.add_argument("--decode", action="store_true",
                    help="mixed prefill/decode open-arrival load over the "
                         "DecodeEngine fleet: heavy-tailed prompts, shared "
                         "system prefix, chunked prefill + COW prefix "
                         "sharing on (config transformer; the "
                         "TRANSFORMER_r02.json receipt, gated by "
                         "check_budgets)")
    ap.add_argument("--speculative", action="store_true",
                    help="with --decode: the TRANSFORMER_r03 arm — "
                         "copy-task-trained target + gpt_mini_draft, "
                         "speculation off then on over the same r02 "
                         "load, accepted-tokens/step and tokens/sec "
                         "comparison (gated by check_budgets)")
    ap.add_argument("--spec-k", type=int, default=3,
                    help="draft tokens proposed per speculative round "
                         "(--decode --speculative)")
    ap.add_argument("--sessions", type=int, default=12,
                    help="concurrent decode sessions (--decode)")
    ap.add_argument("--gen-tokens", type=int, default=24,
                    help="greedy tokens generated per session (--decode)")
    ap.add_argument("--no-train", action="store_true",
                    help="skip the gpt_mini training-MFU entry in the "
                         "--decode report")
    ap.add_argument("--replicas", type=int, nargs="+", default=[1, 2, 4],
                    help="fleet sweep replica counts")
    ap.add_argument("--device-sim-ms", type=float, default=20.0,
                    help="simulated per-bucket device time (fleet sweep)")
    ap.add_argument("--clients", type=int, default=128,
                    help="closed-loop clients in the fleet sweep (on a "
                         "1-core host more threads just add GIL churn; "
                         "raise this on real machines)")
    ap.add_argument("--out", metavar="OUT.json", default=None,
                    help="also write the report to this file "
                         "(consumed by scripts/perf_probe.py --serving-results"
                         " and scripts/check_budgets.py)")
    args = ap.parse_args()
    if args.quick:
        args.concurrency, args.requests = [16], 10
    if args.decode:
        if args.speculative:
            report = bench_decode_speculative(sessions=args.sessions,
                                              gen_tokens=args.gen_tokens,
                                              spec_k=args.spec_k)
        else:
            report = bench_decode(sessions=args.sessions,
                                  gen_tokens=args.gen_tokens)
        if not args.no_train and "error" not in report:
            # the training side of the workload: gpt_mini fit step with
            # the XLA-cost-model FLOPs ledger (bench.py `transformer`) —
            # train_mfu is hoisted flat so the budget gate sees it
            import importlib.util
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "bench.py")
            spec = importlib.util.spec_from_file_location("bench", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            train = mod.run_config("transformer")
            report["train"] = train
            if train.get("mfu") is not None:
                report["train_mfu"] = train["mfu"]
            if train.get("tokens_per_sec") is not None:
                report["train_tokens_per_sec"] = train["tokens_per_sec"]
    elif args.fleet or args.mesh:
        # BEFORE any deeplearning4j_tpu/jax import: the fleet story is
        # "8 simulated devices" — force the host platform to expose them
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        report = {"config": "serving_fleet", "platform": _platform()}
        if args.fleet:
            report.update(bench_fleet(tuple(args.replicas),
                                      args.device_sim_ms, args.clients,
                                      max_batch=args.max_batch
                                      if args.max_batch != 64 else 8))
        report["mesh"] = bench_mesh()
    else:
        report = bench_serving(tuple(args.concurrency), args.requests,
                               args.max_batch, args.batch_window_ms,
                               args.hidden, args.depth)
    print(json.dumps(report, indent=2))
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        os.replace(tmp, args.out)


if __name__ == "__main__":
    main()
