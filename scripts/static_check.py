"""CI correctness-analysis gate: run the analysis/ passes and diff the
findings against the committed ANALYSIS_BASELINE.json.

Two passes run (ANALYSIS.md has the full finding-code table):

- the **concurrency lint** (analysis/concurrency.py): a millisecond AST
  sweep over deeplearning4j_tpu/, scripts/ and bench.py;
- the **jaxpr hazard lint** (analysis/jaxpr_lint.py): traces the jitted
  fit steps and serving forwards of the real models (host-only —
  ``make_jaxpr``/``lower``, no compile, no device execution) and walks
  the IR for dtype leaks, retrace bombs, donation misses and
  off-allowlist primitives.

The gate is a ratchet, same spirit as check_budgets.py:

- a finding NOT in the baseline (or exceeding its baselined count)
  **fails** — new hazards don't land;
- a baselined finding that no longer occurs also **fails** ("stale
  baseline") until the baseline is shrunk with ``--update-baseline`` —
  fixed hazards can't silently come back.

Baseline entries key on ``code|path|symbol|message`` (no line numbers),
so unrelated edits that shift code around don't churn the file. The
shipped baseline is empty: every initial finding was burned down in the
PR that introduced this gate.

Usage:
    python scripts/static_check.py                  # the CI gate
    python scripts/static_check.py --json out.json  # findings as JSON
    python scripts/static_check.py --update-baseline
    python scripts/static_check.py --skip-jaxpr     # AST passes only

Exit status 0 = findings match the baseline, 1 = new or stale findings
(each printed on its own line), 2 = usage / unreadable baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(_REPO, "ANALYSIS_BASELINE.json")

sys.path.insert(0, _REPO)


def collect_findings(skip_jaxpr: bool = False):
    from deeplearning4j_tpu.analysis import concurrency, sort_findings

    findings = concurrency.lint_tree(_REPO)
    if not skip_jaxpr:
        import jax
        # match the pytest environment (tests/conftest.py) so both entry
        # points trace identical programs and agree on the baseline
        jax.config.update("jax_enable_x64", True)
        from deeplearning4j_tpu.analysis import jaxpr_lint
        findings.extend(jaxpr_lint.lint_all())
    return sort_findings(findings)


def _counts(findings) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint()
        out[fp] = out.get(fp, 0) + 1
    return out


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {k: int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(path: str, counts: Dict[str, int]) -> None:
    data = {
        "_comment": "Committed findings the static_check gate tolerates "
                    "(fingerprint -> count). New findings fail; fixed "
                    "findings must be removed here (--update-baseline) "
                    "so they cannot return. See ANALYSIS.md.",
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def gate(findings, baseline: Dict[str, int]) -> List[str]:
    """-> violation lines (empty == gate passes)."""
    found = _counts(findings)
    by_fp = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint(), f)
    problems = []
    for fp, n in sorted(found.items()):
        base = baseline.get(fp, 0)
        if n > base:
            problems.append(f"NEW ({n} > baseline {base}): {by_fp[fp]}")
    for fp, base in sorted(baseline.items()):
        n = found.get(fp, 0)
        if n < base:
            problems.append(
                f"STALE baseline entry ({n} < baseline {base}) — fixed? "
                f"shrink it with --update-baseline: {fp}")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default ANALYSIS_BASELINE.json)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the findings as JSON to PATH "
                         "('-' for stdout)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings")
    ap.add_argument("--skip-jaxpr", action="store_true",
                    help="run only the AST passes (no model tracing)")
    args = ap.parse_args(argv)

    try:
        baseline = load_baseline(args.baseline)
    except (OSError, ValueError) as e:
        print(f"unreadable baseline {args.baseline}: {e}", file=sys.stderr)
        return 2

    findings = collect_findings(skip_jaxpr=args.skip_jaxpr)

    if args.json:
        payload = json.dumps([f.to_dict() for f in findings], indent=2)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(payload + "\n")

    if args.update_baseline:
        write_baseline(args.baseline, _counts(findings))
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    problems = gate(findings, baseline)
    if problems:
        for line in problems:
            print(line)
        print(f"static_check: {len(problems)} problem(s) "
              f"({len(findings)} finding(s) vs baseline "
              f"{os.path.basename(args.baseline)})")
        return 1
    print(f"static_check: OK ({len(findings)} finding(s), all baselined; "
          f"baseline entries: {len(baseline)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
