"""Chaos training demo: the fault-tolerant runtime survives a hostile
schedule of injected failures and still lands on BIT-IDENTICAL final
parameters vs an uninterrupted run.

Drives resilience/supervisor.py end to end through a relaunch loop:

1. **Reference** — train the MNIST-shaped MLP ``--steps`` steps with a
   plain ``fit_batch`` loop, no supervisor.
2. **Chaos** — train the same net/data/step-count under the supervisor,
   but keep killing it: each launch arms ONE fault from a deterministic
   schedule (crash between the checkpoint tree commit and its
   ``meta.json`` rename, transient step exceptions retried with backoff,
   SIGTERM-style preemption), then relaunches with a FRESH net object —
   resume must come entirely from disk, exactly like a new process.
3. **Verdict** — every parameter array of the chaos survivor is compared
   bit-for-bit against the reference (``np.testing.assert_array_equal``,
   not allclose): recovery that perturbed the trajectory would not count.

The net is dropout-free and seed-fixed, so the step sequence is
deterministic given the step counter — which is exactly what the
supervisor checkpoints and restores.

This runs with ``async_checkpoints=True`` (the default): saves are
snapshotted on the step path but written by a background thread, so an
injected save-crash surfaces at the NEXT writer barrier (the following
save / preemption / exit), a few steps past the doomed save. Resume and
the final bit-identity verdict are unchanged — that deferral is exactly
what ``tests/test_resilience.py`` pins.

Run: ``python scripts/chaos_train.py`` (CPU is fine, ~20s). The slow
pytest variant of this loop is
``tests/test_resilience.py::test_composite_chaos_run_slow``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_enable_x64", True)  # F64 policy, like the tests


def build_net(seed):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.core import DtypePolicy
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Adam
    f64 = DtypePolicy(param_dtype="float64", compute_dtype="float64")
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .dtype(f64).list()
            .layer(Dense(n_in=12, n_out=16, activation="tanh"))
            .layer(Output(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def build_batches(seed, batch_size, n_batches=4):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(seed)
    batches = []
    for _ in range(n_batches):
        x = rng.normal(size=(batch_size, 12))
        y = np.eye(4)[rng.integers(0, 4, batch_size)]
        batches.append(DataSet(x, y))
    return batches


def flat_params(net):
    return {(n, k): np.asarray(v) for n, sub in net.params.items()
            for k, v in sub.items()}


def verify_flight(launch, expect_reason=None):
    """Every injected fault must leave a readable post-mortem: assert
    the flight-recorder artifact for this launch exists and parses,
    print its path, return the parsed doc."""
    from deeplearning4j_tpu.observability.flightrec import (
        get_flight_recorder)
    rec = get_flight_recorder()
    path = rec.last_path if rec is not None else None
    assert path and os.path.exists(path), (
        f"launch {launch}: no flight-recorder artifact was flushed")
    with open(path) as fh:
        doc = json.load(fh)
    assert doc.get("schema") == 1 and doc.get("identity"), doc.keys()
    if expect_reason is not None:
        assert doc["reason"] == expect_reason, (doc["reason"], expect_reason)
    print(f"[flight] launch {launch}: '{doc['reason']}' post-mortem -> "
          f"{path}  ({len(doc['events'])} events, {len(doc['spans'])} "
          f"spans, incarnation {doc['identity']['incarnation']})")
    return doc


def chaos_schedule(steps):
    """Faults armed per launch (a launch survives transients in place but
    dies to save-crashes and stops for preemptions, so every launch
    except the last ends early). Deterministic, so reruns of this
    script behave identically."""
    return [
        [("crash_save", 1)],                        # kill the 2nd save
        [("transient", max(2, steps // 3)),         # retried in-place...
         ("preempt", max(3, steps // 2))],          # ...then clean stop
        [("crash_save", 1)],                        # kill a save again
        [],                                         # clean final launch
    ]


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=60,
                    help="absolute target step count (default 60)")
    ap.add_argument("--checkpoint-every", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=24)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--keep", type=int, default=3,
                    help="checkpoint retention (default 3)")
    ap.add_argument("--dir", default=None,
                    help="checkpoint directory (default: fresh tempdir)")
    args = ap.parse_args()

    from deeplearning4j_tpu.resilience import (FaultInjector, InjectedCrash,
                                               SupervisorConfig,
                                               TrainingSupervisor)

    ckpt_dir = args.dir or tempfile.mkdtemp(prefix="chaos_train_")
    if args.dir:
        os.makedirs(ckpt_dir, exist_ok=True)

    batches = build_batches(args.seed, args.batch_size)
    batch_fn = lambda step: batches[step % len(batches)]  # noqa: E731

    # ------------------------------------------------ 1. reference run
    print(f"[reference] {args.steps} uninterrupted steps ...")
    t0 = time.perf_counter()
    ref = build_net(args.seed)
    for step in range(args.steps):
        ref.fit_batch(batch_fn(step))
    print(f"[reference] done in {time.perf_counter() - t0:.1f}s "
          f"(final score {float(ref.score_value):.4f})")

    # ---------------------------------------------------- 2. chaos run
    schedule = chaos_schedule(args.steps)
    n_faults = sum(len(launch) for launch in schedule)
    print(f"\n[chaos] target step {args.steps}, checkpoint every "
          f"{args.checkpoint_every}, dir {ckpt_dir}")
    from deeplearning4j_tpu.observability.distributed import (
        bump_incarnation, get_identity)

    launches, net, result = 0, None, None
    totals = {}
    while True:
        launches += 1
        # each relaunch is a new incarnation of the same instance: the
        # flight-recorder artifact and federation tag for launch N must
        # not collide with launch N-1's (the relaunch is in-process, so
        # the pid alone cannot tell them apart)
        if launches > 1:
            bump_incarnation()
        print(f"[chaos] launch {launches}: identity "
              f"{get_identity().tag}")
        injector = FaultInjector()
        for fault, at in schedule[min(launches - 1, len(schedule) - 1)]:
            if fault == "crash_save":
                injector.crash_during_save(at)
            elif fault == "transient":
                injector.fail_step(at, times=2)
            elif fault == "preempt":
                injector.preempt_at_step(at)

        net = build_net(args.seed)  # fresh object: resume is disk-only
        sup = TrainingSupervisor(
            net,
            SupervisorConfig(checkpoint_dir=ckpt_dir,
                             checkpoint_every_steps=args.checkpoint_every,
                             keep_checkpoints=args.keep,
                             backoff_initial_s=0.01,
                             handle_sigterm=False),
            injector=injector)
        try:
            with injector.installed():
                result = sup.run(batch_fn, args.steps)
        except InjectedCrash as e:
            print(f"[chaos] launch {launches}: KILLED mid-save ({e}) at "
                  f"step {net.iteration} — relaunching")
            verify_flight(launches, expect_reason="exception")
            for k, v in sup.stats.snapshot().items():
                totals[k] = totals.get(k, 0) + v
            continue
        for k, v in result.stats.items():
            totals[k] = totals.get(k, 0) + v
        if result.status == "preempted":
            print(f"[chaos] launch {launches}: preempted cleanly at step "
                  f"{result.final_step} — relaunching")
            verify_flight(launches, expect_reason="preemption")
            continue
        print(f"[chaos] launch {launches}: completed at step "
              f"{result.final_step}"
              + (f" (resumed from {os.path.basename(result.resumed_from)})"
                 if result.resumed_from else ""))
        break

    # ------------------------------------------------------ 3. verdict
    assert result.final_step == args.steps, (result.final_step, args.steps)
    pr, pc = flat_params(ref), flat_params(net)
    assert pr.keys() == pc.keys()
    for key in pr:
        np.testing.assert_array_equal(pr[key], pc[key],
                                      err_msg=f"param {key} diverged")

    print(f"\n[verdict] PASS — {launches} launches "
          f"({n_faults} injected faults), final step "
          f"{result.final_step}, all {len(pr)} parameter arrays "
          "BIT-IDENTICAL to the uninterrupted run")
    print("[stats]  " + "  ".join(f"{k}={v}" for k, v in sorted(
        totals.items()) if v))
    if not args.dir:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
