"""Quality-acceptance run (BASELINE.md): train the stock entry points and
record accuracies in ACCEPTANCE.md.

- MNIST LeNet via MnistDataSetIterator + zoo.lenet: uses REAL IDX files
  when present in the cache dirs (see datasets/fetchers.py); this
  environment has no network egress and no cached copy, so the fetcher's
  clearly-flagged synthetic fallback is used and recorded as such.
- Real-data acceptance: scikit-learn's bundled handwritten-digits dataset
  (1,797 real 8x8 scans) through the same fit(iterator)/evaluate entry
  path, bar >= 97% test accuracy.

Usage: python scripts/acceptance.py   (runs on whatever jax.devices()[0] is)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def mnist_lenet():
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator

    train_it = MnistDataSetIterator(batch_size=128, train=True)
    test_it = MnistDataSetIterator(batch_size=512, train=False)
    synthetic = train_it.descriptor.synthetic
    net = zoo.lenet()
    t0 = time.time()
    net.fit(train_it, epochs=3)
    secs = time.time() - t0
    ev = net.evaluate(test_it)
    return {"dataset": "MNIST" + (" (SYNTHETIC fallback)" if synthetic
                                  else " (real IDX files)"),
            "synthetic": synthetic, "model": "zoo.lenet (bf16)",
            "epochs": 3, "train_seconds": round(secs, 1),
            "test_accuracy": round(ev.accuracy(), 4)}


def digits_net():
    from sklearn.datasets import load_digits

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets import ArrayDataSetIterator
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.conf.layers_conv import (Convolution2D,
                                                        Subsampling)
    from deeplearning4j_tpu.nn.updater import Adam

    d = load_digits()
    x = (d.images / 16.0).astype(np.float32)[..., None]  # [n, 8, 8, 1]
    y = np.eye(10, dtype=np.float32)[d.target]
    rng = np.random.default_rng(42)
    idx = rng.permutation(len(x))
    n_test = 360
    xtr, ytr = x[idx[:-n_test]], y[idx[:-n_test]]
    xte, yte = x[idx[-n_test:]], y[idx[-n_test:]]

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
            .activation("relu").list()
            .layer(Convolution2D(n_out=32, kernel=(3, 3), mode="same",
                                 activation="relu"))
            .layer(Subsampling(kernel=(2, 2), stride=(2, 2), pooling="max"))
            .layer(Dense(n_out=128, activation="relu"))
            .layer(Output(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    t0 = time.time()
    net.fit(ArrayDataSetIterator(xtr, ytr, batch_size=64), epochs=60)
    secs = time.time() - t0
    ev = net.evaluate(DataSet(xte, yte))
    return {"dataset": "sklearn digits (REAL handwritten scans, 8x8)",
            "synthetic": False, "model": "conv32-pool-dense128-softmax (f32)",
            "epochs": 60, "train_seconds": round(secs, 1),
            "test_examples": n_test,
            "test_accuracy": round(ev.accuracy(), 4)}


def resnet18_cifar():
    """ResNet-18/CIFAR convergence smoke (BASELINE config #5's model):
    the residual stack + batch-norm chain must actually LEARN — this run
    is the regression guard for the round-4 zoo fix (BN layers used to
    inherit the global sigmoid default, silently squashing every BN
    output)."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.datasets.fetchers import CifarDataSetIterator
    from deeplearning4j_tpu.eval import Evaluation
    from deeplearning4j_tpu.nn.updater import Adam

    train_it = CifarDataSetIterator(batch_size=128, train=True,
                                    num_examples=4096)
    test_it = CifarDataSetIterator(batch_size=512, train=False,
                                   num_examples=1024)
    synthetic = train_it.descriptor.synthetic
    net = zoo.resnet18(updater=Adam(1e-3))
    t0 = time.time()
    for _ in range(3):
        for ds in train_it:
            x, y = np.asarray(ds.features), np.asarray(ds.labels)
            net.fit_batch(MultiDataSet([x], [y]))
        train_it.reset()
    secs = time.time() - t0
    ev = Evaluation(num_classes=10)
    for ds in test_it:
        out = np.asarray(net.output(np.asarray(ds.features)))
        ev.eval(np.asarray(ds.labels), out)
    return {"dataset": "CIFAR-10" + (" (SYNTHETIC fallback)" if synthetic
                                     else " (real batches)"),
            "synthetic": synthetic, "model": "zoo.resnet18 (bf16)",
            "epochs": 3, "train_seconds": round(secs, 1),
            "test_accuracy": round(ev.accuracy(), 4)}


def main():
    import jax
    dev = jax.devices()[0]
    results = {"device": str(dev), "device_kind":
               getattr(dev, "device_kind", "?"),
               "mnist_lenet": mnist_lenet(),
               "real_digits": digits_net(),
               "resnet18_cifar": resnet18_cifar()}
    print(json.dumps(results, indent=2))

    md = f"""# ACCEPTANCE — quality runs from the stock entry points

Recorded by ``scripts/acceptance.py`` on ``{results['device_kind']}``.

## Real-data acceptance (bar: >= 97% test accuracy)

| run | dataset | model | epochs | test acc |
|---|---|---|---|---|
| real_digits | {results['real_digits']['dataset']} | {results['real_digits']['model']} | {results['real_digits']['epochs']} | **{results['real_digits']['test_accuracy']:.4f}** |
| mnist_lenet | {results['mnist_lenet']['dataset']} | {results['mnist_lenet']['model']} | {results['mnist_lenet']['epochs']} | {results['mnist_lenet']['test_accuracy']:.4f} |
| resnet18_cifar | {results['resnet18_cifar']['dataset']} | {results['resnet18_cifar']['model']} | {results['resnet18_cifar']['epochs']} | {results['resnet18_cifar']['test_accuracy']:.4f} |

Notes:
- This environment has **no network egress and no cached MNIST IDX
  files**, so the MNIST run exercises the full
  ``MnistDataSetIterator -> zoo.lenet -> fit -> evaluate`` entry path on
  the fetcher's clearly-flagged synthetic fallback
  (``datasets/fetchers.py``). Drop the standard
  ``train-images-idx3-ubyte`` files into ``~/.deeplearning4j_tpu/mnist/``
  and the same command records the real-MNIST number.
- Round-4 re-attempt (VERDICT asked for the IDX files as committed
  fixtures): a full filesystem scan found no cached MNIST anywhere
  (keras/TF/HF/torch caches all empty) and a live download attempt via
  ``keras.datasets.mnist`` fails with DNS resolution disabled — the
  files physically cannot be obtained from inside this sandbox. The
  fetcher's real-IDX path itself is exercised by tests on generated IDX
  fixtures (tests/test_native_io.py).
- The **real-data** bar is met on scikit-learn's bundled handwritten
  digits (1,797 real scans, 8x8): same entry path, held-out test split.

Raw JSON:

```json
{json.dumps(results, indent=2)}
```
"""
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ACCEPTANCE.md")
    with open(out, "w") as f:
        f.write(md)
    print("wrote", out)


if __name__ == "__main__":
    main()
