"""CI budget gate: assert a RunReport (or bench result JSON) against
the committed efficiency budgets in BUDGETS.json.

Budgets are grouped into sections keyed by the report's ``kind`` (for
RunReports: "fit" / "resilient_fit" / "serving") or the bench result's
``config`` name ("goodput_overhead", "trace_overhead", ...). Inside a
section every key follows the ``min_<field>`` / ``max_<field>``
convention:

    "fit": {
        "min_goodput_fraction": 0.30,   # report.goodput_fraction >= 0.30
        "max_compile_count": 32,        # report.compile_count <= 32
        "max_untracked_fraction": 0.25  # derived: untracked_s / wall_s
    }

Fields that are absent or null in the report are SKIPPED, not failed —
e.g. ``min_mfu`` only gates on hardware where peak FLOP/s is known.
Keys starting with "_" are comments. Derived fields available beyond
the raw RunReport keys: ``untracked_fraction``, ``attributed_fraction``
(attributed_s / wall_s) and ``padding_waste_fraction`` (worst source).

``--fleet`` gates a fleet snapshot (the UIServer's ``/api/fleet``
payload, or ``scripts/fleet_demo.py --out``) against the "fleet"
section: every ``min_``/``max_`` bound is evaluated PER INSTANCE (e.g.
``max_heartbeat_age_s`` fails if ANY member's heartbeat is stale), plus
``min_live`` / ``min_ready`` over the rollup counts.

Usage:
    python scripts/check_budgets.py --report run_report.json
    python scripts/check_budgets.py --report rr.json --section fit
    python scripts/check_budgets.py --bench goodput_overhead.json
    python scripts/check_budgets.py --report rr.json --budgets MY.json
    python scripts/check_budgets.py --fleet fleet.json

Exit status 0 = all budgets hold, 1 = at least one violated (each
violation printed on its own line), 2 = usage / unreadable input.
The test suite runs this end-to-end on a tiny-model fit
(tests/test_goodput.py) so a budget regression fails CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUDGETS = os.path.join(_REPO, "BUDGETS.json")


def _resolve(report: dict, field: str) -> Optional[float]:
    """A budget field -> its numeric value in the report, or None when
    the report doesn't carry it (skip, don't fail)."""
    if field == "untracked_fraction":
        wall = report.get("wall_s")
        return (report.get("untracked_s", 0.0) / wall) if wall else None
    if field == "attributed_fraction":
        wall = report.get("wall_s")
        return (report.get("attributed_s", 0.0) / wall) if wall else None
    if field == "padding_waste_fraction":
        # RunReport carries per-source padding; gate on the worst one.
        # Bench/summary dicts may carry the scalar directly.
        pad = report.get("padding")
        if isinstance(pad, dict) and pad:
            return max(e.get("waste_fraction", 0.0) for e in pad.values())
        val = report.get("padding_waste_fraction")
        return float(val) if val is not None else None
    if field == "replica_scaling":
        # serve_bench --fleet publishes the scalar; derive it from the
        # sweep rows (rows/sec at 4 replicas over 1) when absent
        val = report.get("replica_scaling")
        if val is not None:
            return float(val)
        sweep = report.get("replica_sweep")
        if isinstance(sweep, dict):
            r1 = (sweep.get("r1") or {}).get("rows_per_sec")
            r4 = (sweep.get("r4") or {}).get("rows_per_sec")
            if r1 and r4:
                return float(r4) / float(r1)
        return None
    if field == "mesh_bit_identical":
        # 1.0 when every tensor-parallel serve row matched the
        # single-device reference bit for bit (min_ bound of 1 gates it)
        mesh = report.get("mesh")
        if isinstance(mesh, dict) and "bit_identical" in mesh:
            return 1.0 if mesh["bit_identical"] else 0.0
        val = report.get("mesh_bit_identical")
        return None if val is None else (1.0 if val else 0.0)
    val = report.get(field)
    if val is None or isinstance(val, (dict, list, str)):
        return None
    return float(val)


def check_report(report: dict, budgets: dict) -> List[str]:
    """Evaluate one budget section against one report dict; returns a
    list of human-readable violation strings (empty = all green)."""
    violations: List[str] = []
    for key, bound in budgets.items():
        if key.startswith("_"):
            continue
        if key.startswith("min_"):
            field, op = key[4:], "min"
        elif key.startswith("max_"):
            field, op = key[4:], "max"
        else:
            continue  # unknown convention: ignore, stays forward-compatible
        value = _resolve(report, field)
        if value is None:
            continue
        bound = float(bound)
        if op == "min" and value < bound:
            violations.append(
                f"{field} = {value:.6g} below budget min {bound:.6g}")
        elif op == "max" and value > bound:
            violations.append(
                f"{field} = {value:.6g} above budget max {bound:.6g}")
    return violations


def check_fleet(payload: dict, budgets: dict) -> List[str]:
    """Evaluate the "fleet" budget section against an /api/fleet
    payload: rollup bounds (min_live / min_ready / max_instances) over
    the whole fleet, every other bound per instance — one stale or
    backed-up member is a violation, not an average."""
    violations: List[str] = []
    rollup = {"live": payload.get("live"), "ready": payload.get("ready"),
              "instances": len(payload.get("instances") or ())}
    per_instance = {}
    for key, bound in budgets.items():
        if key.startswith("_"):
            continue
        field = key[4:]
        if field in rollup:
            violations.extend(
                f"fleet {v}" for v in check_report(rollup, {key: bound}))
        else:
            per_instance[key] = bound
    for row in payload.get("instances") or ():
        for v in check_report(row, per_instance):
            violations.append(f"instance {row.get('instance')!r}: {v}")
    return violations


def _section_for(report: dict, budgets: dict,
                 override: Optional[str]) -> Optional[str]:
    if override:
        return override
    for key in ("kind", "config"):
        name = report.get(key)
        if name and name in budgets:
            return name
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budgets", default=DEFAULT_BUDGETS,
                    help=f"budgets file (default: {DEFAULT_BUDGETS})")
    ap.add_argument("--report", default=None,
                    help="RunReport JSON (from fit / resilient_fit / "
                         "serving drain, or DL4J_TPU_RUN_REPORT_DIR)")
    ap.add_argument("--bench", default=None,
                    help="bench result JSON with a 'config' key (e.g. "
                         "perf_probe/serve_bench output)")
    ap.add_argument("--fleet", default=None,
                    help="fleet snapshot JSON (/api/fleet payload or "
                         "fleet_demo.py --out) gated per instance "
                         "against the 'fleet' section")
    ap.add_argument("--section", default=None,
                    help="budget section to apply (default: the "
                         "report's 'kind' or the bench's 'config')")
    args = ap.parse_args(argv)

    if not args.report and not args.bench and not args.fleet:
        print("check_budgets: need --report, --bench or --fleet",
              file=sys.stderr)
        return 2
    path = args.report or args.bench or args.fleet
    try:
        with open(path) as f:
            report = json.load(f)
        with open(args.budgets) as f:
            budgets = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_budgets: {e}", file=sys.stderr)
        return 2

    if args.fleet:
        section = args.section or "fleet"
        if section not in budgets:
            print(f"check_budgets: no {section!r} section in "
                  f"{args.budgets}", file=sys.stderr)
            return 2
        violations = check_fleet(report, budgets[section])
        if violations:
            for v in violations:
                print(f"BUDGET VIOLATION [{section}]: {v}")
            return 1
        n = len(report.get("instances") or ())
        print(f"budgets OK [{section}]: {n} instance(s) checked, "
              "0 violated")
        return 0

    # a serve_bench.py --out file: gate the embedded drain RunReport,
    # folding in the summary rollup (p99, rows/sec, waste fraction)
    if "kind" not in report and "config" not in report \
            and isinstance(report.get("run_report"), dict):
        merged = dict(report["run_report"])
        merged.update(report.get("summary") or {})
        report = merged

    section = _section_for(report, budgets, args.section)
    if section is None or section not in budgets:
        print(f"check_budgets: no budget section for "
              f"kind/config {report.get('kind') or report.get('config')!r} "
              f"in {args.budgets} (use --section)", file=sys.stderr)
        return 2

    violations = check_report(report, budgets[section])
    if violations:
        for v in violations:
            print(f"BUDGET VIOLATION [{section}]: {v}")
        return 1
    checked = sum(1 for k in budgets[section]
                  if k.startswith(("min_", "max_")))
    print(f"budgets OK [{section}]: {checked} bounds checked, 0 violated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
