"""Trace-stitching + SLO receipt: a stitched cross-process request
waterfall and SLO attainment over the same federated load.

The receipt behind BUDGETS.json ``slo`` (TRACE_SLO_r01.json). One
topology — a parent-process ``FrontDoorRouter`` federating 2 child
``ModelServer`` processes (``--child-host`` mode), each pushing
metrics snapshots WITH request-scoped span batches to the router —
two arms:

- **stitched waterfall (with failover)**: one decode session runs
  through the router under ONE client-minted ``X-DL4J-Trace-Id``;
  mid-stream the bench SIGKILLs the pinned host, so the survivor's
  re-prefill recovery spans join the same trace. The router's
  ``GET /api/trace/<id>`` must return a waterfall whose spans come
  from >= 3 instances (router + both hosts), carry derived
  ``network`` gap segments, and whose per-hop windows sum to the
  client-observed latency within ``max_waterfall_latency_gap_pct`` —
  the proof that the queue/device/network attribution adds up to what
  the client actually waited. The stream itself must stay
  bit-identical to the sequential reference (tracing changes nothing).
- **SLO attainment**: closed-loop /predict load through the router;
  the router's ``SLOEngine`` folds the hosts' pushed serving counters
  into its sliding windows and ``/api/fleet`` reports availability
  attainment / burn-rate over exactly that load.

Run: ``python scripts/trace_slo_bench.py --out TRACE_SLO_r01.json``
then ``python scripts/check_budgets.py --bench TRACE_SLO_r01.json``.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------- child
def child_main(args) -> int:
    """One serving host (crosshost_serve_bench child pattern): warmed
    ModelServer with a gpt_mini DecodeEngine, heartbeats + span batches
    pushed to the router. Decode ops are padded with a GIL-released
    sleep standing in for the device, so the waterfall's per-hop
    windows are dominated by modeled device time, not stack overhead
    (the same reason crosshost_serve_bench pads /predict)."""
    from crosshost_serve_bench import DECODE_CFG
    from deeplearning4j_tpu.serving import DecodeEngine
    from deeplearning4j_tpu.serving.server import ModelServer
    from deeplearning4j_tpu.zoo import gpt_mini
    from serve_bench import _serving_mlp

    net = _serving_mlp(args.hidden, args.depth)
    engine = DecodeEngine(gpt_mini(**DECODE_CFG), n_pages=64,
                          page_tokens=8)
    server = ModelServer(net, port=0, max_batch=args.max_batch,
                         batch_window_ms=1.0, max_queue=4096,
                         compile_cache_dir=args.cache_dir,
                         decode_engine=engine,
                         push_url=args.push_url or None,
                         push_interval_s=0.4).start()
    engine.warm()

    sim_s = args.device_sim_ms / 1000.0
    real_prefill, real_step = engine.prefill, engine.step

    def slow_prefill(sid, ids, trace_id=None):
        out = real_prefill(sid, ids, trace_id=trace_id)
        time.sleep(sim_s)
        return out

    def slow_step(sid, token, trace_id=None):
        out = real_step(sid, token, trace_id=trace_id)
        time.sleep(sim_s)
        return out

    engine.prefill, engine.step = slow_prefill, slow_step

    print(json.dumps({"ready": True, "port": server.port,
                      "url": server.url, "pid": os.getpid()}),
          flush=True)
    try:
        for _ in sys.stdin:   # parent closes stdin (or SIGKILLs us)
            pass
    except Exception:
        pass
    server.stop()
    return 0


# ------------------------------------------------------------------ parent
def spawn_host(idx: int, cache_dir: str, push_url: str, run_id: str,
               args, timeout_s: float = 900.0) -> dict:
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__), "--child-host",
           "--cache-dir", cache_dir, "--push-url", push_url or "",
           "--hidden", str(args.hidden), "--depth", str(args.depth),
           "--max-batch", str(args.max_batch),
           "--device-sim-ms", str(args.device_sim_ms)]
    env = {**os.environ,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
           "DL4J_TPU_RUN_ID": run_id,
           "DL4J_TPU_INSTANCE": f"host{idx}"}
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=_REPO, env=env)
    deadline = time.monotonic() + timeout_s
    line = proc.stdout.readline()
    while line and not line.startswith("{"):
        line = proc.stdout.readline()
        if time.monotonic() > deadline:
            break
    if not line:
        proc.kill()
        err = proc.stderr.read()
        raise RuntimeError(f"host{idx} died before ready:\n{err[-2000:]}")
    boot = json.loads(line)
    return {"proc": proc, "url": boot["url"], "port": boot["port"],
            "boot": boot}


class _Client:
    """Keep-alive client to the router: latency measured tightly
    around request/response, so the client-observed total and the
    router's hop windows disagree only by loopback + handler parse."""

    def __init__(self, host: str, port: int, timeout_s: float = 300.0):
        self.conn = http.client.HTTPConnection(host, port,
                                               timeout=timeout_s)

    def post(self, path: str, obj: dict, trace_id: str = None):
        from deeplearning4j_tpu.observability.distributed import (
            TRACE_HEADER)
        body = json.dumps(obj).encode()
        hdrs = {"Content-Type": "application/json"}
        if trace_id:
            hdrs[TRACE_HEADER] = trace_id
        t0 = time.perf_counter()
        self.conn.request("POST", path, body, hdrs)
        resp = self.conn.getresponse()
        data = resp.read()
        ms = (time.perf_counter() - t0) * 1e3
        return resp.status, json.loads(data or b"{}"), ms

    def close(self):
        self.conn.close()


def stitched_waterfall_arm(router, hosts, args) -> dict:
    """One traced decode session through the router, SIGKILLing the
    pinned host mid-stream; harvest /api/trace/<id> and compare its
    hop windows against the client-observed latency."""
    import numpy as np

    from crosshost_serve_bench import (DECODE_CFG, kill_host,
                                       reference_streams, _get)
    from deeplearning4j_tpu.observability.distributed import new_trace_id

    n_tokens = args.gen_tokens
    kill_after = max(1, n_tokens * 2 // 3)
    rng = np.random.default_rng(11)
    prompt = [int(t) for t in
              rng.integers(1, DECODE_CFG["vocab_size"], size=4)]
    ref = reference_streams([prompt], n_tokens)[0]

    tid = new_trace_id()
    cli = _Client(router.host, router.port)
    sid = "traced-s0"
    client_ms = 0.0
    recovered = 0
    killed = None
    try:
        st, out, ms = cli.post("/decode", {"op": "prefill", "sid": sid,
                                           "ids": prompt}, tid)
        assert st == 200, (st, out)
        client_ms += ms
        logits = np.asarray(out["logits"], np.float32)
        toks = []
        for t in range(n_tokens):
            nxt = int(np.argmax(logits))
            toks.append(nxt)
            if t == kill_after:
                # let the pinned host's span pushes land, then kill it:
                # the tail of the stream fails over and the survivor's
                # recovery spans join the SAME trace
                time.sleep(1.2)
                pinned_urls = {h.base_url
                               for h in router._affinity.values()}
                victim = next((h for h in hosts
                               if h["url"].rstrip("/") in pinned_urls),
                              hosts[0])
                kill_host(victim)
                killed = victim["url"]
            st, out, ms = cli.post("/decode", {"op": "step", "sid": sid,
                                               "token": nxt}, tid)
            assert st == 200, (st, out)
            client_ms += ms
            if out.get("recovered"):
                recovered += 1
            logits = np.asarray(out["logits"], np.float32)
        st, out, ms = cli.post("/decode", {"op": "close", "sid": sid},
                               tid)
        client_ms += ms
    finally:
        cli.close()

    # survivor span batches ride 0.4s heartbeats: poll until the trace
    # shows handler spans from both hosts (or give up after 15s)
    wf = {}
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        code, wf = _get(router.url, f"/api/trace/{tid}")
        insts = {s["instance"] for s in wf.get("segments", ())
                 if s["instance"] not in ("wire",)}
        if code == 200 and len(insts) >= 3:
            break
        time.sleep(0.5)

    segs = wf.get("segments", [])
    insts = sorted({s["instance"] for s in segs
                    if s["instance"] != "wire"})
    summary = wf.get("summary_ms", {})
    hop_ms = summary.get("router_proxy", 0.0)
    gap_pct = (abs(client_ms - hop_ms) / client_ms * 100.0
               if client_ms else None)
    survivor_insts = {s["instance"] for s in segs
                      if s["name"] == "decode_prefill"}
    return {
        "trace_id": tid,
        "tokens": n_tokens,
        "kill_after_tokens": kill_after,
        "killed_host": killed,
        "failover_recoveries": recovered,
        "bit_identical": int(toks == ref),
        "client_ms": round(client_ms, 3),
        "hop_ms": round(hop_ms, 3),
        "latency_gap_pct": round(gap_pct, 3) if gap_pct is not None
        else None,
        "instances": insts,
        "network_segments": sum(1 for s in segs
                                if s["name"] == "network"),
        "summary_ms": summary,
        "recovery_prefill_instances": sorted(survivor_insts),
        "waterfall": wf,
    }


def slo_arm(router, args) -> dict:
    """Closed-loop /predict load through the router, then the router's
    own SLO report over the hosts' pushed counters."""
    import numpy as np

    from crosshost_serve_bench import _get
    from serve_bench import _serving_mlp, run_load

    net = _serving_mlp(args.hidden, args.depth)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 64)).astype(np.float32)
    reference = np.asarray(net.output(x))

    # baseline ingest (counter deltas need two sightings per source)
    _get(router.url, "/api/fleet")
    load = run_load(router.port, x, reference, args.clients,
                    args.requests)
    if "error" in load:
        raise RuntimeError(f"predict load failed: {load['error']}")
    # let the post-load pushes land, folding the load's counters into
    # the engine's windows across a couple of polls
    slo = {}
    for _ in range(4):
        time.sleep(0.7)
        code, fleet = _get(router.url, "/api/fleet")
        slo = fleet.get("slo") or {}
        att = ((slo.get("slos") or {}).get("availability")
               or {}).get("attainment")
        if att is not None:
            break
    return {"load": {k: load.get(k) for k in
                     ("rows_per_sec", "p50_ms", "p99_ms", "errors",
                      "bit_identical")},
            "slo": slo}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child-host", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--cache-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--push-url", default="", help=argparse.SUPPRESS)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--max-batch", type=int, default=8)
    # decode ops padded to ~40ms so per-hop windows dominate the
    # client-observed latency (the gap bound measures attribution, not
    # loopback noise)
    ap.add_argument("--device-sim-ms", type=float, default=40.0)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=15,
                    help="predict requests per client (SLO arm)")
    ap.add_argument("--gen-tokens", type=int, default=15)
    ap.add_argument("--out", default=None,
                    help="artifact path (check_budgets --bench gates it)")
    args = ap.parse_args(argv)
    if args.child_host:
        return child_main(args)

    from crosshost_serve_bench import kill_host
    from deeplearning4j_tpu.compilecache import atomic_publish
    from deeplearning4j_tpu.serving import FrontDoorRouter

    report: dict = {
        "config": "slo",
        "model": f"serving_mlp 64-{args.hidden}x{args.depth}-10 "
                 f"+ gpt_mini decode",
        "device_sim_ms": args.device_sim_ms,
        "clients": args.clients,
        "created_unix": round(time.time(), 3),
    }
    run_id = f"traceslo-{os.getpid()}"
    router = FrontDoorRouter(stale_after_s=5.0).start()
    push_url = router.url + "/api/metrics_push"
    hosts = []
    try:
        with tempfile.TemporaryDirectory(prefix="dl4j_traceslo_") as tmp:
            cache = os.path.join(tmp, "shared-xla-cache")
            for i in range(2):
                print(f"== host {i}: boot ==", file=sys.stderr)
                h = spawn_host(i, cache, push_url, run_id, args)
                hosts.append(h)
                router.add_host(h["url"])
            time.sleep(1.0)   # first pushes land

            print("== SLO arm: /predict load through the router ==",
                  file=sys.stderr)
            report["slo_arm"] = slo_arm(router, args)

            print("== waterfall arm: traced decode + failover ==",
                  file=sys.stderr)
            report["waterfall_arm"] = stitched_waterfall_arm(
                router, hosts, args)
            report["trace_store"] = router.trace_store.describe()
    finally:
        for h in hosts:
            try:
                kill_host(h)
            except Exception:
                pass
        router.stop()

    wfa = report["waterfall_arm"]
    slos = (report["slo_arm"]["slo"].get("slos") or {})
    avail = slos.get("availability") or {}
    # gated scalars, top-level so check_budgets' generic resolver sees
    # them (BUDGETS.json "slo" section)
    report.update({
        "stitched_instances": len(wfa["instances"]),
        "waterfall_latency_gap_pct": wfa["latency_gap_pct"],
        "waterfall_network_segments": wfa["network_segments"],
        "failover_trace_stitched":
            int(bool(wfa["recovery_prefill_instances"])
                and wfa["failover_recoveries"] >= 1),
        "decode_bit_identical": wfa["bit_identical"],
        "slo_availability_attainment": avail.get("attainment"),
        "slo_availability_burn_rate": avail.get("burn_rate"),
    })

    print(json.dumps({k: v for k, v in report.items()
                      if k != "waterfall_arm"}, indent=1))
    print(json.dumps({k: v for k, v in wfa.items()
                      if k != "waterfall"}, indent=1))
    if args.out:
        out = os.path.abspath(args.out)
        atomic_publish(os.path.dirname(out), os.path.basename(out),
                       report)
        print(f"wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
