"""Cross-process chaos drill: murder real fleet members and prove the
fleet recovers — coordinated, detected, relaunched, bit-identical.

Three arms, each a REAL multi-process jax.distributed fleet (2 workers
x 2 virtual CPU devices, one 4-device data mesh) spawned through
``resilience.launcher.FleetLauncher``; this same script is the worker
(``--worker``), so the fault plan is constructed identically on every
rank and fires only where targeted:

**Arm A — lockstep NaN rollback.** Rank 0 alone is poisoned (NaN param
leaf at step 4). The NaN consensus round must roll BOTH ranks back to
the same checkpoint — the poisoned rank and the healthy one — and the
replayed fleet must finish with params bit-identical across ranks AND
bit-identical to a no-fault control fleet of the same shape.

**Arm B — peer death, detection, elastic relaunch.** Rank 1 takes a
real SIGKILL at step 5 (no handlers, no cleanup). Rank 0 must detect
the loss as a consensus timeout within the collective deadline, flush a
``peer_lost`` flight record, write NO further checkpoint, and exit
``PEER_LOST_EXIT``. The launcher then relaunches the fleet SHRUNK to
one process (same 4 global devices), which elastically restores the
2-process checkpoint: params land on the new layout, the datapipe
shard cursor remaps at the coverage rule's low-water mark (a
``reshard`` RecoveryEvent), and the survivor's final params are
bit-identical to a hand-replayed control on the same topology. The
records consumed after restore tile the epoch exactly from the
low-water mark — nothing dropped, nothing doubled.

**Arm C — SIGTERM broadcast.** A real SIGTERM lands on rank 1 only.
The preemption consensus must broadcast it: both ranks stop at the SAME
step boundary, write ONE final barriered checkpoint, and exit cleanly
with per-rank run reports (``run_report.json`` + ``run_report.r1.json``)
and per-rank flight artifacts.

The meta.json validity invariant is audited between Arm B launches:
after the kill, the newest restorable checkpoint is the last one that
completed on every rank — no partial save is ever restorable.

Run: ``python scripts/chaos_multihost.py --out CROSSHOST_r01.json``
(CPU, ~3 min — dominated by per-worker XLA compiles). The receipt is
gated by ``scripts/check_budgets.py --bench`` against the
``cross_host`` section of BUDGETS.json.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    # the parent needs the same 4 devices as the resumed lone survivor,
    # so the Arm B control replay runs on matching topology
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

import numpy as np

SEED = 17
N_RECORDS = 64
GLOBAL_BATCH = 8          # records per step, whole fleet
TOTAL_DEVICES = 4         # constant across fleet sizes (2x2 -> 1x4)
CKPT_EVERY = 3
POISON_STEP = 4           # Arm A: NaN lands on rank 0 here
KILL_STEP = 5             # Arm B: SIGKILL lands on rank 1 here
SIGTERM_STEP = 4          # Arm C: SIGTERM lands on rank 1 here
DETECT_TIMEOUT_S = 20.0   # Arm B consensus deadline (budget: <= 30s)


def build_net(seed):
    import jax  # noqa: F401  (x64 flag set by caller)
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.core import DtypePolicy
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Adam
    f64 = DtypePolicy(param_dtype="float64", compute_dtype="float64")
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .dtype(f64).list()
            .layer(Dense(n_in=12, n_out=16, activation="tanh"))
            .layer(Output(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def build_data(seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N_RECORDS, 12))
    x[:, 0] = np.arange(N_RECORDS)  # record id rides in feature column 0
    y = np.eye(4)[rng.integers(0, 4, N_RECORDS)]
    return x, y


def build_pipeline(x, y, num_shards, index, tracker, batch):
    """shard -> map(track record ids) -> batch — the same 1:1 tracking
    stage chaos_reshard.py uses, so the elastic remap accepts it."""
    from deeplearning4j_tpu import datapipe

    def track(rec):
        tracker.append(int(round(float(rec[0][0]))))
        return rec

    return (datapipe.from_arrays(x, y).shard(num_shards, index)
            .map(track).batch(batch))


def flat_params(net):
    import jax
    return {f"{ln}.{pn}": np.asarray(jax.device_get(arr))
            for ln, sub in net.params.items() for pn, arr in sub.items()}


# ----------------------------------------------------------------- worker
def run_worker(args) -> int:
    import jax
    jax.config.update("jax_enable_x64", True)
    from deeplearning4j_tpu.parallel import distributed
    if args.size > 1:
        distributed.initialize(args.coord, args.size, args.rank)
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.resilience import (PEER_LOST_EXIT,
                                               FaultInjector,
                                               SupervisorConfig,
                                               TrainingSupervisor)

    net = build_net(SEED).use_mesh(make_mesh({"data": len(jax.devices())}))
    x, y = build_data(SEED)
    seen: list = []
    pipe = build_pipeline(x, y, args.size, args.rank, seen,
                          GLOBAL_BATCH // args.size)

    # one fault plan, built identically on EVERY rank; rank= targets it
    injector = FaultInjector()
    if args.poison_step >= 0:
        injector.poison_step(args.poison_step, rank=args.poison_rank)
    if args.kill_step >= 0:
        injector.kill_at_step(args.kill_step, rank=args.kill_rank)
    if args.sigterm_step >= 0:
        injector.sigterm_at_step(args.sigterm_step, rank=args.sigterm_rank)

    cfg = SupervisorConfig(
        checkpoint_dir=args.ckpt_dir,
        checkpoint_every_steps=CKPT_EVERY,
        keep_checkpoints=10,        # the drill audits old steps post-hoc
        backoff_initial_s=0.01,
        # bit-identity standard: a rollback must replay the control
        # trajectory exactly, so the LR stays untouched in this drill
        nan_lr_backoff=1.0)
    sup = TrainingSupervisor(net, cfg, injector=injector)
    with injector.installed():
        res = sup.fit_pipeline(pipe, epochs=1)

    inc = os.environ.get("DL4J_TPU_INCARNATION", "0")
    os.makedirs(args.out_dir, exist_ok=True)
    np.savez(os.path.join(args.out_dir,
                          f"params_l{inc}_r{args.rank}.npz"),
             **flat_params(net))
    result = {
        "arm": args.arm, "rank": args.rank, "size": args.size,
        "incarnation": int(inc), "status": res.status,
        "final_step": res.final_step,
        "resumed_from": (res.resumed_from
                         and os.path.basename(res.resumed_from)),
        "events": [{"kind": e.kind, "step": e.step, "detail": e.detail}
                   for e in res.events],
        "stats": res.stats,
        "peer_loss": res.peer_loss,
        "seen": seen,
    }
    path = os.path.join(args.out_dir, f"result_l{inc}_r{args.rank}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh, indent=1)
    os.replace(tmp, path)
    print(f"[worker r{args.rank} l{inc}] {res.status} at step "
          f"{res.final_step}", flush=True)
    if res.status == "peer_lost":
        # hard exit: the interpreter's atexit jax.distributed shutdown
        # would block on a barrier the dead peer can never join, pinning
        # this process until the launcher's grace window SIGKILLs it
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)
    return 0


# ----------------------------------------------------------------- parent
def _load_result(out_dir, launch, rank):
    with open(os.path.join(out_dir,
                           f"result_l{launch}_r{rank}.json")) as fh:
        return json.load(fh)


def _load_params(out_dir, launch, rank):
    return dict(np.load(os.path.join(
        out_dir, f"params_l{launch}_r{rank}.npz")))


def _assert_params_equal(a: dict, b: dict, what: str):
    assert sorted(a) == sorted(b), (what, sorted(a), sorted(b))
    for key in a:
        np.testing.assert_array_equal(a[key], b[key],
                                      err_msg=f"{what}: {key}")


def _events(result, kind):
    return [e for e in result["events"] if e["kind"] == kind]


def run_fleet(arm, ckpt_dir, out_dir, fault_flags, *, size=2,
              max_launches=1, audit=None, timeout_s=None):
    from deeplearning4j_tpu.resilience.launcher import FleetLauncher

    script = os.path.abspath(__file__)

    def build_argv(n, rank, coord):
        return [sys.executable, script, "--worker",
                "--coord", coord, "--size", str(n), "--rank", str(rank),
                "--ckpt-dir", ckpt_dir, "--out-dir", out_dir,
                "--arm", arm, *fault_flags]

    extra_env = {"JAX_PLATFORMS": "cpu"}
    if timeout_s is not None:
        extra_env["DL4J_TPU_COLLECTIVE_TIMEOUT_S"] = str(timeout_s)

    class AuditedLauncher(FleetLauncher):
        def launch_once(self, n, launch_index=0):
            rec = super().launch_once(n, launch_index)
            if audit is not None:
                audit(rec)
            return rec

    launcher = AuditedLauncher(
        build_argv, min_size=1, max_launches=max_launches,
        total_devices=TOTAL_DEVICES, straggler_grace_s=90.0,
        launch_timeout_s=420.0, extra_env=extra_env, log_dir=out_dir)
    return launcher.run(size)


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--dir", default=None,
                    help="work directory (default: fresh tempdir)")
    ap.add_argument("--out", default=None,
                    help="write the receipt JSON here (CROSSHOST_r01.json)")
    # worker mode (internal): spawned by the FleetLauncher
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--coord", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--size", type=int, default=1, help=argparse.SUPPRESS)
    ap.add_argument("--rank", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--out-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--arm", default="", help=argparse.SUPPRESS)
    ap.add_argument("--poison-step", type=int, default=-1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--poison-rank", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--kill-step", type=int, default=-1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--kill-rank", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--sigterm-step", type=int, default=-1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--sigterm-rank", type=int, default=1,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.worker:
        return run_worker(args)

    import logging
    logging.basicConfig(level=logging.INFO,
                        format="%(levelname)s %(name)s: %(message)s")
    root = args.dir or tempfile.mkdtemp(prefix="chaos_multihost_")
    os.makedirs(root, exist_ok=True)
    d = {name: os.path.join(root, name)
         for name in ("ckptA", "outA", "ckptA0", "outA0",
                      "ckptB", "outB", "ckptC", "outC")}

    from deeplearning4j_tpu.resilience.launcher import PEER_LOST_EXIT
    from deeplearning4j_tpu.utils.checkpoint import (find_latest_checkpoint,
                                                     read_checkpoint_meta)

    steps_per_epoch = N_RECORDS // GLOBAL_BATCH

    # ============ Arm A: rank-0 poison -> fleet-wide lockstep rollback
    print(f"\n[armA] 2-proc fleet, NaN poison on rank 0 at step "
          f"{POISON_STEP} (dir {root})")
    resA = run_fleet("A", d["ckptA"], d["outA"],
                     ["--poison-step", str(POISON_STEP),
                      "--poison-rank", "0"])
    assert resA.status == "completed" and len(resA.launches) == 1, resA
    print("[armA0] no-fault control fleet, same shape")
    resA0 = run_fleet("A0", d["ckptA0"], d["outA0"], [])
    assert resA0.status == "completed", resA0

    rA = [_load_result(d["outA"], 0, r) for r in (0, 1)]
    for r in rA:
        assert r["status"] == "completed", r["status"]
        assert r["final_step"] == steps_per_epoch, r["final_step"]
        assert r["stats"]["rollbacks_total"] == 1, r["stats"]
        assert _events(r, "rollback"), "no rollback event"
    pA = [_load_params(d["outA"], 0, r) for r in (0, 1)]
    pA0 = _load_params(d["outA0"], 0, 0)
    _assert_params_equal(pA[0], pA[1], "armA rank0 vs rank1")
    _assert_params_equal(pA[0], pA0, "armA vs no-fault control fleet")
    lockstep_rollback = 1
    print(f"[armA] PASS — one poisoned rank rolled BOTH ranks back "
          f"(healthy rank too); final params bit-identical across ranks "
          f"and to the control fleet "
          f"(rollback: '{_events(rA[0], 'rollback')[0]['detail']}')")

    # ====== Arm B: SIGKILL rank 1 -> detect, no partial ckpt, relaunch 1
    print(f"\n[armB] 2-proc fleet, SIGKILL on rank 1 at step {KILL_STEP}; "
          f"collective timeout {DETECT_TIMEOUT_S:.0f}s")
    audit_state = {}

    def audit(rec):
        if not rec.peer_lost_ranks:
            return
        # between launches: the kill must leave the last COMPLETE
        # checkpoint as the newest restorable one — the meta.json
        # validity invariant (no partial save is ever restorable)
        latest = find_latest_checkpoint(d["ckptB"])
        assert latest is not None
        step = int(os.path.basename(latest).split("_")[1])
        last_full_ckpt = (KILL_STEP // CKPT_EVERY) * CKPT_EVERY
        assert step == last_full_ckpt, (latest, last_full_ckpt)
        audit_state["latest"] = latest
        audit_state["meta"] = read_checkpoint_meta(latest)

    resB = run_fleet("B", d["ckptB"], d["outB"],
                     ["--kill-step", str(KILL_STEP), "--kill-rank", "1"],
                     max_launches=3, audit=audit,
                     timeout_s=DETECT_TIMEOUT_S)
    assert resB.status == "completed", resB
    assert resB.final_size == 1 and len(resB.launches) == 2, resB
    first, second = resB.launches
    assert first.peer_lost_ranks == [0], first.workers
    assert first.workers[0].returncode == PEER_LOST_EXIT
    assert first.workers[1].returncode < 0, first.workers  # signal death

    # the survivor's view: peer named, detection timed, nothing saved
    rB0 = _load_result(d["outB"], 0, 0)
    assert rB0["status"] == "peer_lost"
    assert rB0["peer_loss"]["lost_ranks"] == [1], rB0["peer_loss"]
    detection_s = float(rB0["peer_loss"]["detection_s"])
    assert rB0["stats"]["peer_losses_total"] == 1
    peer_loss_detected = 1
    flights = [p for p in glob.glob(os.path.join(d["ckptB"], "flight_*"))
               if json.load(open(p)).get("reason") == "peer_lost"]
    assert flights, "no peer_lost flight record"
    print(f"[armB] survivor detected the loss in {detection_s:.1f}s, "
          f"exited {PEER_LOST_EXIT} with flight record "
          f"{os.path.basename(flights[0])}; launcher relaunched at "
          f"size 1")

    # the relaunched lone survivor: elastic restore + exact tiling
    from deeplearning4j_tpu.datapipe.reshard import low_water_mark
    low_water = low_water_mark(audit_state["meta"]["datapipe"])
    ckpt_step = int(os.path.basename(
        audit_state["latest"]).split("_")[1])
    assert low_water == ckpt_step * GLOBAL_BATCH, (low_water, ckpt_step)
    rB1 = _load_result(d["outB"], 1, 0)
    assert rB1["status"] == "completed"
    assert rB1["resumed_from"] == os.path.basename(audit_state["latest"])
    reshard_events = _events(rB1, "reshard")
    assert reshard_events, [e["kind"] for e in rB1["events"]]
    assert rB1["seen"] == list(range(low_water, N_RECORDS)), (
        rB1["seen"][:4], low_water)
    assert rB1["final_step"] == steps_per_epoch, rB1["final_step"]
    datapipe_exact = 1
    print(f"[armB] relaunched run resumed {rB1['resumed_from']} onto "
          f"1 process: reshard event '{reshard_events[0]['detail']}'; "
          f"records [{low_water}, {N_RECORDS}) consumed exactly "
          f"(low-water mark {low_water})")

    # control: restore the same checkpoint on this (4-device) process
    # and hand-replay the remainder — bit-identity standard
    import jax
    jax.config.update("jax_enable_x64", True)
    from deeplearning4j_tpu.datapipe.reshard import remap_for
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.utils.checkpoint import \
        restore_multi_layer_network
    mesh4 = make_mesh({"data": len(jax.devices())})
    net_c = restore_multi_layer_network(audit_state["latest"], mesh=mesh4)
    seen_control: list = []
    pipe_c = build_pipeline(*build_data(SEED), 1, 0, seen_control,
                            GLOBAL_BATCH)
    pipe_c.load_state_dict(
        remap_for(pipe_c, audit_state["meta"]["datapipe"]))
    for ds in pipe_c.stream(1):
        net_c.fit_batch(ds)
    assert seen_control == rB1["seen"]
    _assert_params_equal(_load_params(d["outB"], 1, 0),
                         flat_params(net_c),
                         "armB survivor vs hand-replayed control")
    bit_identical = 1
    print("[armB] PASS — survivor's final params bit-identical to the "
          "hand-replayed control")

    # ========== Arm C: SIGTERM on one rank -> fleet-wide clean preempt
    print(f"\n[armC] 2-proc fleet, SIGTERM on rank 1 at step "
          f"{SIGTERM_STEP}")
    resC = run_fleet("C", d["ckptC"], d["outC"],
                     ["--sigterm-step", str(SIGTERM_STEP),
                      "--sigterm-rank", "1"])
    assert resC.status == "completed" and len(resC.launches) == 1, resC
    rC = [_load_result(d["outC"], 0, r) for r in (0, 1)]
    for r in rC:
        assert r["status"] == "preempted", r["status"]
        assert _events(r, "preempt"), "no preempt event"
    assert rC[0]["final_step"] == rC[1]["final_step"], (
        rC[0]["final_step"], rC[1]["final_step"])
    latest_c = find_latest_checkpoint(d["ckptC"])
    assert latest_c is not None and latest_c.endswith(
        f"step_{rC[0]['final_step']}"), latest_c
    # per-rank artifacts: rank 0 keeps the legacy names, rank 1 suffixed
    assert os.path.exists(os.path.join(d["ckptC"], "run_report.json"))
    assert os.path.exists(os.path.join(d["ckptC"], "run_report.r1.json"))
    assert glob.glob(os.path.join(d["ckptC"], "flight_*.r1.json"))
    preempt_broadcast = 1
    print(f"[armC] PASS — SIGTERM on rank 1 stopped BOTH ranks at step "
          f"{rC[0]['final_step']} with one barriered final checkpoint "
          f"({os.path.basename(latest_c)}) and per-rank "
          f"run_report/flight artifacts")

    # ------------------------------------------------------------ receipt
    receipt = {
        "config": "cross_host",
        "created_unix": round(time.time(), 2),
        "fleet_size": 2, "total_devices": TOTAL_DEVICES,
        "records": N_RECORDS, "steps_per_epoch": steps_per_epoch,
        "lockstep_rollback": lockstep_rollback,
        "bit_identical": bit_identical,
        "peer_loss_detected": peer_loss_detected,
        "detection_s": round(detection_s, 3),
        "collective_timeout_s": DETECT_TIMEOUT_S,
        "reshard_events": len(reshard_events),
        "datapipe_exact": datapipe_exact,
        "preempt_broadcast": preempt_broadcast,
        "relaunches": resB.relaunches,
        "final_fleet_size": resB.final_size,
        "low_water_record": low_water,
        "detail": {
            "armA_rollback": _events(rA[0], "rollback")[0]["detail"],
            "armB_peer_loss": rB0["peer_loss"],
            "armB_reshard": reshard_events[0]["detail"],
            "armB_resumed_from": rB1["resumed_from"],
            "armC_final_step": rC[0]["final_step"],
        },
    }
    print(f"\n[verdict] PASS — lockstep rollback, peer loss detected in "
          f"{detection_s:.1f}s (limit {DETECT_TIMEOUT_S:.0f}s), elastic "
          f"relaunch 2->1 bit-identical, SIGTERM broadcast clean")
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(receipt, fh, indent=1)
        print(f"[receipt] {args.out}")
    else:
        print(json.dumps(receipt, indent=1))
    if not args.dir:
        shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
