"""Cold-start benchmark: time-to-first-reply and compile cost across
three boot arms, plus the autotuned-vs-default serving schedule.

Each arm boots a FRESH python process (``--child`` mode) that builds
the bench MLP, starts a warmed ``ModelServer``, fires one /predict,
then replays the bucket ladder to count steady-state compiles:

- ``cold`` — empty persistent cache: every ladder bucket misses and
  pays a real XLA compile.
- ``warm`` — same cache dir, second boot: every lookup hits, the boot
  compiles ~nothing (``cache_misses == 0``, ``compile_seconds`` a
  fraction of cold's).
- ``aot``  — a cache populated by ``scripts/precompile.py`` before the
  first boot ever runs, manifest-validated at boot: the deploy-time
  story (never pay the compile online at all).

The autotune section replays a ``serve_bench --out`` trace through
``scripts/autotune_serving.py`` and reports the tuned config's
objective vs the default's (<= 1.0 by construction).

Output (``--out COLDSTART_r01.json``) carries ``"config":
"cold_start"`` with the gated numbers top-level, so
``scripts/check_budgets.py --bench COLDSTART_r01.json`` applies the
BUDGETS.json ``cold_start`` section directly.

Run: ``python scripts/coldstart_bench.py --out COLDSTART_r01.json``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# ------------------------------------------------------------------- child
def child_main(args) -> int:
    """One boot measurement in a pristine process: start a warmed
    server against ``--cache-dir``, reply once, replay the ladder,
    print one JSON dict on stdout."""
    import numpy as np

    from deeplearning4j_tpu.observability import metrics as obs
    from deeplearning4j_tpu.serving.server import ModelServer
    from serve_bench import _serving_mlp

    net = _serving_mlp(args.hidden, args.depth)
    server = ModelServer(net, port=0, max_batch=args.max_batch,
                         compile_cache_dir=args.cache_dir).start()
    try:
        rng = np.random.default_rng(0)
        server.predict(rng.normal(size=(1, 64)).astype(np.float32))
        ttfr = server.stats.first_reply_unix - obs.process_start_unix()
        boot = obs.compile_snapshot()
        # steady state: traffic over every ladder bucket (odd sizes so
        # each pads up) must compile nothing — the warm-up already ran
        # every shape this server will ever execute
        b = 1
        while b <= args.max_batch:
            server.predict(rng.normal(size=(b, 64)).astype(np.float32))
            b *= 2
        steady = obs.compile_delta(boot)
    finally:
        server.stop()
    rep = server.run_report
    print(json.dumps({
        "time_to_first_reply_s": round(ttfr, 3),
        "cold_start_s": rep.cold_start_s,
        "warmup_s": rep.warmup_s,
        "compile_count": rep.compile_count,
        # backend_compile_duration fires on cache HITS too (it times the
        # retrieve-or-compile), so fresh XLA compiles = events - hits
        "fresh_compiles": rep.compile_count - rep.xla_cache_hits,
        "compile_seconds": rep.compile_seconds,
        "cache_hits": rep.xla_cache_hits,
        "cache_misses": rep.xla_cache_misses,
        "steady_state_compiles": steady["count"],
        "aot_manifest_ok": server.aot_manifest_ok,
    }))
    return 0


# ------------------------------------------------------------------ parent
def _run_child(cache_dir: str, args) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--cache-dir", cache_dir, "--hidden", str(args.hidden),
           "--depth", str(args.depth), "--max-batch", str(args.max_batch)]
    env = {**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS",
                                                         "cpu")}
    out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         cwd=_REPO, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"child boot failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def _run_autotune(args) -> dict:
    """serve_bench (trace-capturing) + autotune_serving, both in this
    process; returns the report's receipt fields."""
    from deeplearning4j_tpu.compilecache import autotune as at
    from serve_bench import bench_serving

    results = bench_serving(concurrencies=(16,), requests_per_client=10,
                            max_batch=args.max_batch, batch_window_ms=2.0,
                            hidden=args.hidden, depth=args.depth)
    report = at.autotune(results)
    return {"default": report["default"], "tuned": report["tuned"],
            "objective_ratio": report["objective_ratio"],
            "trace_requests": report["trace"]["requests"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--cache-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--skip-autotune", action="store_true",
                    help="skip the serve_bench replay section")
    ap.add_argument("--out", default=None,
                    help="write the artifact here (check_budgets gates "
                         "it via --bench)")
    args = ap.parse_args(argv)
    if args.child:
        return child_main(args)

    report: dict = {"config": "cold_start",
                    "model": f"serving_mlp 64-{args.hidden}x{args.depth}-10",
                    "max_batch": args.max_batch,
                    "created_unix": round(time.time(), 3)}

    with tempfile.TemporaryDirectory(prefix="dl4j_coldstart_") as tmp:
        cache = os.path.join(tmp, "xla-cache")
        print("== arm: cold (empty cache) ==", file=sys.stderr)
        report["cold"] = _run_child(cache, args)
        print("== arm: warm (same cache, new process) ==", file=sys.stderr)
        report["warm"] = _run_child(cache, args)

        aot_cache = os.path.join(tmp, "xla-cache-aot")
        print("== arm: aot (precompile, then first boot) ==",
              file=sys.stderr)
        pre = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", "precompile.py"),
             "--cache-dir", aot_cache, "--hidden", str(args.hidden),
             "--depth", str(args.depth), "--max-batch", str(args.max_batch)],
            capture_output=True, text=True, timeout=900, cwd=_REPO,
            env={**os.environ,
                 "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")})
        if pre.returncode != 0:
            raise RuntimeError(f"precompile failed:\n{pre.stderr[-2000:]}")
        report["precompile"] = json.loads(pre.stdout)
        report["aot"] = _run_child(aot_cache, args)

    if not args.skip_autotune:
        print("== autotune: serve_bench trace replay ==", file=sys.stderr)
        report["autotune"] = _run_autotune(args)

    cold, warm, aot = report["cold"], report["warm"], report["aot"]
    # gated scalars, top-level so check_budgets' generic resolver sees
    # them (BUDGETS.json "cold_start" section)
    report.update({
        "cold_start_s": cold["time_to_first_reply_s"],
        "warm_cold_start_s": warm["time_to_first_reply_s"],
        "warm_boot_compile_count": warm["fresh_compiles"],
        "warm_compile_seconds_ratio": round(
            warm["compile_seconds"] / cold["compile_seconds"], 4)
        if cold["compile_seconds"] else None,
        "warm_cache_misses": warm["cache_misses"],
        "aot_cache_misses": aot["cache_misses"],
        "aot_manifest_ok": bool(aot.get("aot_manifest_ok")),
        "steady_state_compiles": max(cold["steady_state_compiles"],
                                     warm["steady_state_compiles"],
                                     aot["steady_state_compiles"]),
    })
    if "autotune" in report:
        report["autotuned_objective_ratio"] = \
            report["autotune"]["objective_ratio"]

    print(json.dumps(report, indent=2))
    if args.out:
        tmp_path = args.out + ".tmp"
        with open(tmp_path, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        os.replace(tmp_path, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
