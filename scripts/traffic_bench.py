"""SLO-aware traffic engine benchmark: open-loop flash-crowd load
through the scheduling core, with the autoscaler closing the loop.

The receipt behind BUDGETS.json ``traffic`` (TRAFFIC_r01.json). One
topology, one storyline — a parent-process ``FrontDoorRouter``
(front-door SchedulingCore: tenant quotas + deadline sheds) over REAL
child ``ModelServer`` processes (``--child-host`` mode, the
crosshost_serve_bench pattern), each host running the same scheduling
core against its own queue (class watermarks: batch sheds at 50%,
interactive at 100%):

- **calibrate**: a short closed-loop probe through the router at 1
  host measures the sustainable rows/sec the open-loop phases are
  scaled against (open-loop load is meaningless without the capacity
  it is a multiple of).
- **open-loop flash crowd**: ``scheduling.loadgen.TrafficModel``
  materializes a seeded arrival trace — diurnal base load, then a
  flash crowd offering >= 2x the measured sustainable rate — with
  heavy-tailed row counts, mixed tenants (one tenant quota-capped at
  the front door) and mixed classes carrying their deadline headers.
  ``OpenLoopRunner`` fires every arrival at its appointed offset and
  NEVER waits for completions: when the fleet falls behind, requests
  pile up exactly as at a real front door. The gates: interactive
  p99 stays within its deadline and its SLO attainment beats batch
  (batch sheds first — per-class 503s with X-DL4J-Shed-Class prove
  it), and the capped tenant's flood quota-sheds without starving the
  others.
- **closed-loop autoscaler**: an ``Autoscaler`` watches the router's
  live federation gauges (pushed queue depth / derived retry-after);
  when the flash crowd breaches its thresholds it spawns host 1 as a
  real subprocess WARM off the shared compile-cache dir (gated: 0
  fresh compiles on scale-up) and registers it through the router's
  own ``POST /api/hosts`` verb. ``last_reaction_s`` — first breached
  observation to capacity live — is the gated reaction time.

The receipt also publishes the attainment-vs-offered-load curve
(per-bucket offered rows/sec and per-class attainment) so the shed
order is visible over time, not just in aggregate.

Run: ``python scripts/traffic_bench.py --out TRAFFIC_r01.json`` then
``python scripts/check_budgets.py --bench TRAFFIC_r01.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------- child
def child_main(args) -> int:
    """One serving host in a pristine process: warmed ModelServer
    against the SHARED compile cache (scheduler on by default — class
    watermarks enforce batch-first shedding at this queue), heartbeats
    pushed to the router, simulated device patched in AFTER warm-up so
    the ready line's compile counts measure real XLA work."""
    import numpy as np

    from deeplearning4j_tpu.observability import metrics as obs
    from deeplearning4j_tpu.serving.server import ModelServer
    from serve_bench import _serving_mlp

    net = _serving_mlp(args.hidden, args.depth)
    server = ModelServer(net, port=0, max_batch=args.max_batch,
                         batch_window_ms=1.0, max_queue=args.max_queue,
                         compile_cache_dir=args.cache_dir,
                         push_url=args.push_url or None,
                         push_interval_s=0.5).start()
    snap = obs.compile_snapshot()
    boot = {"ready": True, "port": server.port, "url": server.url,
            "pid": os.getpid(),
            "compile_count": snap["count"],
            "cache_hits": snap["cache_hits"],
            "cache_misses": snap["cache_misses"],
            "fresh_compiles": snap["count"] - snap["cache_hits"]}

    real = server._device_forward

    def simulated(feats, _real=real):
        out = _real(feats)
        np.asarray(out)
        time.sleep(args.device_sim_ms / 1000.0)
        return out

    for rep in server.fleet.replicas:
        rep.batcher._forward = simulated

    print(json.dumps(boot), flush=True)
    try:
        for _ in sys.stdin:
            pass
    except Exception:
        pass
    server.stop()
    return 0


# ------------------------------------------------------------------ parent
def spawn_host(idx: int, cache_dir: str, push_url: str, run_id: str,
               args, timeout_s: float = 900.0) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--child-host",
           "--cache-dir", cache_dir, "--push-url", push_url or "",
           "--hidden", str(args.hidden), "--depth", str(args.depth),
           "--max-batch", str(args.max_batch),
           "--max-queue", str(args.max_queue),
           "--device-sim-ms", str(args.device_sim_ms)]
    env = {**os.environ,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
           "DL4J_TPU_RUN_ID": run_id,
           "DL4J_TPU_INSTANCE": f"host{idx}"}
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=_REPO, env=env)
    deadline = time.monotonic() + timeout_s
    line = proc.stdout.readline()
    while line and not line.startswith("{"):
        line = proc.stdout.readline()
        if time.monotonic() > deadline:
            break
    if not line:
        proc.kill()
        err = proc.stderr.read()
        raise RuntimeError(f"host{idx} died before ready:\n{err[-2000:]}")
    boot = json.loads(line)
    return {"proc": proc, "url": boot["url"], "port": boot["port"],
            "boot": boot}


def stop_host(host: dict) -> None:
    proc = host["proc"]
    if proc.poll() is None:
        try:
            proc.stdin.close()
        except Exception:
            pass
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _post_json(url: str, path: str, obj: dict, headers=None,
               timeout: float = 60.0):
    """POST returning (status, body, reply headers) — 503 and friends
    come back as data, not exceptions (the open-loop runner records
    them as outcomes)."""
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}"), \
                dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


# ------------------------------------------------------------ calibration
def calibrate(router_url: str, bodies: dict, rows: int = 4,
              threads: int = 8, seconds: float = 5.0) -> float:
    """Closed-loop probe: the sustainable rows/sec the open-loop
    phases are multiples of. Closed loop by design — it can never
    overload, so it finds the knee, not the cliff."""
    import urllib.request
    stop_at = time.monotonic() + seconds
    counts = [0] * threads

    def worker(i: int):
        while time.monotonic() < stop_at:
            req = urllib.request.Request(
                router_url + "/predict", data=bodies[rows],
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                    if resp.status == 200:
                        counts[i] += rows
            except Exception:
                pass

    t0 = time.monotonic()
    ts = [threading.Thread(target=worker, args=(i,), daemon=True)
          for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=seconds + 60)
    return sum(counts) / (time.monotonic() - t0)


def _curve(rows, flash_start, duration, bucket_s=10.0):
    """Offered-load vs attainment over time — the published curve."""
    from deeplearning4j_tpu.scheduling.loadgen import attainment
    out = []
    t = 0.0
    while t < duration:
        w = (t, min(t + bucket_s, duration))
        sel = [r for r in rows if w[0] <= r["t"] < w[1]]
        point = {"t0": w[0], "t1": w[1],
                 "offered_req": len(sel),
                 "offered_rows_per_sec": round(
                     sum(r["rows"] for r in sel) / (w[1] - w[0]), 2),
                 "in_flash": w[0] >= flash_start}
        for k in ("interactive", "batch", "best_effort"):
            a = attainment(rows, k, window=w)
            point[f"attainment_{k}"] = a["attainment"]
            point[f"shed_{k}"] = sum(
                1 for r in sel if r["class"] == k and r["status"] == 503)
        out.append(point)
        t += bucket_s
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child-host", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--cache-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--push-url", default="", help=argparse.SUPPRESS)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--depth", type=int, default=2)
    # per-host ceiling = max_batch / device_sim_ms ~= 114 rows/s: small
    # enough that the shared-core client tier can offer 2.3x it, big
    # enough that the queue dynamics are real
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--device-sim-ms", type=float, default=70.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--duration", type=float, default=90.0,
                    help="open-loop trace length (s)")
    ap.add_argument("--flash-start", type=float, default=12.0)
    ap.add_argument("--base-frac", type=float, default=0.45,
                    help="base offered rows/s as a fraction of "
                         "sustainable")
    ap.add_argument("--flash-target", type=float, default=2.3,
                    help="flash offered rows/s over sustainable "
                         "(gate: >= 2.0)")
    ap.add_argument("--interactive-deadline-ms", type=float,
                    default=2500.0)
    ap.add_argument("--batch-deadline-ms", type=float, default=10000.0)
    ap.add_argument("--out", default=None,
                    help="artifact path (check_budgets --bench gates it)")
    args = ap.parse_args(argv)
    if args.child_host:
        return child_main(args)

    import numpy as np

    from deeplearning4j_tpu.compilecache import atomic_publish
    from deeplearning4j_tpu.scheduling import core as sched_core
    from deeplearning4j_tpu.scheduling.autoscaler import (Autoscaler,
                                                          fleet_signals)
    from deeplearning4j_tpu.scheduling.loadgen import (OpenLoopRunner,
                                                       TrafficModel,
                                                       attainment)
    from deeplearning4j_tpu.serving import FrontDoorRouter

    report: dict = {
        "config": "traffic",
        "model": f"serving_mlp 64-{args.hidden}x{args.depth}-10",
        "device_sim_ms": args.device_sim_ms,
        "max_batch": args.max_batch, "max_queue": args.max_queue,
        "seed": args.seed, "duration_s": args.duration,
        "created_unix": round(time.time(), 3),
    }

    # request bodies per row count, built once (the open-loop hot path
    # must not spend its dispatch budget on json)
    rng = np.random.default_rng(args.seed)
    bodies = {r: json.dumps(
        {"features": rng.normal(size=(r, 64)).astype(np.float32).tolist()}
    ).encode() for r in range(1, 9)}

    run_id = f"traffic-{os.getpid()}"
    # the front door: tenant 'scraper' is quota-capped HERE (2 rows/s,
    # burst 8) — its flood must shed without touching a backend
    router = FrontDoorRouter(
        stale_after_s=3.0,
        scheduler=sched_core.SchedulingCore(
            quotas={"scraper": (2.0, 8.0)})).start()
    push_url = router.url + "/api/metrics_push"
    hosts = []
    scaler = None
    try:
        with tempfile.TemporaryDirectory(prefix="dl4j_traffic_") as tmp:
            cache = os.path.join(tmp, "shared-xla-cache")

            print("== host 0: cold boot (populates the shared cache) ==",
                  file=sys.stderr)
            h0 = spawn_host(0, cache, push_url, run_id, args)
            hosts.append(h0)
            router.add_host(h0["url"])
            time.sleep(1.5)   # first heartbeats land

            print("== calibrate: closed-loop sustainable rows/sec ==",
                  file=sys.stderr)
            sustainable = calibrate(router.url, bodies)
            report["sustainable_rows_per_sec"] = round(sustainable, 2)
            print(f"   sustainable ~= {sustainable:.1f} rows/s",
                  file=sys.stderr)

            # ---- the arrival trace: scale request rate so offered
            # rows/s hits the base/flash targets (row counts are
            # heavy-tailed, so measure the trace's own mean)
            flash_dur = args.duration - args.flash_start
            mix = dict(class_mix={"interactive": 0.35, "batch": 0.5,
                                  "best_effort": 0.15},
                       tenants={"acme": 0.5, "globex": 0.35,
                                "scraper": 0.15},
                       deadlines_ms={
                           "interactive": args.interactive_deadline_ms,
                           "batch": args.batch_deadline_ms},
                       pareto_alpha=1.6, max_rows=8,
                       session_fraction=0.2, think_s=2.0)
            probe = TrafficModel(seed=args.seed, duration_s=60.0,
                                 base_rps=20.0, **mix).arrivals()
            mean_rows = sum(a.rows for a in probe) / max(1, len(probe))
            base_rps = args.base_frac * sustainable / mean_rows
            mult = args.flash_target / args.base_frac
            model = TrafficModel(
                seed=args.seed, duration_s=args.duration,
                base_rps=base_rps, diurnal_amplitude=0.25,
                diurnal_period_s=60.0,
                flash_crowds=[(args.flash_start, flash_dur, mult)],
                **mix)
            arrivals = model.arrivals()
            flash_w = (args.flash_start, args.duration)
            flash_rows = sum(a.rows for a in arrivals
                             if flash_w[0] <= a.t < flash_w[1])
            report.update({
                "arrivals_total": len(arrivals),
                "mean_rows_per_request": round(mean_rows, 3),
                "offered_base_rows_per_sec": round(
                    base_rps * mean_rows, 2),
                "offered_flash_rows_per_sec": round(
                    flash_rows / flash_dur, 2),
                "offered_over_sustainable": round(
                    flash_rows / flash_dur / sustainable, 3),
            })
            print(f"   trace: {len(arrivals)} arrivals, flash offers "
                  f"{report['offered_over_sustainable']}x sustainable",
                  file=sys.stderr)

            # ---- the autoscaler: breach -> spawn host 1 warm off the
            # shared cache -> register via POST /api/hosts (the verb)
            def scale_up() -> bool:
                if len(hosts) >= 2:
                    return False
                try:
                    h = spawn_host(len(hosts), cache, push_url, run_id,
                                   args)
                except Exception as e:
                    print(f"   scale-up spawn failed: {e}",
                          file=sys.stderr)
                    return False
                hosts.append(h)
                st, body, _ = _post_json(router.url, "/api/hosts",
                                         {"url": h["url"],
                                          "action": "add"})
                print(f"   scale-up: {h['url']} added "
                      f"(fresh_compiles="
                      f"{h['boot']['fresh_compiles']})", file=sys.stderr)
                return st == 200 and body.get("added")

            scaler = Autoscaler(
                signals_fn=lambda: fleet_signals(router),
                up=scale_up, min_size=1, max_size=2,
                up_queue_depth=args.max_queue * 0.3,
                up_retry_after_s=0.5,
                breach_n=3, up_cooldown_s=120.0, interval_s=0.5)
            scaler.start()

            # ---- the open-loop run
            import urllib.error
            import urllib.request

            def submit(a):
                req = urllib.request.Request(
                    router.url + "/predict", data=bodies[a.rows],
                    headers={"Content-Type": "application/json",
                             **a.headers()})
                try:
                    with urllib.request.urlopen(req, timeout=60) as r:
                        r.read()
                        status, hdrs = r.status, r.headers
                except urllib.error.HTTPError as e:
                    e.read()
                    status, hdrs = e.code, e.headers
                return {"status": status,
                        "shed_class": hdrs.get(
                            sched_core.SHED_CLASS_HEADER)}

            print("== open-loop run (base, then flash crowd) ==",
                  file=sys.stderr)
            runner = OpenLoopRunner(submit, arrivals, max_workers=96)
            rows = runner.run()
            scaler.stop()

            # ---- attainment + receipts
            att = {k: attainment(rows, k, window=flash_w)
                   for k in ("interactive", "batch", "best_effort")}
            report["attainment_flash"] = att
            report["attainment_full"] = {
                k: attainment(rows, k)
                for k in ("interactive", "batch", "best_effort")}
            report["curve"] = _curve(rows, args.flash_start,
                                     args.duration)
            sched_snap = router.scheduler.snapshot()
            auto_snap = scaler.snapshot()
            report["router"] = router.describe()
            report["autoscaler"] = auto_snap
            report["hosts"] = {f"host{i}": h["boot"]
                               for i, h in enumerate(hosts)}
            errors = sum(1 for r in rows if r["error"])
            sheds = sum(1 for r in rows if r["status"] == 503)
            batch_sheds = sum(1 for r in rows
                              if r["status"] == 503
                              and r["shed_class"] == "batch")
            interactive_sheds = sum(1 for r in rows
                                    if r["status"] == 503
                                    and r["shed_class"] == "interactive")
            quota_sheds = sum(
                n for key, n in sched_snap["shed_by_reason"].items()
                if key.endswith("/quota"))
            scraper = [r for r in rows if r["tenant"] == "scraper"]
            others_ok = [r for r in rows if r["tenant"] != "scraper"
                         and r["status"] == 200]
            report.update({
                "connection_errors": errors,
                "sheds_total": sheds,
                "batch_sheds": batch_sheds,
                "interactive_sheds": interactive_sheds,
                "quota_sheds": quota_sheds,
                "scraper_offered": len(scraper),
                "scraper_served": sum(1 for r in scraper
                                      if r["status"] == 200),
                "other_tenants_served": len(others_ok),
                # ---- gated scalars (BUDGETS.json "traffic") ----
                "attainment_interactive":
                    att["interactive"]["attainment"],
                "attainment_batch": att["batch"]["attainment"],
                "attainment_gap": round(
                    (att["interactive"]["attainment"] or 0.0)
                    - (att["batch"]["attainment"] or 0.0), 4),
                "interactive_p99_ms": att["interactive"]["p99_ms"],
                "scale_ups_total": auto_snap["scale_ups_total"],
                "scaleup_reaction_s": auto_snap["last_reaction_s"],
                "scaleup_fresh_compiles": (
                    hosts[1]["boot"]["fresh_compiles"]
                    if len(hosts) > 1 else None),
                "hosts_after": len(hosts),
            })
    finally:
        if scaler is not None:
            scaler.stop()
        for h in hosts:
            try:
                stop_host(h)
            except Exception:
                pass
        router.stop()

    print(json.dumps({k: v for k, v in report.items()
                      if k not in ("curve",)}, indent=1))
    if args.out:
        out = os.path.abspath(args.out)
        atomic_publish(os.path.dirname(out), os.path.basename(out),
                       report)
        print(f"wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
