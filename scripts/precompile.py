"""Build-time AOT precompile: populate a persistent XLA compilation
cache with every executable a deploy will need, plus a schema'd
manifest the server validates at boot.

Runs ``lower().compile()`` / the server's own warm-up seam over:

- the serving bucket ladder (every power-of-two bucket up to
  ``--max-batch``, through the same ``ReplicaSet.warm`` path a live
  boot uses — identical HLO, identical cache keys), and
- the net's jitted train step at ``--train-batch`` (``--train``).

The artifacts land in ``--cache-dir`` (the dir you point
``DL4J_TPU_COMPILE_CACHE`` / ``ModelServer(compile_cache_dir=...)`` at)
next to ``aot_manifest.json`` describing exactly what was compiled —
shapes, dtypes, ladder, mesh axes, model fingerprint. A later boot
whose config drifted from the manifest warns and falls back to lazy
compile instead of silently recompiling everything.

The model here is the serve_bench MLP (same ``--hidden`` / ``--depth``
knobs); real deployments import :mod:`deeplearning4j_tpu.compilecache.
precompile` and call ``precompile_serving`` / ``precompile_fit`` on
their own net.

Run: ``python scripts/precompile.py --cache-dir /var/cache/dl4j-xla``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cache-dir", required=True,
                    help="persistent compilation cache dir to populate")
    ap.add_argument("--max-batch", type=int, default=64,
                    help="serving bucket ladder cap (powers of two up "
                         "to this are compiled)")
    ap.add_argument("--hidden", type=int, default=4096)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--compute-dtype", default=None,
                    help="serving compute dtype override (e.g. bfloat16)")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--train", action="store_true",
                    help="also AOT-compile the train step")
    ap.add_argument("--train-batch", type=int, default=32)
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.compilecache import manifest as man
    from deeplearning4j_tpu.compilecache.precompile import (precompile_fit,
                                                            precompile_serving)
    from deeplearning4j_tpu.observability import metrics as obs
    from serve_bench import _serving_mlp

    net = _serving_mlp(args.hidden, args.depth)
    snap0 = obs.compile_snapshot()
    t0 = time.perf_counter()
    serving = precompile_serving(net, cache_dir=args.cache_dir,
                                 max_batch=args.max_batch,
                                 compute_dtype=args.compute_dtype,
                                 replicas=args.replicas)
    train = []
    if args.train:
        train.append(precompile_fit(net, cache_dir=args.cache_dir,
                                    batch=args.train_batch))
    wall = time.perf_counter() - t0
    manifest = man.build(net, serving=serving, train=train)
    path = man.save(manifest, args.cache_dir)
    delta = obs.compile_delta(snap0)
    print(json.dumps({
        "cache_dir": os.path.abspath(args.cache_dir),
        "manifest": path,
        "precompile_wall_s": round(wall, 3),
        "compiled": delta["count"],
        "compile_seconds": delta["seconds"],
        "cache_hits": delta["cache_hits"],
        "cache_misses": delta["cache_misses"],
        "serving": serving,
        "train": train,
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
